"""Expert parallelism: mixture-of-experts FFN with all_to_all dispatch.

Beyond the reference entirely (its zoo is MLP+CNN, reference
``models/model.py:3-33``); this completes the parallelism-strategy inventory
(dp / sp / tp / pp / ep) the framework exposes. The design is the GShard /
Switch top-1 formulation (Lepikhin et al. 2020; Fedus et al. 2021) expressed
the shard_map way:

- the router (gate) is a replicated ``[D, E]`` projection over ALL experts;
- expert weights are stacked on a leading expert dim — ``wi [E, D, H]``,
  ``wo [E, H, D]`` — and sharded over the ``ep`` mesh axis on that dim, so
  each shard owns ``E / ep_shards`` complete experts;
- each shard routes its LOCAL token block (the per-peer batch is split over
  the ep axis) into per-expert capacity buffers by scatter-add on flat slot
  ids (NOT the GShard ``[n, E, C]`` dispatch one-hot, which is
  memory-quadratic in token count — see :func:`top1_route`),
  ``lax.all_to_all`` moves buffers to the experts' owners, the owners run
  their experts as one stacked einsum (MXU-friendly: ``[E_local, S, D] x
  [E_local, D, H]``), and a reverse ``all_to_all`` brings results home;
- a slot gather scatters expert outputs back to token positions, scaled by
  the gate probability.

Two ``all_to_all``s per MoE layer — the textbook count. Tokens beyond an
expert's capacity are dropped (their FFN output is zero; the residual
carries them), exactly as in Switch; with ``capacity_factor >= num_experts``
no token can ever drop and the ep-sharded layer equals its dense twin
bit-for-bit modulo reduction order (test-asserted in
``tests/test_expert_parallel.py``).

Gradient story (why no explicit collectives appear in the backward): expert
weights are ep-VARYING, so their grads are complete per shard — every remote
token's contribution arrives through the ``all_to_all`` transpose (which is
the reverse ``all_to_all``). The gate and all non-MoE params stay
ep-INVARIANT; the local loss is pre-scaled by ``1 / ep_shards`` so the vma
machinery's implicit psum over the ep axis reconstructs exactly the
global-batch mean gradient (see ``parallel/round.py::make_local_train``).
"""

from __future__ import annotations

import re

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from p2pdl_tpu.parallel.mesh import EP_AXIS


def moe_capacity(tokens: int, num_experts: int, capacity_factor: float) -> int:
    """Per-expert slot count for ``tokens`` routed tokens on one shard."""
    return max(1, int(-(-capacity_factor * tokens // num_experts)))


def top1_route(
    gate_logits: jnp.ndarray, capacity: int
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Switch top-1 routing. ``gate_logits``: [n, E] (float32).

    Returns ``(expert, slot, keep, prob)``, each ``[n]``: the token's
    expert, its 0-based slot in that expert's capacity buffer, whether it
    was admitted (slots fill in token order; tokens past ``capacity`` drop —
    the residual carries them), and its gate probability. The compact form
    deliberately avoids the GShard ``[n, E, C]`` dispatch one-hot: with
    ``C ∝ n`` that tensor is memory-QUADRATIC in token count (a 1024-sample
    ViT eval would need a ~35 GB dispatch tensor); scatter/gather by flat
    slot id is O(n·D + E·C·D). With no drops the layer output is
    slot-order invariant, which is what makes the ep layer equal its dense
    twin even though their cumsum orders differ.
    """
    n, num_experts = gate_logits.shape
    probs = jax.nn.softmax(gate_logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1).astype(jnp.int32)  # [n]
    prob = jnp.max(probs, axis=-1)  # [n]
    onehot = jax.nn.one_hot(expert, num_experts, dtype=jnp.float32)  # [n, E]
    # 1-based arrival rank of each token within its expert.
    pos = jnp.sum(jnp.cumsum(onehot, axis=0) * onehot, axis=-1)  # [n]
    keep = pos <= capacity
    slot = jnp.clip(pos - 1, 0, capacity - 1).astype(jnp.int32)
    return expert, slot, keep, prob


class MoEFFN(nn.Module):
    """Top-1 mixture-of-experts FFN over ``[B, T, D]`` (or ``[n, D]``).

    ``ep_axis = None`` is the dense twin: all ``num_experts`` experts live on
    one shard (identical math, no collectives). With ``ep_axis`` set (inside
    ``shard_map``), this module DECLARES the local expert slice
    (``num_experts // ep_shards``) — flax validates param shapes at apply, so
    the sharded twin must declare what the ``P(ep)`` placement hands it. The
    logical (stored) pytree keeps the full ``[E, ...]`` shapes; see
    :func:`param_specs`.
    """

    num_experts: int
    dim: int
    hidden: int
    capacity_factor: float = 2.0
    ep_axis: str | None = None
    ep_shards: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        if (self.ep_shards != 1) != (self.ep_axis is not None):
            raise ValueError("ep_shards and ep_axis must be set together")
        if self.num_experts % self.ep_shards != 0:
            raise ValueError(
                f"ep_shards ({self.ep_shards}) must divide num_experts "
                f"({self.num_experts})"
            )
        e_local = self.num_experts // self.ep_shards
        shape = x.shape
        tokens = x.reshape(-1, shape[-1])  # [n, D]
        n = tokens.shape[0]

        gate_w = self.param(
            "gate", nn.initializers.lecun_normal(), (self.dim, self.num_experts)
        )
        init = nn.initializers.lecun_normal(batch_axis=(0,))
        wi = self.param("wi", init, (e_local, self.dim, self.hidden))
        bi = self.param("bi", nn.initializers.zeros, (e_local, self.hidden))
        wo = self.param("wo", init, (e_local, self.hidden, self.dim))
        bo = self.param("bo", nn.initializers.zeros, (e_local, self.dim))

        # Route in float32 (softmax/argmax stability under bfloat16 compute).
        logits = (tokens.astype(jnp.float32)) @ (gate_w.astype(jnp.float32))
        capacity = moe_capacity(n, self.num_experts, self.capacity_factor)
        expert, slot, keep, prob = top1_route(logits, capacity)

        # Scatter admitted tokens into per-expert capacity buffers by flat
        # slot id; dropped tokens pile onto a dump row that is never read.
        # Admitted (expert, slot) pairs are unique, so scatter-add has no
        # real collisions (its transpose is the gather below).
        flat = jnp.where(keep, expert * capacity + slot, self.num_experts * capacity)
        buf = jnp.zeros((self.num_experts * capacity + 1, tokens.shape[-1]), x.dtype)
        buf = buf.at[flat].add(tokens)
        expert_in = buf[:-1].reshape(self.num_experts, capacity, -1)
        if self.ep_axis is not None:
            # Send each block of E_local consecutive experts to its owner;
            # receive every shard's buffer for MY experts: [E, C, D] ->
            # [E_local, ep * C, D] (slots from all source shards).
            expert_in = lax.all_to_all(
                expert_in, self.ep_axis, split_axis=0, concat_axis=1, tiled=True
            )
        h = jnp.einsum("esd,edh->esh", expert_in, wi.astype(x.dtype))
        h = nn.gelu(h + bi.astype(x.dtype)[:, None])
        out = jnp.einsum("esh,ehd->esd", h, wo.astype(x.dtype))
        out = out + bo.astype(x.dtype)[:, None]
        if self.ep_axis is not None:
            # Reverse: give every source shard back its slots: [E_local,
            # ep * C, D] -> [E, C, D].
            out = lax.all_to_all(
                out, self.ep_axis, split_axis=1, concat_axis=0, tiled=True
            )
        # Gather each token's slot output, scaled by its gate probability;
        # dropped tokens read the zero dump row.
        out_flat = jnp.concatenate(
            [
                out.reshape(self.num_experts * capacity, -1),
                jnp.zeros((1, out.shape[-1]), out.dtype),
            ]
        )
        y = out_flat[flat] * prob[:, None].astype(x.dtype)
        return y.reshape(shape)


# Leaf-path classification for expert-stacked params, anchored on the
# OWNING MODULE's scope (``.../MoEFFN_k/wi``), not the bare leaf name — a
# future module reusing wi/bi/wo/bo must not silently get its leading dim
# expert-sharded. Root-scope bare names match only under the explicit
# ``root_is_moe`` opt-in below (a MoEFFN initialized directly as the
# top-level module, as the unit tests do).
_EXPERT_LEAF = re.compile(r"(^|/)MoEFFN_\d+/(wi|bi|wo|bo)$")
_EXPERT_LEAF_ROOT = re.compile(r"(^|/)MoEFFN_\d+/(wi|bi|wo|bo)$|^(wi|bi|wo|bo)$")


def param_specs(params, ep_axis: str = EP_AXIS, root_is_moe: bool = False):
    """Per-leaf ``PartitionSpec`` pytree: expert-stacked leaves split their
    leading (expert) dim over the ep axis; everything else replicated
    (shared walk: ``ops.placement.leading_dim_specs``). ``root_is_moe``
    opts top-level bare ``wi/bi/wo/bo`` names into expert sharding — only
    for a tree whose ROOT module is a MoEFFN; the default keeps any other
    module's same-named params replicated instead of silently missharded."""
    from p2pdl_tpu.ops.placement import leading_dim_specs

    pattern = _EXPERT_LEAF_ROOT if root_is_moe else _EXPERT_LEAF
    return leading_dim_specs(params, pattern, ep_axis)


def validate_ep_geometry(num_experts: int, ep_shards: int, batch_size: int) -> None:
    if num_experts % ep_shards != 0:
        raise ValueError(
            f"ep_shards ({ep_shards}) must divide moe_experts ({num_experts})"
        )
    if batch_size % ep_shards != 0:
        raise ValueError(
            f"ep_shards ({ep_shards}) must divide batch_size ({batch_size}) — "
            f"each ep shard trains on its slice of every batch"
        )
