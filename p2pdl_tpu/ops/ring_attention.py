"""Ring attention: exact sequence-parallel attention over a mesh axis.

Long-context support the reference does not have (no attention or sequence
dimension anywhere in reference ``models/model.py``), built the TPU way: the
sequence is sharded over a mesh axis, each device keeps its query block
resident, and key/value blocks rotate around the ring with one
``lax.ppermute`` per step so communication rides ICI and overlaps with the
block matmuls. The online-softmax (running max / normalizer) accumulation
makes the blockwise result exactly equal to dense softmax attention
(Liu et al., "Ring Attention with Blockwise Transformers", 2023; the
numerics are the flash-attention recurrence).

Memory per device is O(T_local^2-free): only the [B, H, Tq_local, Tk_local]
block of logits is live at a time, so sequence length scales linearly with
the number of devices.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool = False,
    impl: str = "dense",
) -> jnp.ndarray:
    """Attention over a sequence sharded on ``axis_name``.

    ``q, k, v``: local blocks ``[B, H, T_local, D]`` inside ``shard_map``;
    the global sequence is the concatenation of blocks in mesh order.
    Returns the local ``[B, H, T_local, D]`` output block, bitwise-equivalent
    (up to float assoc.) to slicing dense attention over the full sequence.

    ``impl``: per-block compute. ``"dense"`` is the inline online-softmax
    recurrence below; ``"flash"`` computes each block with the fused Pallas
    kernel (``pallas_attention.flash_attention_with_lse``) and merges blocks
    exactly via their logsumexp — the long-context path where even one
    ``[T_local, T_local]`` score matrix must not hit HBM.
    """
    if impl == "flash":
        return _ring_flash(q, k, v, axis_name, causal)
    if impl != "dense":
        raise ValueError(f"unknown ring attention impl {impl!r}")
    n_dev = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    t_local = q.shape[2]
    scale = q.shape[-1] ** -0.5
    q32 = q.astype(jnp.float32) * scale

    # Running flash-attention accumulators, tagged as varying over the ring
    # axis AND every axis the operands already vary over (inside the peer
    # round, q is peer-varying too) so the scan carry types match the
    # block-dependent updates.
    def _vary(x):
        axes = frozenset(jax.typeof(q).vma) | {axis_name}
        return lax.pcast(x, tuple(axes), to="varying")

    o = _vary(jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32))
    m = _vary(jnp.full(q.shape[:3], -jnp.inf, jnp.float32))
    l = _vary(jnp.zeros(q.shape[:3], jnp.float32))

    # Pass k/v to the next device each step; after s steps we hold the block
    # originally owned by (my_idx - s) mod n_dev.
    perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]
    q_pos = my_idx * t_local + jnp.arange(t_local)

    def step(carry, s):
        o, m, l, k_cur, v_cur = carry
        logits = jnp.einsum("bhqd,bhkd->bhqk", q32, k_cur.astype(jnp.float32))
        if causal:
            src = (my_idx - s) % n_dev
            k_pos = src * t_local + jnp.arange(t_local)
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask, logits, -jnp.inf)
        block_max = jnp.max(logits, axis=-1)
        new_m = jnp.maximum(m, block_max)
        # exp(-inf - -inf) guard: rows with no unmasked keys yet keep m=-inf.
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        p = jnp.exp(logits - safe_m[..., None])
        if causal:
            p = jnp.where(jnp.isfinite(logits), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32)
        )
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o_new, new_m, l_new, k_nxt, v_nxt), None

    (o, m, l, _, _), _ = lax.scan(
        step, (o, m, l, k, v), jnp.arange(n_dev)
    )
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def _ring_flash(q, k, v, axis_name: str, causal: bool) -> jnp.ndarray:
    """Ring attention with fused per-block kernels.

    Each rotation computes one ``(out_s, lse_s)`` block pair with the flash
    kernel and folds it into running ``(o, lse)`` accumulators:
    ``o' = o*exp(lse - lse') + out_s*exp(lse_s - lse')`` with
    ``lse' = logaddexp(lse, lse_s)`` — exact blockwise softmax composition.
    Under causality the block relation is static per (my_idx, src) pair only
    at runtime, so the three cases (diagonal = causal kernel, past = full
    kernel, future = skip) dispatch through ``lax.switch``.
    """
    from p2pdl_tpu.ops.pallas_attention import flash_attention_with_lse

    n_dev = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]

    def _vary(x):
        axes = frozenset(jax.typeof(q).vma) | {axis_name}
        return lax.pcast(x, tuple(axes), to="varying")

    o0 = _vary(jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32))
    lse0 = _vary(jnp.full(q.shape[:3], -jnp.inf, jnp.float32))

    def block(k_cur, v_cur, s):
        if not causal:
            return flash_attention_with_lse(q, k_cur, v_cur, causal=False)
        src = (my_idx - s) % n_dev

        def diag(args):
            return flash_attention_with_lse(*args, causal=True)

        def past(args):
            return flash_attention_with_lse(*args, causal=False)

        def future(args):
            qq, kk, vv = args
            # Match the kernel branches' vma typing exactly (lax.switch
            # requires equal output types): the zeros must claim the same
            # varying axes as a real block result would.
            vma = tuple(
                frozenset(jax.typeof(qq).vma)
                | frozenset(jax.typeof(kk).vma)
                | frozenset(jax.typeof(vv).vma)
            )
            out = jnp.zeros(qq.shape[:3] + (vv.shape[-1],), qq.dtype)
            lse = jnp.full(qq.shape[:3], -jnp.inf, jnp.float32)
            if vma:
                out = lax.pcast(out, vma, to="varying")
                lse = lax.pcast(lse, vma, to="varying")
            return out, lse

        branch = jnp.where(src == my_idx, 0, jnp.where(src < my_idx, 1, 2))
        return lax.switch(branch, (diag, past, future), (q, k_cur, v_cur))

    def step(carry, s):
        o, lse, k_cur, v_cur = carry
        out_s, lse_s = block(k_cur, v_cur, s)
        lse_new = jnp.logaddexp(lse, lse_s)
        safe = jnp.where(jnp.isfinite(lse_new), lse_new, 0.0)
        w_old = jnp.where(jnp.isfinite(lse), jnp.exp(lse - safe), 0.0)
        w_new = jnp.where(jnp.isfinite(lse_s), jnp.exp(lse_s - safe), 0.0)
        o = o * w_old[..., None] + out_s.astype(jnp.float32) * w_new[..., None]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o, lse_new, k_nxt, v_nxt), None

    (o, lse, _, _), _ = lax.scan(step, (o0, lse0, k, v), jnp.arange(n_dev))
    return o.astype(q.dtype)
