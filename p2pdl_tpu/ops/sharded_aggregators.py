"""Blockwise robust aggregation: Krum/trimmed-mean/median without the full
all-gather.

The gathered reducers (``ops.aggregators`` after ``lax.all_gather``) hold
every trainer's full update on every device — O(num_peers × model) HBM per
device, which contradicts the 1024-peer story on any real model (SURVEY §7
hard part (b)). These variants stream the peer axis through fixed-size
feature blocks instead:

- **Krum / multi-Krum**: pairwise squared distances come from the Gram
  matrix ``G[i,j] = <d_i, d_j>`` over *full concatenated* updates, and the
  Gram matrix is a sum over feature blocks — per block, ``all_gather`` a
  ``[P, B]`` slice and accumulate one ``[P, P]`` MXU matmul. Peak transient
  is O(P × B), never O(P × D). The selected update(s) are then extracted
  with a masked ``psum`` — no stacked copy ever exists.
- **Trimmed mean / median**: coordinate-wise order statistics need all peers
  per coordinate, but coordinates are independent — per block, gather
  ``[P, B]``, reduce over the peer axis to ``[B]``, and write the output
  block. Same O(P × B) transient.

All functions run *inside* ``shard_map`` over the peer mesh axis and take the
local peer-stacked delta block ``[L, ...]`` (L = peers per device); they
return the aggregated pytree (no peer axis), replicated across devices.
Numerically they match the dense reducers up to float summation order
(asserted by ``tests/test_sharded_aggregators.py``).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from p2pdl_tpu.ops import pallas_aggregators
from p2pdl_tpu.parallel.mesh import PEER_AXIS

# Target transient size for one gathered block: P * block * 4 bytes. 2^22
# elements ≈ 16 MB float32 — large enough to amortize collective latency,
# small enough to live comfortably in HBM beside the model at P = 1024.
_TARGET_BLOCK_ELEMS = 1 << 22


def default_block(num_peers: int, flat_dim: int) -> int:
    return max(128, min(flat_dim, _TARGET_BLOCK_ELEMS // max(num_peers, 1)))


def _flatten_local(delta: Any) -> jnp.ndarray:
    """``[L, D]`` float32 concatenation of all leaves (one copy, local)."""
    leaves = jax.tree.leaves(delta)
    l_per_dev = leaves[0].shape[0]
    return jnp.concatenate(
        [x.reshape(l_per_dev, -1).astype(jnp.float32) for x in leaves], axis=1
    )


def _unflatten(vec: jnp.ndarray, delta: Any) -> Any:
    """Inverse of ``_flatten_local`` for a single aggregated vector ``[D]``."""
    leaves, treedef = jax.tree_util.tree_flatten(delta)
    out = []
    off = 0
    for leaf in leaves:
        n = int(np.prod(leaf.shape[1:], dtype=np.int64)) if leaf.ndim > 1 else 1
        out.append(vec[off : off + n].reshape(leaf.shape[1:]).astype(leaf.dtype))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def _chunked(flat: jnp.ndarray, block: int) -> jnp.ndarray:
    """``[n_blocks, L, block]`` zero-padded view for scanning."""
    l_per_dev, d = flat.shape
    d_pad = -(-d // block) * block
    flat = jnp.pad(flat, ((0, 0), (0, d_pad - d)))
    return jnp.moveaxis(flat.reshape(l_per_dev, d_pad // block, block), 1, 0)


def block_gram(
    delta: Any,
    axis_name: str = PEER_AXIS,
    block: int | None = None,
    center_idx: jnp.ndarray | None = None,
    pallas: bool = False,
) -> jnp.ndarray:
    """``[P, P]`` Gram matrix of full flattened updates, streamed blockwise.

    Zero padding is Gram-neutral, so the result equals the dense
    ``flat @ flat.T`` over the concatenated update matrix.

    ``center_idx``: subtract the MEAN over these rows from every gathered
    chunk before accumulating. Distance computations built from Gram
    entries (``|a-b|^2 = G_aa + G_bb - 2 G_ab``) are translation-invariant
    in exact arithmetic but NOT in float32: federated deltas share a large
    common component (the global gradient direction), so raw entries are
    huge while the spreads distance math needs are tiny — catastrophic
    cancellation that turns Krum scores and Weiszfeld weights into noise.
    Centering on the trainer mean makes entries O(spread^2) and restores
    conditioning; callers doing distance math should always pass it.

    ``pallas=True`` (``Config.pallas_aggregators``) routes each gathered
    chunk's center+accumulate through the fused Pallas kernel when trusted
    on this build/backend (``pallas_aggregators.use_fused()``): the
    centered copy of the ``[P, B]`` chunk never materializes in HBM.
    Per-chunk centering equals whole-matrix centering (column means are
    per-column), so the accumulated Gram matches this path within
    :data:`~p2pdl_tpu.ops.aggregators.PATH_TOLERANCE_ATOL`.
    """
    flat = _flatten_local(delta)
    num_peers = flat.shape[0] * lax.axis_size(axis_name)
    if block is None:
        block = default_block(num_peers, flat.shape[1])
    use_kernel = (
        pallas
        and num_peers <= pallas_aggregators.MAX_FUSED_T
        and pallas_aggregators.use_fused()
    )
    center_mask = None
    if use_kernel and center_idx is not None:
        center_mask = jnp.zeros((num_peers,), jnp.float32).at[center_idx].set(1.0)

    def step(gram, chunk):
        g = lax.all_gather(chunk, axis_name, axis=0, tiled=True)  # [P, B]
        if use_kernel:
            if center_idx is None:
                return gram + pallas_aggregators.fused_gram(g), None
            return gram + pallas_aggregators.fused_centered_gram(g, center_mask), None
        if center_idx is not None:
            g = g - jnp.mean(g[center_idx], axis=0, keepdims=True)
        return gram + g @ g.T, None

    gram0 = lax.pcast(
        jnp.zeros((num_peers, num_peers), jnp.float32), axis_name, to="varying"
    )
    gram, _ = lax.scan(step, gram0, _chunked(flat, block))
    # Identical on every device but vma-typed varying (all_gather output);
    # materialize it replicated — [P, P] is tiny next to the updates.
    dev = lax.axis_index(axis_name)
    return lax.psum(jnp.where(dev == 0, gram, jnp.zeros_like(gram)), axis_name)


def _d2_from_gram(gram: jnp.ndarray, trainer_idx: jnp.ndarray) -> jnp.ndarray:
    """``[T, T]`` pairwise squared distances over the trainer subset from
    the (centered) Gram matrix — |a-b|^2 = |a|^2 + |b|^2 - 2<a,b>. ONE copy
    of this conditioning-sensitive identity, shared by every Gram-space
    consumer (Krum scores, Bulyan selection)."""
    sub = gram[trainer_idx][:, trainer_idx].astype(jnp.float32)
    sq = jnp.diagonal(sub)
    return jnp.maximum(sq[:, None] + sq[None, :] - 2.0 * sub, 0.0)


def _scores_from_gram(gram: jnp.ndarray, trainer_idx: jnp.ndarray, f: int) -> jnp.ndarray:
    """Krum scores over the trainer subset: sum of each update's T-f-2
    smallest squared distances to the others (``aggregators.krum_scores``
    semantics, distances from the Gram identity |a-b|^2 = |a|^2+|b|^2-2ab)."""
    t = trainer_idx.shape[0]
    if t < 2 * f + 3:
        raise ValueError(f"krum requires T >= 2f+3 ({2 * f + 3}), got T={t}")
    d2 = _d2_from_gram(gram, trainer_idx)
    d2 = d2 + jnp.diag(jnp.full((t,), jnp.inf, d2.dtype))
    return jnp.sum(jnp.sort(d2, axis=1)[:, : t - f - 2], axis=1)


def _extract_weighted(
    delta: Any, peer_weights: jnp.ndarray, axis_name: str
) -> Any:
    """Weighted sum over ALL peers via masked ``psum`` — the collective that
    replaces materializing any stacked copy. ``peer_weights``: ``[P]``.

    Accumulates in FLOAT32 and quantizes to the leaf dtype exactly once at
    the end — the same discipline as the gathered reducers' final
    ``.astype`` (see ``aggregators.PATH_TOLERANCE_ATOL``). Weighting in the
    leaf dtype instead (the old behavior) rounds every product AND every
    psum partial to e.g. bfloat16, which diverges from the gathered paths
    by the leaf ulp at the update's magnitude — catastrophic under the
    correlated-deltas regime where a large common offset inflates that ulp
    past the honest spread (regression-tested in
    tests/test_sharded_aggregators.py)."""
    leaves = jax.tree.leaves(delta)
    l_per_dev = leaves[0].shape[0]
    dev = lax.axis_index(axis_name)
    local_w = peer_weights[dev * l_per_dev + jnp.arange(l_per_dev)].astype(
        jnp.float32
    )

    def leaf(d):
        w = local_w.reshape((l_per_dev,) + (1,) * (d.ndim - 1))
        acc = lax.psum(jnp.sum(d.astype(jnp.float32) * w, axis=0), axis_name)
        return acc.astype(d.dtype)

    return jax.tree.map(leaf, delta)


def krum_sharded(
    delta: Any,
    trainer_idx: jnp.ndarray,
    f: int,
    axis_name: str = PEER_AXIS,
    block: int | None = None,
    pallas: bool = False,
) -> Any:
    """Krum's single most-central trainer update, O(P × block) transient."""
    num_peers = jax.tree.leaves(delta)[0].shape[0] * lax.axis_size(axis_name)
    gram = block_gram(delta, axis_name, block, center_idx=trainer_idx, pallas=pallas)
    scores = _scores_from_gram(gram, trainer_idx, f)
    winner = trainer_idx[jnp.argmin(scores)]
    weights = (jnp.arange(num_peers) == winner).astype(jnp.float32)
    return _extract_weighted(delta, weights, axis_name)


def multi_krum_sharded(
    delta: Any,
    trainer_idx: jnp.ndarray,
    f: int,
    m: int = 0,
    axis_name: str = PEER_AXIS,
    block: int | None = None,
    pallas: bool = False,
) -> Any:
    """Mean of the m lowest-scored trainer updates (``aggregators.multi_krum``
    semantics), extracted by one weighted masked ``psum``."""
    num_peers = jax.tree.leaves(delta)[0].shape[0] * lax.axis_size(axis_name)
    t = trainer_idx.shape[0]
    if m <= 0:
        m = max(t - f - 2, 1)
    m = min(m, t)
    gram = block_gram(delta, axis_name, block, center_idx=trainer_idx, pallas=pallas)
    scores = _scores_from_gram(gram, trainer_idx, f)
    chosen = trainer_idx[jnp.argsort(scores)[:m]]
    weights = jnp.isin(jnp.arange(num_peers), chosen).astype(jnp.float32) / m
    return _extract_weighted(delta, weights, axis_name)


def _coordinate_reduce_sharded(
    delta: Any,
    trainer_idx: jnp.ndarray,
    reduce_fn: Callable[[jnp.ndarray], jnp.ndarray],
    axis_name: str,
    block: int | None,
) -> Any:
    """Coordinate-wise reducer over the trainer axis, streamed blockwise.
    ``reduce_fn``: ``[T, B] -> [B]``."""
    flat = _flatten_local(delta)
    d = flat.shape[1]
    num_peers = flat.shape[0] * lax.axis_size(axis_name)
    if block is None:
        block = default_block(num_peers, d)

    def step(_, chunk):
        g = lax.all_gather(chunk, axis_name, axis=0, tiled=True)  # [P, B]
        return None, reduce_fn(g[trainer_idx])

    _, blocks = lax.scan(step, None, _chunked(flat, block))
    vec = blocks.reshape(-1)[:d]
    # The value is identical on every device but vma-typed varying (it came
    # through all_gather + data-dependent math); materialize it replicated.
    dev = lax.axis_index(axis_name)
    vec = lax.psum(jnp.where(dev == 0, vec, jnp.zeros_like(vec)), axis_name)
    return _unflatten(vec, delta)


def trimmed_mean_sharded(
    delta: Any,
    trainer_idx: jnp.ndarray,
    beta: float,
    axis_name: str = PEER_AXIS,
    block: int | None = None,
) -> Any:
    """Coordinate-wise beta-trimmed mean (``aggregators.trimmed_mean``
    semantics) with O(P × block) transient."""
    t = trainer_idx.shape[0]
    k = int(beta * t)
    if 2 * k >= t:
        raise ValueError(f"beta={beta} trims everything for T={t}")

    def reduce_fn(g):
        s = jnp.sort(g, axis=0)
        return jnp.mean(s[k : t - k] if k > 0 else s, axis=0)

    return _coordinate_reduce_sharded(delta, trainer_idx, reduce_fn, axis_name, block)


def median_sharded(
    delta: Any,
    trainer_idx: jnp.ndarray,
    axis_name: str = PEER_AXIS,
    block: int | None = None,
) -> Any:
    """Coordinate-wise median (``jnp.median`` semantics: midpoint average
    for even T) with O(P × block) transient."""
    t = trainer_idx.shape[0]

    def reduce_fn(g):
        s = jnp.sort(g, axis=0)
        return 0.5 * (s[(t - 1) // 2] + s[t // 2])

    return _coordinate_reduce_sharded(delta, trainer_idx, reduce_fn, axis_name, block)


def bulyan_sharded(
    delta: Any,
    trainer_idx: jnp.ndarray,
    f: int,
    axis_name: str = PEER_AXIS,
    block: int | None = None,
    pallas: bool = False,
) -> Any:
    """Bulyan with O(P × block) transient: the iterative Krum selection
    runs on the centered-Gram distance matrix (``[T, T]`` host of the same
    ``_bulyan_select`` loop as the gathered path), and the per-coordinate
    closest-to-median aggregation (``closest_to_median_mean``, the paper's
    Alg. 3 second stage) streams through the feature blocks like
    trimmed-mean — the selection mask rides into ``reduce_fn``."""
    from p2pdl_tpu.ops.aggregators import _bulyan_select, closest_to_median_mean
    from p2pdl_tpu.utils import jax_compat

    if jax_compat.active():
        # On shimmed builds XLA:CPU's backend aborts (no diagnostic, straight
        # SIGABRT in backend_compile) on this program's HLO. Every other
        # sharded reducer compiles fine there; fail loudly instead of
        # taking down the process.
        raise NotImplementedError(
            "bulyan_sharded crashes the XLA:CPU compiler on JAX builds old "
            "enough to need the p2pdl jax_compat shims; use the gathered "
            "bulyan path or a newer JAX"
        )

    t = trainer_idx.shape[0]
    if t < 4 * f + 3:
        raise ValueError(f"bulyan requires T >= 4f+3 ({4 * f + 3}), got T={t}")
    theta = t - 2 * f
    beta = theta - 2 * f
    gram = block_gram(delta, axis_name, block, center_idx=trainer_idx, pallas=pallas)
    sel = _bulyan_select(_d2_from_gram(gram, trainer_idx), f, theta)  # [T] 0/1

    def reduce_fn(g):  # [T, B] this feature block's trainer values
        masked = jnp.where(sel[:, None] > 0, g.astype(jnp.float32), jnp.inf)
        srt = jnp.sort(masked, axis=0)[:theta]
        return closest_to_median_mean(srt, beta)

    return _coordinate_reduce_sharded(delta, trainer_idx, reduce_fn, axis_name, block)


def _dists_from_gram(sub: jnp.ndarray, c: jnp.ndarray) -> jnp.ndarray:
    """``[T]`` distances ``||x_i - v||`` for ``v = sum_j c_j x_j`` (with
    ``sum c = 1``) from the centered Gram matrix:
    ``||x_i - v||^2 = G_ii - 2 (G c)_i + c^T G c``. Shared by every
    Gram-space iterative reducer (geometric median, centered clipping) so
    a conditioning or clamping change lands in all of them at once."""
    gc = sub @ c
    return jnp.sqrt(jnp.maximum(jnp.diagonal(sub) - 2.0 * gc + c @ gc, 0.0))


def centered_clip_sharded(
    delta: Any,
    trainer_idx: jnp.ndarray,
    tau: float = 0.0,
    iters: int | None = None,
    axis_name: str = PEER_AXIS,
    block: int | None = None,
    pallas: bool = False,
) -> Any:
    """Centered clipping with O(P × block) transient — the whole iteration
    runs in GRAM SPACE, like :func:`geometric_median_sharded`.

    The iterate ``v <- v + mean_i clip(x_i - v, tau)`` is an affine
    combination of the inputs whose coefficients sum to 1:
    ``c' = (1 - mean_i s_i) c + s / T`` with ``s_i = min(1, tau/||x_i - v||)``.
    Distances come from the centered Gram matrix
    (``||x_i - v||^2 = G_ii - 2 (G c)_i + c^T G c``; centering is exact
    here because translation cancels inside ``x_i - v`` when the
    coefficients sum to 1), the iteration updates only the ``[T]``
    coefficient vector, and the result is extracted by one weighted masked
    ``psum``. Matches ``aggregators.centered_clip`` on the gathered stack
    (test-asserted to float tolerance)."""
    from p2pdl_tpu.ops.aggregators import CCLIP_ITERS

    if not iters:  # None or the 0 sentinel (Config.cclip_iters default)
        iters = CCLIP_ITERS
    num_peers = jax.tree.leaves(delta)[0].shape[0] * lax.axis_size(axis_name)
    gram = block_gram(delta, axis_name, block, center_idx=trainer_idx, pallas=pallas)
    sub = gram[trainer_idx][:, trainer_idx].astype(jnp.float32)  # [T, T]
    t = sub.shape[0]
    c0 = jnp.full((t,), 1.0 / t, jnp.float32)

    def step(_, c):
        d = _dists_from_gram(sub, c)
        # Auto-tau re-estimated per iteration, exactly like the gathered
        # path (see aggregators.centered_clip: a one-shot radius at the
        # attack-dragged mean would be the attack scale, not the honest
        # spread).
        tau_eff = jnp.where(tau > 0, jnp.float32(tau), jnp.median(d))
        s = jnp.minimum(1.0, tau_eff / jnp.maximum(d, 1e-12))
        return (1.0 - jnp.mean(s)) * c + s / t

    c = lax.fori_loop(0, iters, step, c0)
    weights = jnp.zeros((num_peers,), jnp.float32).at[trainer_idx].add(c)
    return _extract_weighted(delta, weights, axis_name)


def geometric_median_sharded(
    delta: Any,
    trainer_idx: jnp.ndarray,
    iters: int | None = None,
    axis_name: str = PEER_AXIS,
    block: int | None = None,
    pallas: bool = False,
) -> Any:
    """Geometric median (RFA / smoothed Weiszfeld) with O(P × block)
    transient — the whole iteration runs in GRAM SPACE.

    The Weiszfeld iterate is always a convex combination of the inputs,
    ``z = sum_j c_j x_j``, so every distance it needs reduces to Gram
    entries: ``||x_i - z||^2 = G_ii - 2 (G c)_i + c^T G c``. One blockwise
    ``block_gram`` pass builds ``G`` (never materializing stacked full
    vectors), the iteration updates only the ``[T]`` coefficient vector,
    and the final median is extracted by a single weighted masked ``psum``.
    Algebraically identical to ``aggregators.geometric_median`` on the
    gathered stack (test-asserted to float tolerance)."""
    from p2pdl_tpu.ops.aggregators import _GEOMEDIAN_SMOOTH, GEOMEDIAN_ITERS

    if iters is None:
        iters = GEOMEDIAN_ITERS
    num_peers = jax.tree.leaves(delta)[0].shape[0] * lax.axis_size(axis_name)
    # Centered Gram: the geometric median is translation-equivariant and
    # the coefficients sum to 1, so Weiszfeld over (x_i - mean) yields the
    # SAME final point — while the centered entries are O(spread^2),
    # avoiding the float32 cancellation that would otherwise flatten the
    # weights toward uniform whenever updates share a large common
    # component (the realistic correlated-deltas regime).
    gram = block_gram(delta, axis_name, block, center_idx=trainer_idx, pallas=pallas)
    sub = gram[trainer_idx][:, trainer_idx].astype(jnp.float32)  # [T, T]
    t = sub.shape[0]

    def step(_, c):
        w = 1.0 / jnp.maximum(_dists_from_gram(sub, c), _GEOMEDIAN_SMOOTH)
        return w / jnp.sum(w)

    c = lax.fori_loop(0, iters, step, jnp.full((t,), 1.0 / t, jnp.float32))
    weights = jnp.zeros((num_peers,), jnp.float32).at[trainer_idx].add(c)
    return _extract_weighted(delta, weights, axis_name)
