"""Fused pairwise-distance / Gram-accumulate Pallas TPU kernel for the
robust aggregators.

The ``[T, T]`` pairwise squared-distance matrix behind Krum, Bulyan and the
Gram-space iterative reducers is the dominant non-matmul cost at high peer
counts: the XLA paths (``aggregators.pairwise_sq_dists``,
``sharded_aggregators.block_gram``) materialize a centered copy of every
``[T, block]`` update chunk in HBM, run a generic dot, and then assemble
``sq[:, None] + sq[None, :] - 2*gram`` as separate HLOs — three HBM
round-trips of ``[T, T]``-shaped traffic per leaf/block. This kernel fuses
the whole identity: update chunks stream through VMEM feature block by
feature block, the center-subtract happens in registers, the Gram
accumulator lives in the (revisited) output block in VMEM across the
sequential grid, and the distance assembly (including the diagonal
extraction — after centering ``sq_i = G_ii``) runs on the final grid step
before the single ``[T, T]`` result leaves the chip.

Centering semantics match the XLA paths exactly: the mean over the center
rows (``center_mask``; all rows by default) is subtracted from EVERY row —
the float32 conditioning fix both reference paths rely on (entries at
O(spread^2), not O(offset^2)). Zero feature padding is both center- and
Gram-neutral, and padded T rows only contaminate padded Gram entries (a
row's centered value never depends on other rows beyond the shared mean),
so the unpadded ``[T, T]`` slice is exact.

Routing follows ``ops.pallas_attention``: Mosaic-compiled on TPU, the XLA
reference path elsewhere (the generic Pallas interpreter breaks under
``shard_map`` vma typing in current JAX, and the reducers run inside
``shard_map``). On JAX builds old enough to need the ``jax_compat`` shims
the kernels are not trusted at all (``available()`` is False) and every
caller falls back to the XLA path — same capability-detection stance as
``sharded_aggregators.bulyan_sharded``. Kernel *math* is CPU-tested by
passing ``interpret=True`` explicitly on plain arrays
(tests/test_sharded_aggregators.py compares it against the dense Gram
oracle across dtypes, peer counts, and center-mask clamps).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # capability probe, not a hard dependency (old builds lack pieces)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_IMPORTED = True
except Exception:  # pragma: no cover - import-time environment probe
    pl = None
    pltpu = None
    _PALLAS_IMPORTED = False

# Old-build spellings resolved lazily (NOT via jax_compat.install(), which
# is opt-in and process-wide): TPUCompilerParams was renamed
# CompilerParams, and pre-vma ShapeDtypeStruct rejects the vma kwarg.
# Interpret mode works on those builds with these two bridges, which is
# what keeps the CPU equivalence tests running there instead of
# collection-erroring like the modern-API-only flash kernels.
_COMPILER_PARAMS = (
    getattr(pltpu, "CompilerParams", None) or getattr(pltpu, "TPUCompilerParams", None)
    if _PALLAS_IMPORTED
    else None
)


def _sds(shape, dtype, vma):
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # pre-vma build: no replication typing to satisfy
        return jax.ShapeDtypeStruct(shape, dtype)

# The Gram accumulator is the [T_pad, T_pad] float32 output block held in
# VMEM across the sequential feature grid: 1024^2 f32 = 4 MB, comfortable
# beside two streamed operand blocks in ~16 MB of VMEM. Past this the
# accumulator alone would crowd out the pipeline — callers fall back to
# the blockwise XLA path, which has no such cap.
MAX_FUSED_T = 1024

# Feature-block width streamed through VMEM per grid step. Lane-aligned
# (multiple of 128); 512 keeps the [T_pad, block_d] operand block at
# 2 MB even at the T cap.
_DEFAULT_BLOCK_D = 512

_SUBLANES = 8  # float32 sublane tile: pad T to a multiple of this

# Test hook: when True, use_fused() reports True off-TPU and every kernel
# launch runs in the Pallas interpreter, so CPU tier-1 can exercise the
# flag-gated REDUCER paths (krum(pallas=True), the Gram-space
# centered-clip), not just the raw kernels. Only valid OUTSIDE shard_map
# (the generic interpreter breaks under vma typing there) — tests
# monkeypatch it around gathered-path calls.
_FORCE_INTERPRET = False


def available() -> bool:
    """Kernel path trusted on this JAX build: pallas imports and the
    process is NOT running on the ``jax_compat`` shims (the shimmed builds
    predate the vma/CompilerParams machinery the kernels are written
    against — same gate as ``bulyan_sharded``)."""
    from p2pdl_tpu.utils import jax_compat

    return _PALLAS_IMPORTED and not jax_compat.active()


def use_fused() -> bool:
    """True when flag-gated callers should take the kernel path: build
    capability plus an actual TPU device (off-TPU the XLA path IS the
    fallback — see module docstring for why interpret mode cannot serve
    inside ``shard_map``)."""
    return available() and (_on_tpu() or _FORCE_INTERPRET)


def _on_tpu() -> bool:
    """Device-keyed TPU detection (same rationale as
    ``pallas_attention._on_tpu``: TPU PJRT plugins can register under a
    different platform name, e.g. this image's tunnel's "axon")."""
    dev = jax.devices()[0]
    return "tpu" in dev.platform.lower() or "tpu" in dev.device_kind.lower()


def _vma(x) -> frozenset:
    """Varying-manual-axes of ``x`` — pallas_call output avals must carry
    the operands' vma when the kernel runs inside ``shard_map``."""
    try:
        return frozenset(jax.typeof(x).vma)
    except Exception:  # non-traced input or backend without vma support
        return frozenset()


def _gram_kernel(x_ref, cmask_ref, out_ref, *, center, assemble, t_pad):
    """Grid ``(n_feature_blocks,)``, sequential. Refs: x ``[t_pad,
    block_d]`` f32; cmask ``[1, t_pad]`` f32 (1.0 on center rows); out
    ``[t_pad, t_pad]`` f32 — the Gram accumulator itself (the block is
    revisited every step, so it persists in VMEM like scratch but needs no
    separate copy-out).

    Per step: fused center-subtract (one ``[1, t_pad] @ [t_pad, block_d]``
    MXU row for the mean) + Gram accumulate. Final step optionally
    rewrites the accumulated Gram into clamped squared distances in place
    (``assemble``) — the diagonal comes off an iota mask, no host trip."""
    j = pl.program_id(0)
    nj = pl.num_programs(0)

    @pl.when(j == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    xb = x_ref[...]  # [t_pad, block_d] float32
    if center:
        cmask = cmask_ref[...]  # [1, t_pad]
        n_center = jnp.maximum(jnp.sum(cmask), 1.0)
        mean = (
            jax.lax.dot_general(
                cmask, xb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            / n_center
        )  # [1, block_d]
        xb = xb - mean
    out_ref[...] += jax.lax.dot_general(
        xb, xb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )

    if assemble:

        @pl.when(j == nj - 1)
        def _():
            g = out_ref[...]
            eq = jax.lax.broadcasted_iota(
                jnp.int32, (t_pad, t_pad), 0
            ) == jax.lax.broadcasted_iota(jnp.int32, (t_pad, t_pad), 1)
            diag = jnp.sum(jnp.where(eq, g, 0.0), axis=1)  # [t_pad]
            d2 = diag[:, None] + diag[None, :] - 2.0 * g
            out_ref[...] = jnp.maximum(d2, 0.0)


def _fused_call(x, center_mask, *, center, assemble, block_d, interpret):
    """Shared pallas_call wrapper: pad, launch, slice. ``x``: [T, D]
    (cast to float32); returns [T, T] float32."""
    t, d = x.shape
    if t > MAX_FUSED_T:
        raise ValueError(
            f"fused aggregator kernel caps T at {MAX_FUSED_T} (the [T, T] "
            f"VMEM accumulator), got T={t}; use the blockwise XLA path"
        )
    x = x.astype(jnp.float32)
    block_d = int(block_d or _DEFAULT_BLOCK_D)
    t_pad = -(-t // _SUBLANES) * _SUBLANES
    block_d = min(block_d, -(-d // 128) * 128)
    d_pad = -(-d // block_d) * block_d
    xp = jnp.pad(x, ((0, t_pad - t), (0, d_pad - d)))
    if center_mask is None:
        cm = jnp.ones((1, t), jnp.float32)
    else:
        cm = center_mask.astype(jnp.float32).reshape(1, t)
    # Zero-extend the mask over padded rows so they never enter the mean.
    cm = jnp.pad(cm, ((0, 0), (0, t_pad - t)))
    # Mask must share x's vma inside shard_map (a replicated mask against
    # a varying operand is a pallas typing error there).
    cm = cm + jnp.zeros_like(xp[:1, :1])

    kernel = functools.partial(
        _gram_kernel, center=center, assemble=assemble, t_pad=t_pad
    )
    out = pl.pallas_call(
        kernel,
        grid=(d_pad // block_d,),
        in_specs=[
            pl.BlockSpec((t_pad, block_d), lambda j: (0, j)),
            pl.BlockSpec((1, t_pad), lambda j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t_pad, t_pad), lambda j: (0, 0)),
        out_shape=_sds((t_pad, t_pad), jnp.float32, _vma(x)),
        compiler_params=_COMPILER_PARAMS(dimension_semantics=("arbitrary",)),
        interpret=bool(interpret or _FORCE_INTERPRET),
    )(xp, cm)
    return out[:t, :t]


def fused_centered_gram(
    x: jnp.ndarray,
    center_mask: jnp.ndarray | None = None,
    *,
    block_d: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """``[T, T]`` float32 Gram matrix of the (mean-centered) rows of ``x``
    ``[T, D]`` in one fused kernel. ``center_mask`` (``[T]``, nonzero =
    center row) selects the rows whose mean is subtracted from every row;
    ``None`` centers on all rows. Drop-in for ``block_gram``'s per-chunk
    center+accumulate (the blockwise path calls this per gathered chunk).

    Callers gate on :func:`use_fused`; ``interpret=True`` runs the same
    kernel in the Pallas interpreter for CPU equivalence tests."""
    return _fused_call(
        x, center_mask, center=True, assemble=False,
        block_d=block_d, interpret=interpret,
    )


def fused_gram(
    x: jnp.ndarray, *, block_d: int | None = None, interpret: bool = False
) -> jnp.ndarray:
    """Uncentered ``[T, T]`` Gram matrix (``block_gram`` with
    ``center_idx=None`` semantics) in one fused kernel."""
    return _fused_call(
        x, None, center=False, assemble=False, block_d=block_d,
        interpret=interpret,
    )


def fused_pairwise_sq_dists(
    x: jnp.ndarray,
    center_mask: jnp.ndarray | None = None,
    *,
    block_d: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """``[T, T]`` clamped squared L2 distances between the rows of ``x``
    ``[T, D]``, fully fused: center-subtract, Gram accumulate over feature
    blocks, and the ``sq[:, None] + sq[None, :] - 2*gram`` assembly all
    happen in VMEM — the distance matrix is the only ``[T, T]`` array that
    ever touches HBM. Matches ``aggregators.pairwise_sq_dists``'s per-leaf
    term at :data:`~p2pdl_tpu.ops.aggregators.PATH_TOLERANCE_ATOL` (float
    summation order differs; see the tolerance contract there)."""
    return _fused_call(
        x, center_mask, center=True, assemble=True,
        block_d=block_d, interpret=interpret,
    )
