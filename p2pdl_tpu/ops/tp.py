"""Tensor parallelism for the transformer family (megatron-style).

Beyond the reference entirely (its zoo is MLP+CNN); this is the classic
column/row-parallel decomposition (Shoeybi et al., 2019) expressed the
shard_map way: the PARAMETER PYTREE IS UNCHANGED — leaves keep their full
logical shapes and are placed with per-leaf ``PartitionSpec``s over the
``tp`` mesh axis, so ``shard_map`` hands each shard its weight slice:

- attention qkv projection: column-parallel ``P(None, tp)`` — each shard
  owns ``heads / tp_shards`` complete heads (attention is independent per
  head, zero communication inside the ring of heads);
- attention output projection: row-parallel ``P(tp, None)`` + one ``psum``;
- MLP fc1: column-parallel (kernel ``P(None, tp)``, bias ``P(tp)``);
- MLP fc2: row-parallel + one ``psum``; its replicated bias is pre-scaled
  by ``1 / tp_shards`` before apply so the psum reconstructs it exactly;
- everything else (patch stem, layer norms, embeddings, head): replicated.

Two psums per transformer block — the textbook count. The vma typing makes
gradients come out right with no further collectives: the psums type the
activations invariant over ``tp``, so replicated layers compute in the
invariant region (their grads are complete per shard, no double count),
while sliced layers' grads flow through the psum transpose to exactly their
own slice.
"""

from __future__ import annotations

import re
from typing import Any

import jax
from jax.sharding import PartitionSpec as P

from p2pdl_tpu.ops.placement import path_str as _path_str
from p2pdl_tpu.parallel.mesh import TP_AXIS

# Leaf-path classification for the ViT tree (flax auto-naming:
# MultiHeadAttention_0/Dense_0 = qkv, Dense_1 = out projection;
# TransformerBlock_*/Dense_0 = fc1, Dense_1 = fc2).
_COL_KERNEL = re.compile(
    r"(MultiHeadAttention_\d+/Dense_0|TransformerBlock_\d+/Dense_0)/kernel$"
)
_COL_BIAS = re.compile(r"TransformerBlock_\d+/Dense_0/bias$")
_ROW_KERNEL = re.compile(
    r"(MultiHeadAttention_\d+/Dense_1|TransformerBlock_\d+/Dense_1)/kernel$"
)
_ROW_BIAS = re.compile(r"TransformerBlock_\d+/Dense_1/bias$")


def param_specs(params: Any, tp_axis: str = TP_AXIS) -> Any:
    """Per-leaf ``PartitionSpec`` pytree for a transformer param tree:
    column-parallel kernels split their OUTPUT dim, row-parallel kernels
    their INPUT dim, fc1 biases their only dim; everything else replicated.
    Works for any peer-axis prefix too (specs index from the trailing dims
    via full-rank specs built per leaf)."""

    def spec(path, leaf):
        p = _path_str(path)
        nd = leaf.ndim
        if _COL_KERNEL.search(p):
            return P(*([None] * (nd - 1) + [tp_axis]))
        if _COL_BIAS.search(p):
            return P(*([None] * (nd - 1) + [tp_axis]))
        if _ROW_KERNEL.search(p):
            return P(*([None] * (nd - 2) + [tp_axis, None]))
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


def scale_row_parallel_biases(params: Any, factor: float) -> Any:
    """Pre-scale row-parallel (fc2) biases by ``factor`` (= 1 / tp_shards):
    each shard's Dense adds the full replicated bias before the psum, so
    without this the aggregate would carry ``tp_shards x bias``."""

    def maybe_scale(path, leaf):
        if _ROW_BIAS.search(_path_str(path)):
            return leaf * factor
        return leaf

    return jax.tree_util.tree_map_with_path(maybe_scale, params)


def validate_tp_geometry(heads: int, dim: int, mlp_hidden: int, tp_shards: int) -> None:
    if heads % tp_shards != 0:
        raise ValueError(
            f"tp_shards ({tp_shards}) must divide the attention head count "
            f"({heads}) — heads are the unit of attention parallelism"
        )
    if dim % tp_shards != 0 or mlp_hidden % tp_shards != 0:
        raise ValueError(
            f"tp_shards ({tp_shards}) must divide dim ({dim}) and the MLP "
            f"hidden width ({mlp_hidden})"
        )
