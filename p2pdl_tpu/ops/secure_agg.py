"""On-device secure-aggregation masking.

The reference transmits model updates in plaintext pickle, protected only by
ECDSA signatures (reference ``utils/broadcast.py:8-37``); masking/secrecy is
absent. This implements the pairwise-mask construction of practical secure
aggregation (Bonawitz et al., CCS 2017) TPU-natively: each pair of trainers
``(i, j)`` derives a shared mask from a pairwise PRF key, trainer ``i`` adds
``sign(j - i) * mask_ij`` for every other trainer ``j``, and antisymmetry
makes all masks cancel exactly in the summed aggregate — the server (and any
eavesdropper on a single link) sees only masked updates.

Scope (documented limitation vs. the full protocol): pairwise keys come from
a shared experiment key rather than a Diffie-Hellman exchange, and there is
no dropout-recovery secret-sharing — cancellation assumes the round's trainer
set completes, which the round driver guarantees in simulation.

Scaling: the full Bonawitz graph costs O(T x model) PRNG *per trainer* —
O(T^2 x model) per round, which is infeasible at T = 1024 on any hardware
(~10^13 random draws per round for ViT-Tiny). ``neighbors = k`` switches to
the k-regular ring graph of Bell et al. (CCS 2020): each trainer exchanges
masks with its k ring neighbors in the sorted trainer list, masks still
cancel exactly (position-symmetric pairs), and per-round cost drops to
O(T x k x model) with privacy degrading gracefully (an update is hidden
unless all k of its neighbors collude with the server).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax


def pairwise_mask(
    base_key: jax.Array,
    my_id: jax.Array,
    trainer_ids: jnp.ndarray,
    tree: Any,
    neighbors: int = 0,
) -> Any:
    """The net mask trainer ``my_id`` adds: ``sum_j sign(j - i) * PRF(i, j)``
    over its mask partners.

    ``trainer_ids``: ``[T]`` global peer ids of this round's trainers.
    ``neighbors = 0`` pairs with every other trainer (Bonawitz full graph);
    ``neighbors = k`` pairs with the k ring neighbors at offsets
    ``+/-1..k//2`` in the trainer vector (Bell-style k-regular graph). The
    PRF key for a pair is order-independent (``fold_in(min) -> fold_in(max)``)
    so both endpoints derive the same mask; ``sign`` is antisymmetric and
    zero for ``j == i`` (self-pair contributes nothing). Returns a pytree
    shaped like ``tree``.
    """
    t = trainer_ids.shape[0]
    if neighbors and neighbors < t - 1:
        # Ring pairing over the LIVE trainers only, by rank among live
        # entries (symmetric: offset +d from rank p lands on rank q iff
        # offset -d from q lands on p), so both endpoints of every pair
        # include it — cancellation holds. Ranking over live entries (not
        # raw positions) matters: with -1 vacancy gating in place, a trainer
        # whose positional neighbors were all gated out would otherwise get
        # a ZERO mask and enter the "secure" aggregate in plaintext.
        live = trainer_ids >= 0  # [T]
        t_idx = jnp.arange(t)
        my_pos = jnp.argmax(trainer_ids == my_id)
        my_rank = jnp.sum(live & (t_idx < my_pos))
        n_live = jnp.maximum(jnp.sum(live), 1)
        # Live ids first, in positional order (vacancies pushed to the end).
        order = jnp.argsort(jnp.where(live, t_idx, t + t_idx))
        live_first = trainer_ids[order]
        half = neighbors // 2
        offsets = jnp.concatenate(
            [jnp.arange(1, half + 1), -jnp.arange(1, half + 1)]
        )
        partners = live_first[(my_rank + offsets) % n_live]
        # When n_live <= neighbors the ring wraps onto my_id itself —
        # sign(0) = 0 keeps self-pairs inert; duplicated pairs stay
        # symmetric at both endpoints and still cancel.
    else:
        partners = trainer_ids
    leaves, treedef = jax.tree.flatten(tree)

    def mask_for_leaf(leaf_idx: int, leaf: jnp.ndarray) -> jnp.ndarray:
        def body(acc, other):
            lo = jnp.minimum(my_id, other)
            hi = jnp.maximum(my_id, other)
            k = jax.random.fold_in(
                jax.random.fold_in(jax.random.fold_in(base_key, lo), hi), leaf_idx
            )
            m = jax.random.normal(k, leaf.shape, jnp.float32)
            sgn = jnp.sign(other - my_id).astype(jnp.float32)
            # Vacant slots (id -1, dynamic-participation padding) must not
            # contribute: a mask keyed on a phantom pair has no counterparty
            # to cancel against in the aggregate.
            sgn = jnp.where(other >= 0, sgn, 0.0)
            return acc + sgn * m, None

        # Derive the accumulator from the leaf (not a fresh zeros) so its
        # varying-manual-axes type matches inside shard_map scans.
        acc0 = (leaf * 0).astype(jnp.float32)
        out, _ = lax.scan(body, acc0, partners)
        return out.astype(leaf.dtype)

    masks = [mask_for_leaf(i, l) for i, l in enumerate(leaves)]
    return jax.tree.unflatten(treedef, masks)


def apply_masks(
    deltas: Any,
    base_key: jax.Array,
    my_id: jax.Array,
    trainer_ids: jnp.ndarray,
    is_trainer: jax.Array,
    neighbors: int = 0,
) -> Any:
    """Add this peer's net pairwise mask to its delta (no-op for non-trainers)."""
    mask = pairwise_mask(base_key, my_id, trainer_ids, deltas, neighbors=neighbors)
    gate = is_trainer.astype(jnp.float32)

    def leaf(d, m):
        return d + (gate * m.astype(jnp.float32)).astype(d.dtype)

    return jax.tree.map(leaf, deltas, mask)
