"""On-device secure-aggregation masking.

The reference transmits model updates in plaintext pickle, protected only by
ECDSA signatures (reference ``utils/broadcast.py:8-37``); masking/secrecy is
absent. This implements the pairwise-mask construction of practical secure
aggregation (Bonawitz et al., CCS 2017) TPU-natively: each pair of trainers
``(i, j)`` derives a shared mask from a pairwise PRF key, trainer ``i`` adds
``sign(j - i) * mask_ij`` for every other trainer ``j``, and antisymmetry
makes all masks cancel exactly in the summed aggregate — the server (and any
eavesdropper on a single link) sees only masked updates.

Key derivation (``pair_seeds`` path, the default via the round driver):
pairwise PRF seeds come from ECDH over per-peer P-256 keys + HKDF
(``protocol/secure_keys.py``) — underivable from public state — baked into
the compiled round as a ``[P, P, 2]`` uint32 matrix. Each peer's private
scalar is Shamir-shared (``protocol/shamir.py``), so when a trainer drops
AFTER masking (BRB gate-out mid-round), survivors reconstruct its seeds and
:func:`residual_mask_sum` cancels the orphaned masks out of the aggregate.
The legacy shared-experiment-key derivation (``base_key`` + ``fold_in``)
remains for A/B benchmarking only.

Scaling: the full Bonawitz graph costs O(T x model) PRNG *per trainer* —
O(T^2 x model) per round, which is infeasible at T = 1024 on any hardware
(~10^13 random draws per round for ViT-Tiny). ``neighbors = k`` switches to
the k-regular ring graph of Bell et al. (CCS 2020): each trainer exchanges
masks with its k ring neighbors in the sorted trainer list, masks still
cancel exactly (position-symmetric pairs), and per-round cost drops to
O(T x k x model) with privacy degrading gracefully (an update is hidden
unless all k of its neighbors collude with the server).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _partner_ids(trainer_ids: jnp.ndarray, my_id: jax.Array, neighbors: int) -> jnp.ndarray:
    """The mask partners of ``my_id`` given this round's trainer vector.

    Shared by masking and residual correction — the two MUST agree on the
    pairing or orphan cancellation breaks. ``neighbors = 0`` (or >= T-1)
    pairs with every trainer slot (Bonawitz full graph; self/vacant slots
    are inert via ``sign``); ``neighbors = k`` pairs with the k ring
    neighbors by RANK AMONG LIVE entries (Bell-style k-regular graph).
    """
    t = trainer_ids.shape[0]
    if not (neighbors and neighbors < t - 1):
        return trainer_ids
    # Ring pairing over the LIVE trainers only, by rank among live
    # entries (symmetric: offset +d from rank p lands on rank q iff
    # offset -d from q lands on p), so both endpoints of every pair
    # include it — cancellation holds. Ranking over live entries (not
    # raw positions) matters: with -1 vacancy gating in place, a trainer
    # whose positional neighbors were all gated out would otherwise get
    # a ZERO mask and enter the "secure" aggregate in plaintext.
    live = trainer_ids >= 0  # [T]
    t_idx = jnp.arange(t)
    my_pos = jnp.argmax(trainer_ids == my_id)
    my_rank = jnp.sum(live & (t_idx < my_pos))
    n_live = jnp.maximum(jnp.sum(live), 1)
    # Live ids first, in positional order (vacancies pushed to the end).
    order = jnp.argsort(jnp.where(live, t_idx, t + t_idx))
    live_first = trainer_ids[order]
    half = neighbors // 2
    offsets = jnp.concatenate([jnp.arange(1, half + 1), -jnp.arange(1, half + 1)])
    # When n_live <= neighbors the ring wraps onto my_id itself —
    # sign(0) = 0 keeps self-pairs inert; duplicated pairs stay
    # symmetric at both endpoints and still cancel.
    return live_first[(my_rank + offsets) % n_live]


def _pair_prf_key(
    base_key: jax.Array | None,
    pair_seeds: jnp.ndarray | None,
    round_idx: jax.Array | None,
    my_id: jax.Array,
    other: jax.Array,
    leaf_idx: int,
) -> jax.Array:
    """The PRF key for pair ``(my_id, other)`` at one leaf.

    ``pair_seeds`` given: key from the ECDH-derived ``[P, P, 2]`` seed
    matrix (both uint32 halves folded in) + round index — reconstructible
    for a dropped peer from its Shamir-shared scalar, underivable from
    public state. Otherwise: legacy order-independent fold chain on the
    shared ``base_key`` (already round-folded by the driver).
    """
    if pair_seeds is not None:
        # Clamp vacant ids for the gather only; callers zero the
        # contribution via sign() gating.
        s = pair_seeds[jnp.maximum(my_id, 0), jnp.maximum(other, 0)]  # [2] uint32
        k = jax.random.fold_in(jax.random.PRNGKey(s[0]), s[1])
        if round_idx is not None:
            k = jax.random.fold_in(k, round_idx)
        return jax.random.fold_in(k, leaf_idx)
    lo = jnp.minimum(my_id, other)
    hi = jnp.maximum(my_id, other)
    return jax.random.fold_in(
        jax.random.fold_in(jax.random.fold_in(base_key, lo), hi), leaf_idx
    )


def pairwise_mask(
    base_key: jax.Array | None,
    my_id: jax.Array,
    trainer_ids: jnp.ndarray,
    tree: Any,
    neighbors: int = 0,
    pair_seeds: jnp.ndarray | None = None,
    round_idx: jax.Array | None = None,
) -> Any:
    """The net mask trainer ``my_id`` adds: ``sum_j sign(j - i) * PRF(i, j)``
    over its mask partners (see :func:`_partner_ids` for the pairing and
    :func:`_pair_prf_key` for the two key-derivation modes).

    ``trainer_ids``: ``[T]`` global peer ids of this round's trainers.
    ``sign`` is antisymmetric and zero for ``j == i`` (self-pair contributes
    nothing). Returns a pytree shaped like ``tree``.
    """
    partners = _partner_ids(trainer_ids, my_id, neighbors)
    leaves, treedef = jax.tree.flatten(tree)

    def mask_for_leaf(leaf_idx: int, leaf: jnp.ndarray) -> jnp.ndarray:
        def body(acc, other):
            k = _pair_prf_key(base_key, pair_seeds, round_idx, my_id, other, leaf_idx)
            m = jax.random.normal(k, leaf.shape, jnp.float32)
            sgn = jnp.sign(other - my_id).astype(jnp.float32)
            # Vacant slots (id -1, dynamic-participation padding) must not
            # contribute: a mask keyed on a phantom pair has no counterparty
            # to cancel against in the aggregate.
            sgn = jnp.where(other >= 0, sgn, 0.0)
            return acc + sgn * m, None

        # Derive the accumulator from the leaf (not a fresh zeros) so its
        # varying-manual-axes type matches inside shard_map scans.
        acc0 = (leaf * 0).astype(jnp.float32)
        out, _ = lax.scan(body, acc0, partners)
        return out.astype(leaf.dtype)

    masks = [mask_for_leaf(i, l) for i, l in enumerate(leaves)]
    return jax.tree.unflatten(treedef, masks)


def apply_masks(
    deltas: Any,
    base_key: jax.Array | None,
    my_id: jax.Array,
    trainer_ids: jnp.ndarray,
    is_trainer: jax.Array,
    neighbors: int = 0,
    pair_seeds: jnp.ndarray | None = None,
    round_idx: jax.Array | None = None,
) -> Any:
    """Add this peer's net pairwise mask to its delta (no-op for non-trainers)."""
    mask = pairwise_mask(
        base_key, my_id, trainer_ids, deltas,
        neighbors=neighbors, pair_seeds=pair_seeds, round_idx=round_idx,
    )
    gate = is_trainer.astype(jnp.float32)

    def leaf(d, m):
        return d + (gate * m.astype(jnp.float32)).astype(d.dtype)

    return jax.tree.map(leaf, deltas, mask)


def residual_mask_sum(
    tree: Any,
    masked_ids: jnp.ndarray,
    gated_ids: jnp.ndarray,
    neighbors: int = 0,
    base_key: jax.Array | None = None,
    pair_seeds: jnp.ndarray | None = None,
    round_idx: jax.Array | None = None,
) -> Any:
    """The orphaned-mask residue left in a gated sum, for subtraction.

    Trainers mask against the PRE-gate trainer vector ``masked_ids`` (what
    they knew when they shipped); the aggregate then admits only
    ``gated_ids`` (BRB survivors). Masks between two survivors cancel; a
    pair (survivor s, dropped d) leaves ``sign(d - s) * mask_sd`` orphaned
    inside s's admitted delta. This returns

        ``sum over s in gated, d in partners(s) \\ gated of
          sign(d - s) * PRF_mask(s, d)``

    — computable by the aggregator only with the dropped peers' pair seeds,
    i.e. after Shamir dropout recovery
    (``protocol/secure_keys.SecureAggKeyring.reconstruct_seeds_for_dropped``);
    in the SPMD engine the reconstructed-equal seed matrix is already baked
    into the program. Partner derivation reuses :func:`_partner_ids` on
    ``masked_ids`` so the pairing matches masking exactly. Cost matches one
    peer's masking pass: O(T x partners x model) PRF draws, replicated.
    """
    leaves, treedef = jax.tree.flatten(tree)
    t = masked_ids.shape[0]

    def resid_for_leaf(leaf_idx: int, leaf: jnp.ndarray) -> jnp.ndarray:
        def outer(acc, s):
            survived = (s >= 0) & jnp.isin(s, gated_ids)
            partners = _partner_ids(masked_ids, s, neighbors)

            def inner(acc2, d):
                orphan = (d >= 0) & ~jnp.isin(d, gated_ids)
                k = _pair_prf_key(base_key, pair_seeds, round_idx, s, d, leaf_idx)
                m = jax.random.normal(k, leaf.shape, jnp.float32)
                sgn = jnp.sign(d - s).astype(jnp.float32)
                w = jnp.where(survived & orphan, sgn, 0.0)
                return acc2 + w * m, None

            acc, _ = lax.scan(inner, acc, partners)
            return acc, None

        acc0 = (leaf * 0).astype(jnp.float32)
        out, _ = lax.scan(outer, acc0, masked_ids)
        return out.astype(leaf.dtype)

    resid = [resid_for_leaf(i, l) for i, l in enumerate(leaves)]
    return jax.tree.unflatten(treedef, resid)


def patch_seed_rows(seed_mat, rows: dict) -> Any:
    """Host-side: patch Shamir-recovered seed rows into a ``[P, P, 2]``
    pairwise-seed matrix.

    ``rows`` maps a dropped peer id to its reconstructed ``[P, 2]`` seed row
    (``SecureAggKeyring.reconstruct_seeds_for_dropped``). Pairwise seeds are
    symmetric (``seed[i, j] == seed[j, i]``), so each recovered row is
    written into both the row and the mirrored column; the diagonal stays
    zero. Returns a copy — the caller's live matrix is never mutated by a
    recovery probe.
    """
    patched = np.array(seed_mat, copy=True)
    for peer, row in rows.items():
        row = np.asarray(row, dtype=patched.dtype)
        patched[peer, :, :] = row
        patched[:, peer, :] = row
        patched[peer, peer, :] = 0
    return patched
