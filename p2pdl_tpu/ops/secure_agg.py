"""On-device secure-aggregation masking.

The reference transmits model updates in plaintext pickle, protected only by
ECDSA signatures (reference ``utils/broadcast.py:8-37``); masking/secrecy is
absent. This implements the pairwise-mask construction of practical secure
aggregation (Bonawitz et al., CCS 2017) TPU-natively: each pair of trainers
``(i, j)`` derives a shared mask from a pairwise PRF key, trainer ``i`` adds
``sign(j - i) * mask_ij`` for every other trainer ``j``, and antisymmetry
makes all masks cancel exactly in the summed aggregate — the server (and any
eavesdropper on a single link) sees only masked updates.

Scope (documented limitation vs. the full protocol): pairwise keys come from
a shared experiment key rather than a Diffie-Hellman exchange, and there is
no dropout-recovery secret-sharing — cancellation assumes the round's trainer
set completes, which the round driver guarantees in simulation.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax


def pairwise_mask(
    base_key: jax.Array,
    my_id: jax.Array,
    trainer_ids: jnp.ndarray,
    tree: Any,
) -> Any:
    """The net mask trainer ``my_id`` adds: ``sum_j sign(j - i) * PRF(i, j)``.

    ``trainer_ids``: ``[T]`` global peer ids of this round's trainers. The
    PRF key for a pair is order-independent (``fold_in(min) -> fold_in(max)``)
    so both endpoints derive the same mask; ``sign`` is antisymmetric and
    zero for ``j == i`` (self-pair contributes nothing). Returns a pytree
    shaped like ``tree``.
    """
    leaves, treedef = jax.tree.flatten(tree)

    def mask_for_leaf(leaf_idx: int, leaf: jnp.ndarray) -> jnp.ndarray:
        def body(acc, other):
            lo = jnp.minimum(my_id, other)
            hi = jnp.maximum(my_id, other)
            k = jax.random.fold_in(
                jax.random.fold_in(jax.random.fold_in(base_key, lo), hi), leaf_idx
            )
            m = jax.random.normal(k, leaf.shape, jnp.float32)
            sgn = jnp.sign(other - my_id).astype(jnp.float32)
            # Vacant slots (id -1, dynamic-participation padding) must not
            # contribute: a mask keyed on a phantom pair has no counterparty
            # to cancel against in the aggregate.
            sgn = jnp.where(other >= 0, sgn, 0.0)
            return acc + sgn * m, None

        # Derive the accumulator from the leaf (not a fresh zeros) so its
        # varying-manual-axes type matches inside shard_map scans.
        acc0 = (leaf * 0).astype(jnp.float32)
        out, _ = lax.scan(body, acc0, trainer_ids)
        return out.astype(leaf.dtype)

    masks = [mask_for_leaf(i, l) for i, l in enumerate(leaves)]
    return jax.tree.unflatten(treedef, masks)


def apply_masks(
    deltas: Any,
    base_key: jax.Array,
    my_id: jax.Array,
    trainer_ids: jnp.ndarray,
    is_trainer: jax.Array,
) -> Any:
    """Add this peer's net pairwise mask to its delta (no-op for non-trainers)."""
    mask = pairwise_mask(base_key, my_id, trainer_ids, deltas)
    gate = is_trainer.astype(jnp.float32)

    def leaf(d, m):
        return d + (gate * m.astype(jnp.float32)).astype(d.dtype)

    return jax.tree.map(leaf, deltas, mask)
