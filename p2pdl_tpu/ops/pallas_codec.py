"""Fused int8 quantize(+pack) Pallas TPU kernel for the compressed-delta
wire format.

The XLA encode path (``delta_codec.encode_jax``) lowers the row-wise
symmetric quantization as separate HLOs: an ``[T, D]`` abs, a full-row max
reduction, and an elementwise scale-multiply/round/clip — the big ``[T, D]``
leaf matrices make three HBM round-trips before the int8 bytes exist. This
kernel fuses the whole quantizer: feature blocks stream through VMEM once,
the per-row absmax accumulates in the revisited scales output block (the
same in-VMEM-accumulator trick as ``pallas_aggregators._gram_kernel``), and
a second grid phase rewrites the accumulator into ``absmax/127`` scales and
emits the int8 blocks — each element of ``x`` is read from HBM exactly
twice (once per phase) and the only other traffic is the int8 result at a
quarter of the input bytes.

Numerics are pinned to the reference encoder bit for bit: all math in
float32, ``scale = absmax/127`` with a zero guard, ``rint`` (half-to-even)
then clip to ±127 — tests compare interpret-mode output against
``delta_codec.encode_np`` bytewise.

Routing matches ``pallas_aggregators``: Mosaic-compiled on TPU, the XLA
encoder elsewhere; on ``jax_compat``-shimmed builds the kernel is not
trusted at all and ``use_fused()`` is False. ``_FORCE_INTERPRET`` lets CPU
tier-1 exercise the flag-gated pack path end-to-end in the interpreter.
The pack step runs OUTSIDE ``shard_map`` (on the gathered ``[T, ...]``
trainer rows, same as ``build_digest_pack_fn``), so interpret mode is safe
here in a way it is not for the in-shard reducers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # capability probe, not a hard dependency (old builds lack pieces)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_IMPORTED = True
except Exception:  # pragma: no cover - import-time environment probe
    pl = None
    pltpu = None
    _PALLAS_IMPORTED = False

_COMPILER_PARAMS = (
    getattr(pltpu, "CompilerParams", None) or getattr(pltpu, "TPUCompilerParams", None)
    if _PALLAS_IMPORTED
    else None
)


def _sds(shape, dtype, vma):
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # pre-vma build: no replication typing to satisfy
        return jax.ShapeDtypeStruct(shape, dtype)


# int8 sublane tile is (32, 128): pad T to a multiple of 32 so the q output
# tiles cleanly (f32 only needs 8; 32 covers both outputs).
_SUBLANES = 32

# Feature-block width streamed through VMEM per grid step (lane-aligned).
_DEFAULT_BLOCK_D = 512

# Same off-TPU test hook as pallas_aggregators._FORCE_INTERPRET: makes
# use_fused() report True and every launch run in the interpreter, so CPU
# tier-1 can pin the flag-gated compressed-pack path, not just the kernel.
_FORCE_INTERPRET = False


def available() -> bool:
    """Kernel path trusted on this JAX build (pallas imports and no
    ``jax_compat`` shims — same capability gate as ``pallas_aggregators``)."""
    from p2pdl_tpu.utils import jax_compat

    return _PALLAS_IMPORTED and not jax_compat.active()


def use_fused() -> bool:
    """True when the flag-gated pack path should take the kernel."""
    return available() and (_on_tpu() or _FORCE_INTERPRET)


def _on_tpu() -> bool:
    dev = jax.devices()[0]
    return "tpu" in dev.platform.lower() or "tpu" in dev.device_kind.lower()


def _vma(x) -> frozenset:
    try:
        return frozenset(jax.typeof(x).vma)
    except Exception:  # non-traced input or backend without vma support
        return frozenset()


def _quantize_kernel(x_ref, q_ref, s_ref, *, nj):
    """Grid ``(2, n_feature_blocks)``, sequential row-major. Refs: x
    ``[t_pad, block_d]`` f32 (block j); q ``[t_pad, block_d]`` int8 (block
    j); s ``[t_pad, 128]`` f32 — block (0, 0) on every step, so it persists
    in VMEM as the absmax accumulator through phase 0 and holds the
    broadcast scales after phase 1's first step.

    Phase 0 (p=0, j sweeps): fold block j's per-row absmax into s via a
    lane-shaped partial max (``[t_pad, block_d] -> [t_pad, 128]``).
    Phase 1 (p=1, j sweeps): on j=0 collapse s across lanes into the final
    per-row scale (``absmax/127``, broadcast back over the 128 lanes);
    every j then quantizes its block against s. The q block at (p=0, j) is
    never written — its phase-1 visit overwrites the whole block."""
    p = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when((p == 0) & (j == 0))
    def _():
        s_ref[...] = jnp.zeros_like(s_ref)

    @pl.when(p == 0)
    def _():
        xb = jnp.abs(x_ref[...])  # [t_pad, block_d] f32
        t_pad, block_d = xb.shape
        part = jnp.max(xb.reshape(t_pad, block_d // 128, 128), axis=1)
        s_ref[...] = jnp.maximum(s_ref[...], part)

    @pl.when((p == 1) & (j == 0))
    def _():
        absmax = jnp.max(s_ref[...], axis=1, keepdims=True)  # [t_pad, 1]
        s_ref[...] = jnp.broadcast_to(absmax / 127.0, s_ref.shape)

    @pl.when(p == 1)
    def _():
        scale = s_ref[...][:, :1]  # [t_pad, 1], identical across lanes
        inv = jnp.where(scale > 0, jnp.float32(1.0) / scale, jnp.float32(0.0))
        q = jnp.clip(jnp.rint(x_ref[...] * inv), -127.0, 127.0)
        q_ref[...] = q.astype(jnp.int8)

    del nj


def fused_quantize_int8(
    x: jnp.ndarray, *, block_d: int | None = None, interpret: bool = False
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Row-wise symmetric int8 quantization of ``x`` ``[T, D]`` in one fused
    kernel: returns ``(q int8 [T, D], scale f32 [T])`` with ``scale =
    absmax/127`` — bitwise the reference ``delta_codec.quantize_jax``.

    Callers gate on :func:`use_fused`; ``interpret=True`` runs the same
    kernel in the Pallas interpreter for the CPU equivalence tests."""
    t, d = x.shape
    x = x.astype(jnp.float32)
    block_d = int(block_d or _DEFAULT_BLOCK_D)
    t_pad = -(-t // _SUBLANES) * _SUBLANES
    block_d = min(block_d, -(-d // 128) * 128)
    d_pad = -(-d // block_d) * block_d
    xp = jnp.pad(x, ((0, t_pad - t), (0, d_pad - d)))
    nj = d_pad // block_d

    kernel = functools.partial(_quantize_kernel, nj=nj)
    q, s = pl.pallas_call(
        kernel,
        grid=(2, nj),
        in_specs=[pl.BlockSpec((t_pad, block_d), lambda p, j: (0, j))],
        out_specs=[
            pl.BlockSpec((t_pad, block_d), lambda p, j: (0, j)),
            pl.BlockSpec((t_pad, 128), lambda p, j: (0, 0)),
        ],
        out_shape=[
            _sds((t_pad, d_pad), jnp.int8, _vma(x)),
            _sds((t_pad, 128), jnp.float32, _vma(x)),
        ],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("arbitrary", "arbitrary")
        ),
        interpret=bool(interpret or _FORCE_INTERPRET),
    )(xp)
    return q[:t, :d], s[:t, 0]


def fused_encode_int8(
    x: jnp.ndarray, *, block_d: int | None = None, interpret: bool = False
) -> jnp.ndarray:
    """int8-mode wire segment ``[T, 4 + D]`` uint8 for ``x`` ``[T, D]``:
    fused quantize, then the same bitcast packing as the XLA encoder (the
    byte shuffle is pure layout — XLA handles it; the FLOP- and
    traffic-heavy quantize is what the kernel owns). Bytewise equal to
    ``delta_codec.encode_np(x, "int8")``."""
    from jax import lax

    q, scale = fused_quantize_int8(x, block_d=block_d, interpret=interpret)
    sb = lax.bitcast_convert_type(scale[:, None], jnp.uint8).reshape(x.shape[0], 4)
    qb = lax.bitcast_convert_type(q, jnp.uint8)
    return jnp.concatenate([sb, qb], axis=1)
