"""Pipeline parallelism: transformer depth sharded over a ``pp`` mesh axis.

Beyond the reference entirely (its zoo is MLP+CNN, reference
``models/model.py:3-33``); together with ``ops/tp.py`` (tensor), ``ops/moe.py``
(expert) and ``ops/ring_attention.py`` (sequence) this completes the
dp/sp/tp/pp/ep parallelism inventory. The schedule is the circular GPipe
formulation (Huang et al. 2019) expressed the shard_map way:

- the transformer blocks are created as ONE ``nn.scan`` stack — every param
  leaf leads with a depth dim — and that leading dim is sharded ``P(pp)``,
  so each shard owns ``depth / pp_shards`` consecutive blocks;
- each peer's batch splits into M microbatches; at step ``t`` stage 0 feeds
  microbatch ``t`` into the ring while every stage applies its local blocks
  to whatever activation it holds and passes the result to the next stage
  with one ``lax.ppermute``;
- after ``M + S - 1`` steps the last stage has emitted every microbatch's
  final activation; a masked ``psum`` replicates them to all shards (the
  logits head runs replicated).

The step loop is an ``nn.scan`` with ``variable_broadcast="params"`` (the
stack's params are created once and reused every step), so gradients flow
through the whole schedule — including the ``ppermute``s, whose transpose is
the reverse rotation — with stage params' grads complete per shard (they are
pp-VARYING; everything outside the stack stays pp-invariant).

The pipeline bubble is explicit and standard: every stage computes on all
``M + S - 1`` steps, so utilization is ``M / (M + S - 1)``; warmup/drain
outputs never reach a capture slot and their cotangents are zero.

The DENSE TWIN is the same module with ``pp_axis=None`` (S = 1): identical
param paths and shapes, the schedule degenerates to scanning microbatches
through the full stack — which is what makes pipeline-vs-dense exactness
testable leaf-for-leaf (``tests/test_pipeline_parallel.py``).
"""

from __future__ import annotations

import re

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

from p2pdl_tpu.parallel.mesh import PP_AXIS

# The module name the stacked blocks live under — param_specs keys on it.
STACK_NAME = "pp_blocks"


class _BlockStep(nn.Module):
    """``nn.scan`` body over DEPTH: carry = activations, one block per slot.

    ``block_kwargs`` is a tuple of (key, value) pairs — flax module
    attributes participate in hashing, so a plain dict is not an option."""

    make_block: type
    block_kwargs: tuple

    @nn.compact
    def __call__(self, x, _):
        return self.make_block(**dict(self.block_kwargs))(x), None


class _ScheduleStep(nn.Module):
    """``nn.scan`` body over PIPELINE STEPS (params broadcast across steps).

    Carry ``(recv, outputs, micro)``: ``recv`` is the activation handed to
    this stage by the previous one, ``outputs [M, mb, T, D]`` the capture
    buffer, ``micro [M, mb, T, D]`` the (invariant) microbatch inputs.
    """

    make_block: type
    block_kwargs: tuple
    local_depth: int
    pp_axis: str | None

    @nn.compact
    def __call__(self, carry, t):
        recv, outputs, micro = carry
        m = micro.shape[0]
        Stack = nn.scan(
            _BlockStep,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            length=self.local_depth,
        )
        stack = Stack(self.make_block, self.block_kwargs, name=STACK_NAME)

        if self.pp_axis is None:
            out, _ = stack(micro[jnp.minimum(t, m - 1)], None)
            outputs = _capture(outputs, out, t, step_of_last_stage=t)
            return (recv, outputs, micro), None

        stage = lax.axis_index(self.pp_axis)
        n_stages = lax.axis_size(self.pp_axis)
        inp = jnp.where(stage == 0, micro[jnp.minimum(t, m - 1)], recv)
        out, _ = stack(inp, None)
        outputs = jnp.where(
            stage == n_stages - 1,
            _capture(outputs, out, t, step_of_last_stage=t - (n_stages - 1)),
            outputs,
        )
        recv = lax.ppermute(
            out,
            self.pp_axis,
            [(i, (i + 1) % n_stages) for i in range(n_stages)],
        )
        return (recv, outputs, micro), None


def _capture(outputs, out, t, step_of_last_stage):
    """Write ``out`` into microbatch slot ``step_of_last_stage`` when that
    slot is valid (>= 0); warmup steps write nothing."""
    m = outputs.shape[0]
    idx = jnp.clip(step_of_last_stage, 0, m - 1)
    written = lax.dynamic_update_index_in_dim(outputs, out, idx, axis=0)
    return jnp.where(step_of_last_stage >= 0, written, outputs)


class PipelinedBlocks(nn.Module):
    """A depth-``local_depth * pp_shards`` transformer trunk over [B, T, D].

    With ``pp_axis`` set (inside ``shard_map``), this module DECLARES the
    local block slice (``depth // pp_shards`` stacked blocks) — the logical
    (stored) pytree keeps the full ``[depth, ...]`` stack; see
    :func:`param_specs`. ``pp_axis=None`` is the dense twin (S = 1, same
    param paths)."""

    make_block: type
    block_kwargs: tuple
    local_depth: int
    microbatches: int = 1
    pp_axis: str | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b, t_len, d = x.shape
        # Microbatching never changes the math (blocks are per-sample), so a
        # batch the configured count can't split — the size-1 init dummy, an
        # odd eval batch — runs as one microbatch instead of erroring. The
        # training batch is validated against the count at config level.
        m = self.microbatches if b % self.microbatches == 0 else 1
        n_stages = 1 if self.pp_axis is None else lax.axis_size(self.pp_axis)
        micro = x.reshape(m, b // m, t_len, d)
        outputs = jnp.zeros_like(micro)
        recv = jnp.zeros_like(micro[0])
        if self.pp_axis is not None:
            # The schedule's carry becomes pp-varying on first rotation; a
            # vma-invariant initial carry would fail the scan carry check.
            recv = lax.pcast(recv, self.pp_axis, to="varying")
            outputs = lax.pcast(outputs, self.pp_axis, to="varying")

        steps = m + n_stages - 1
        Steps = nn.scan(
            _ScheduleStep,
            variable_broadcast="params",
            split_rngs={"params": False},
            length=steps,
        )
        (recv, outputs, _), _ = Steps(
            self.make_block, self.block_kwargs, self.local_depth, self.pp_axis
        )((recv, outputs, micro), jnp.arange(steps))

        if self.pp_axis is not None:
            # Only the last stage's capture buffer is meaningful; the masked
            # psum replicates it so the head computes pp-invariant.
            stage = lax.axis_index(self.pp_axis)
            outputs = lax.psum(
                jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
                self.pp_axis,
            )
        return outputs.reshape(b, t_len, d)


# Any leaf under the scanned stack is depth-stacked on its leading dim.
_STACK_LEAF = re.compile(rf"(^|/){STACK_NAME}/")


def param_specs(params, pp_axis: str = PP_AXIS):
    """Per-leaf ``PartitionSpec`` pytree: block-stack leaves split their
    leading (depth) dim over the pp axis; everything else replicated
    (shared walk: ``ops.placement.leading_dim_specs``)."""
    from p2pdl_tpu.ops.placement import leading_dim_specs

    return leading_dim_specs(params, _STACK_LEAF, pp_axis)


def validate_pp_geometry(depth: int, pp_shards: int, batch_size: int, microbatches: int) -> None:
    if depth % pp_shards != 0:
        raise ValueError(
            f"pp_shards ({pp_shards}) must divide the transformer depth ({depth})"
        )
    if microbatches < pp_shards:
        raise ValueError(
            f"pp_microbatches ({microbatches}) must be >= pp_shards "
            f"({pp_shards}) — fewer microbatches than stages leaves "
            f"permanent bubbles"
        )
    if batch_size % microbatches != 0:
        raise ValueError(
            f"pp_microbatches ({microbatches}) must divide batch_size "
            f"({batch_size})"
        )
