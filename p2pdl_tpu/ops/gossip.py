"""Gossip averaging over the peer axis: ring and exponential graphs.

The reference's only dissemination pattern is full-mesh broadcast over fresh
TCP connections (reference ``aggregator/aggregation.py:66-77``). The
decentralized-averaging capability (D-PSGD-style neighbor mixing) is built
TPU-native instead: peers form a logical sequence laid out as
``n_devices x peers_per_device``; neighbor blocks cross devices with
``lax.ppermute`` over ICI.

Two mixing graphs:

- ``ring_mix``: the static ±1 ring (3-neighbor Metropolis weights) — the
  classic D-PSGD topology; spectral gap O(1/P²), so consensus needs O(P²)
  rounds.
- ``exp_mix``: the one-peer exponential graph — at round r each peer mixes
  with peers at ±2^(r mod ⌈log₂P⌉); cycling through the log₂P power-of-two
  strides touches every scale, giving consensus in O(log P) rounds at the
  same per-round traffic as the ring (Assran et al. 2019 SGP; Ying et al.
  2021 show the exponential graph is provably efficient).
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from p2pdl_tpu.parallel.mesh import PEER_AXIS


def ring_mix(
    tree: Any,
    axis_name: str = PEER_AXIS,
    self_weight: float = 1.0 / 3.0,
    mask: jnp.ndarray | None = None,
) -> Any:
    """Symmetric ring gossip: ``new_i = w*x_i + (1-w)/2 * (x_{i-1} + x_{i+1})``.

    Leaves are local blocks ``[L, ...]`` inside ``shard_map``; global peer
    order is device-major. With ``self_weight=1/3`` this is the uniform
    3-neighbor Metropolis mix; row-stochastic and symmetric, so gossip
    converges to the true average over rounds.

    ``mask``: optional ``[L]`` trust verdict (1.0 = verified) — the BRB
    in-round gate. An unverified neighbor's params contribute ZERO to every
    other peer's mix and its weight mass reverts to self
    (``w_ii = self_weight + side * ((1 - m_left) + (1 - m_right))``), so
    rows stay stochastic and, with every mask 1, the weights equal the
    unmasked mix exactly (values match up to float add association). This
    is the reference's never-consume-unverified semantic (reference
    ``node/node.py:130-145``) for the in-band mix.
    """
    n_dev = lax.axis_size(axis_name)
    fwd = [(i, (i + 1) % n_dev) for i in range(n_dev)]
    bwd = [(i, (i - 1) % n_dev) for i in range(n_dev)]
    side = (1.0 - self_weight) / 2.0

    def shifted(x):
        # x: [L, ...]. Left neighbor of local peer 0 lives on the previous
        # device (its last peer); right neighbor of local peer L-1 on the next.
        from_prev = lax.ppermute(x[-1:], axis_name, fwd)  # prev device's tail
        from_next = lax.ppermute(x[:1], axis_name, bwd)  # next device's head
        left = jnp.concatenate([from_prev, x[:-1]], axis=0)
        right = jnp.concatenate([x[1:], from_next], axis=0)
        return left, right

    # named_scope: labels the mix's ops in jax.profiler device traces, so
    # the ppermute/ICI cost is attributable next to the host "agg" span.
    if mask is None:
        with jax.named_scope("gossip.ring_mix"):
            def leaf(x):
                left, right = shifted(x)
                return self_weight * x + side * (left + right)

            return jax.tree.map(leaf, tree)

    with jax.named_scope("gossip.ring_mix_masked"):
        m = mask.astype(jnp.float32)
        ml, mr = shifted(m)

        def leaf(x):
            left, right = shifted(x)
            bshape = (x.shape[0],) + (1,) * (x.ndim - 1)
            wl = (side * ml).reshape(bshape).astype(x.dtype)
            wr = (side * mr).reshape(bshape).astype(x.dtype)
            ws = (self_weight + side * ((1.0 - ml) + (1.0 - mr))).reshape(bshape).astype(x.dtype)
            return ws * x + wl * left + wr * right

        return jax.tree.map(leaf, tree)


def _global_shift(x: jnp.ndarray, offset: int, axis_name: str) -> jnp.ndarray:
    """``y[l] = x_global[(global_idx + offset) mod P]`` for a device-major
    stacked leaf ``[L, ...]`` inside ``shard_map``. ``offset`` is static.

    Rows are sliced BEFORE they cross ICI — exactly L rows move per shift
    (split k / L-k between the two source devices when the stride straddles
    a block boundary), the same per-round traffic as the ring."""
    l_per_dev = x.shape[0]
    n_dev = lax.axis_size(axis_name)
    d, k = divmod(offset % (n_dev * l_per_dev), l_per_dev)

    def from_dev_ahead(part, shift):
        # Receive ``part``'s rows from device (self + shift).
        if shift % n_dev == 0:
            return part
        perm = [(j, (j - shift) % n_dev) for j in range(n_dev)]
        return lax.ppermute(part, axis_name, perm)

    if k == 0:
        return from_dev_ahead(x, d)
    return jnp.concatenate(
        [from_dev_ahead(x[k:], d), from_dev_ahead(x[:k], d + 1)], axis=0
    )


def exp_mix(
    tree: Any,
    round_idx: jnp.ndarray,
    axis_name: str = PEER_AXIS,
    self_weight: float = 1.0 / 3.0,
    mask: jnp.ndarray | None = None,
) -> Any:
    """One-peer exponential-graph gossip: at round ``r`` mix with the peers
    at ±2^(r mod ⌈log₂P⌉) — same symmetric 3-neighbor weights as the ring,
    stride cycling through every power-of-two scale. ``round_idx`` is
    traced, so the stride is selected by ``lax.switch`` over the (static)
    log₂P candidate mixes. Doubly stochastic at every stride, so the global
    mean is preserved exactly and consensus contracts at every round.

    ``mask``: optional ``[L]`` trust verdict, same semantics as
    :func:`ring_mix` — unverified peers' params are excluded from every
    mix and their weight reverts to the receiving peer's self-weight.
    """
    leaves, treedef = jax.tree.flatten(tree)
    l_per_dev = leaves[0].shape[0]
    # Static axis size: shard_map binds mesh axes at trace time.
    n_dev = lax.axis_size(axis_name)
    num_peers = n_dev * l_per_dev
    n_strides = max(1, math.ceil(math.log2(num_peers)))
    side = (1.0 - self_weight) / 2.0

    def mix_at(offset):
        def branch(leaves_in):
            if mask is None:
                return [
                    self_weight * x
                    + side
                    * (
                        _global_shift(x, offset, axis_name)
                        + _global_shift(x, num_peers - offset, axis_name)
                    )
                    for x in leaves_in
                ]
            m = mask.astype(jnp.float32)
            mf = _global_shift(m, offset, axis_name)
            mb = _global_shift(m, num_peers - offset, axis_name)
            out = []
            for x in leaves_in:
                bshape = (x.shape[0],) + (1,) * (x.ndim - 1)
                wf = (side * mf).reshape(bshape).astype(x.dtype)
                wb = (side * mb).reshape(bshape).astype(x.dtype)
                ws = (
                    (self_weight + side * ((1.0 - mf) + (1.0 - mb)))
                    .reshape(bshape).astype(x.dtype)
                )
                out.append(
                    ws * x
                    + wf * _global_shift(x, offset, axis_name)
                    + wb * _global_shift(x, num_peers - offset, axis_name)
                )
            return out

        return branch

    with jax.named_scope("gossip.exp_mix"):
        mixed = lax.switch(
            round_idx % n_strides,
            [mix_at(2**j) for j in range(n_strides)],
            leaves,
        )
    return jax.tree.unflatten(treedef, mixed)
