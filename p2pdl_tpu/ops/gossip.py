"""Ring gossip averaging over the peer axis.

The reference's only dissemination pattern is full-mesh broadcast over fresh
TCP connections (reference ``aggregator/aggregation.py:66-77``). The
decentralized-averaging capability (D-PSGD-style neighbor mixing) is built
TPU-native instead: peers form a logical ring laid out as
``n_devices x peers_per_device``; in-device neighbors mix with ``jnp.roll``
(pure VMEM shuffles) and the two ring edges cross devices with a single
``lax.ppermute`` each over ICI.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from p2pdl_tpu.parallel.mesh import PEER_AXIS


def ring_mix(tree: Any, axis_name: str = PEER_AXIS, self_weight: float = 1.0 / 3.0) -> Any:
    """Symmetric ring gossip: ``new_i = w*x_i + (1-w)/2 * (x_{i-1} + x_{i+1})``.

    Leaves are local blocks ``[L, ...]`` inside ``shard_map``; global peer
    order is device-major. With ``self_weight=1/3`` this is the uniform
    3-neighbor Metropolis mix; row-stochastic and symmetric, so gossip
    converges to the true average over rounds.
    """
    n_dev = lax.axis_size(axis_name)
    fwd = [(i, (i + 1) % n_dev) for i in range(n_dev)]
    bwd = [(i, (i - 1) % n_dev) for i in range(n_dev)]
    side = (1.0 - self_weight) / 2.0

    def leaf(x):
        # x: [L, ...]. Left neighbor of local peer 0 lives on the previous
        # device (its last peer); right neighbor of local peer L-1 on the next.
        from_prev = lax.ppermute(x[-1:], axis_name, fwd)  # prev device's tail
        from_next = lax.ppermute(x[:1], axis_name, bwd)  # next device's head
        left = jnp.concatenate([from_prev, x[:-1]], axis=0)
        right = jnp.concatenate([x[1:], from_next], axis=0)
        return self_weight * x + side * (left + right)

    return jax.tree.map(leaf, tree)
