"""Attention ops: single-device and (via ``ring_attention``) sequence-parallel.

The reference has no attention or sequence models at all (its zoo is MLP+CNN,
reference ``models/model.py``); this module exists for the transformer/LSTM
benchmark families and for long-context scaling. The core scaled-dot-product
is a pure function so the same module runs dense on one device or blockwise
over a mesh axis with ``lax.ppermute`` (ring attention — see
``p2pdl_tpu.ops.ring_attention``), using the online-softmax accumulator that
makes blockwise attention exact.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


def sdpa(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, causal: bool = False) -> jnp.ndarray:
    """Scaled dot-product attention. ``q,k,v``: [B, H, T, D]."""
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    mask = None
    if causal:
        t_q, t_k = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((t_q, t_k), bool), k=t_k - t_q)
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    weights = jnp.asarray(
        nn.softmax(logits.astype(jnp.float32), axis=-1), dtype=q.dtype
    )
    if mask is not None:
        # Fully-masked query rows (possible when t_q > t_k) output zero, not
        # a uniform average of v — consistent with the fused flash kernel.
        weights = jnp.where(mask.any(axis=-1)[:, None], weights, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)


class MultiHeadAttention(nn.Module):
    """MHA over [B, T, dim].

    With ``seq_axis`` set (the name of a mesh axis the sequence is sharded
    over, inside ``shard_map``), attention runs sequence-parallel in one of
    two exact formulations selected by ``seq_impl``:

    - ``"ring"``: blockwise ring attention (``p2pdl_tpu.ops.ring_attention``)
      — T here is the *local* block and k/v blocks rotate over ICI with an
      online-softmax merge. Communication: (S-1) rotations of the local k/v
      block per layer; any head count.
    - ``"ulysses"``: the all-to-all formulation (DeepSpeed-Ulysses) — one
      ``all_to_all`` re-shards heads<->sequence so each shard computes
      FULL-length attention for ``heads / S`` heads (dense or fused flash,
      unchanged), then one ``all_to_all`` back. Communication: 2
      all_to_alls of the activations per layer; requires ``S | heads``.

    Otherwise dense single-device SDPA.
    """

    dim: int
    heads: int
    causal: bool = False
    seq_axis: str | None = None
    seq_impl: str = "ring"  # "ring" | "ulysses" (with seq_axis set)
    impl: str = "dense"  # "dense" | "flash" (fused Pallas kernels)
    # Tensor parallelism: mesh axis the heads are sharded over (inside
    # shard_map with this module's qkv kernel column-sharded and the output
    # kernel row-sharded — see ops/tp.py). Each shard computes its own
    # complete heads; one psum after the output projection. ``tp_shards``
    # sizes the DECLARED features to the local slice (flax validates param
    # shapes at apply, so the sharded twin must declare what it receives).
    tp_axis: str | None = None
    tp_shards: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        if (self.tp_shards != 1) != (self.tp_axis is not None):
            # Shard-sized features without the completing psums (or vice
            # versa) is a silently-wrong half-width model, not an option.
            raise ValueError("tp_shards and tp_axis must be set together")
        b, t, _ = x.shape
        head_dim = self.dim // self.heads
        qkv = nn.Dense(3 * self.dim // self.tp_shards, use_bias=False)(x)
        # Infer the LOCAL head count from the tensor (under tensor
        # parallelism the column-sharded qkv kernel yields heads/tp heads).
        local_heads = qkv.shape[-1] // (3 * head_dim)
        # HEAD-major feature layout (head, q|k|v, head_dim): a contiguous
        # column slice of the qkv kernel is then exactly one shard's heads
        # with their q, k, AND v — the property column-parallel tensor
        # parallelism needs (a qkv-major layout would give shard 0 all of q).
        qkv = qkv.reshape(b, t, local_heads, 3, head_dim)
        q, k, v = jnp.moveaxis(qkv, 3, 0)  # each [B, T, H, D]
        q, k, v = (jnp.swapaxes(a, 1, 2) for a in (q, k, v))  # [B, H, T, D]
        if self.impl not in ("dense", "flash"):
            raise ValueError(f"unknown attention impl {self.impl!r}; one of ('dense', 'flash')")
        if self.seq_axis is not None and self.seq_impl == "ulysses":
            n_shards = jax.lax.axis_size(self.seq_axis)
            if local_heads % n_shards != 0:
                raise ValueError(
                    f"ulysses sequence parallelism needs the shard count "
                    f"({n_shards}) to divide the head count ({local_heads})"
                )
            # Re-shard heads<->sequence: [B, H, T_local, D] -> [B, H/S,
            # T_global, D] (concat over source shards = device-major
            # sequence order), run UNSHARDED attention on the local heads,
            # then the inverse exchange.
            a2a = lambda x, s, c: jax.lax.all_to_all(  # noqa: E731
                x, self.seq_axis, split_axis=s, concat_axis=c, tiled=True
            )
            q, k, v = (a2a(a, 1, 2) for a in (q, k, v))
            if self.impl == "flash":
                from p2pdl_tpu.ops.pallas_attention import flash_attention

                out = flash_attention(q, k, v, causal=self.causal)
            else:
                out = sdpa(q, k, v, causal=self.causal)
            out = a2a(out, 2, 1)
        elif self.seq_axis is not None:
            from p2pdl_tpu.ops.ring_attention import ring_attention

            # impl selects the per-block compute inside the ring: "flash"
            # merges fused-kernel blocks exactly via their logsumexp.
            out = ring_attention(
                q, k, v, self.seq_axis, causal=self.causal, impl=self.impl
            )
        elif self.impl == "flash":
            from p2pdl_tpu.ops.pallas_attention import flash_attention

            out = flash_attention(q, k, v, causal=self.causal)
        else:
            out = sdpa(q, k, v, causal=self.causal)
        out = jnp.swapaxes(out, 1, 2).reshape(b, t, local_heads * head_dim)
        out = nn.Dense(self.dim, use_bias=False)(out)
        if self.tp_axis is not None:
            # Row-parallel output projection: each shard contributed its
            # heads' partial sum; one collective completes the projection
            # (and types the activations invariant over the tp axis, which
            # is what keeps replicated layers' gradients single-counted).
            out = jax.lax.psum(out, self.tp_axis)
        return out
