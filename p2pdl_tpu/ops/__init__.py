"""On-device collective ops: aggregation reducers (dense + blockwise-
streamed), gossip, secure masking, attention (dense / fused Pallas / ring),
tensor-parallel placement, mixture-of-experts dispatch."""

# moe first: its parallel.mesh import runs the parallel package __init__,
# which (via parallel.round) completes p2pdl_tpu.ops.gossip as a fresh
# import — importing gossip directly at this point instead would leave it
# partially initialized when round asks for it (circular-import order).
from p2pdl_tpu.ops.moe import MoEFFN, top1_route
from p2pdl_tpu.ops.gossip import exp_mix, ring_mix
from p2pdl_tpu.ops.pipeline import PipelinedBlocks
from p2pdl_tpu.ops.compression import topk_ef
from p2pdl_tpu.ops.aggregators import (
    bulyan,
    centered_clip,
    fedavg,
    geometric_median,
    krum,
    krum_scores,
    median,
    multi_krum,
    pairwise_sq_dists,
    trimmed_mean,
)
from p2pdl_tpu.ops.sharded_aggregators import (
    block_gram,
    bulyan_sharded,
    centered_clip_sharded,
    geometric_median_sharded,
    krum_sharded,
    median_sharded,
    multi_krum_sharded,
    trimmed_mean_sharded,
)

__all__ = [
    "topk_ef",
    "bulyan",
    "bulyan_sharded",
    "centered_clip",
    "centered_clip_sharded",
    "fedavg",
    "geometric_median",
    "geometric_median_sharded",
    "krum",
    "krum_scores",
    "median",
    "multi_krum",
    "pairwise_sq_dists",
    "trimmed_mean",
    "block_gram",
    "krum_sharded",
    "median_sharded",
    "multi_krum_sharded",
    "trimmed_mean_sharded",
    "MoEFFN",
    "top1_route",
    "PipelinedBlocks",
    "exp_mix",
    "ring_mix",
]
