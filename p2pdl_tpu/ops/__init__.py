"""On-device collective ops: aggregation reducers, gossip, secure masking."""

from p2pdl_tpu.ops.aggregators import (
    fedavg,
    krum,
    krum_scores,
    median,
    multi_krum,
    pairwise_sq_dists,
    trimmed_mean,
)

__all__ = [
    "fedavg",
    "krum",
    "krum_scores",
    "median",
    "multi_krum",
    "pairwise_sq_dists",
    "trimmed_mean",
]
