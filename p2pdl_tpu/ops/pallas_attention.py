"""Fused flash attention as Pallas TPU kernels (forward + backward).

The reference has no attention at all (its zoo is MLP+CNN, reference
``models/model.py``); our transformer family (ViT, and any long-sequence
model) needs attention that does not materialize the ``[T, T]`` score matrix
in HBM. XLA's dot-softmax-dot emission is already decent at small T, but the
fused kernel keeps the whole online-softmax recurrence in VMEM: one pass over
key blocks per query block, accumulators in float32, logits never leaving
the chip — the flash-attention scheme (Dao et al. 2022) expressed the Pallas
way (grid over [batch*heads, query blocks], ``fori_loop`` over key blocks).

The backward pass is two more Pallas kernels (dk/dv gridded over key blocks,
dq over query blocks) using the stored logsumexp — standard flash backward:
``ds = p * (dp - rowsum(do*o))``. Everything is wrapped in ``jax.custom_vjp``
so ``flash_attention`` drops into any ``jax.grad`` training step.

On non-TPU backends the same kernels run in Pallas interpret mode (tests
compare them bitwise-ish against the dense reference in
``p2pdl_tpu.ops.attention.sdpa``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = float("-inf")


def _auto_interpret() -> bool:
    """Compile on any TPU device, interpret elsewhere (CPU tests).

    Keyed on the device, not the backend *name*: TPU PJRT plugins can be
    registered under a different platform name (this image's tunnel registers
    the TPU as platform "axon"), and interpret mode there would silently run
    the kernels in the Python-level Pallas interpreter on real hardware.
    """
    dev = jax.devices()[0]
    return not ("tpu" in dev.platform.lower() or "tpu" in dev.device_kind.lower())


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, block_k, t_real, off
):
    """One query block against all key blocks. Refs: q [1, bq, D];
    k, v [1, Tk, D]; o [1, bq, D]; lse [1, bq]. ``off = t_k - t_q`` aligns
    causal positions for rectangular attention (sdpa's convention: query i
    attends keys j <= i + off)."""
    iq = pl.program_id(1)
    bq = q_ref.shape[1]
    t_pad = k_ref.shape[1]
    d = q_ref.shape[2]
    nk = t_pad // block_k

    q = q_ref[0].astype(jnp.float32) * scale  # [bq, D]
    q_pos = iq * bq + off + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    if causal:
        # Key blocks strictly after this query block's last allowed key are
        # fully masked — skip them entirely.
        nk_eff = jnp.clip(
            jax.lax.div((iq + 1) * bq + off + block_k - 1, block_k), 0, nk
        )
    else:
        nk_eff = nk

    def body(jk, carry):
        o_acc, m, l = carry
        k_blk = k_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        k_pos = jk * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        mask = k_pos < t_real
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        s = jnp.where(mask, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(mask, jnp.exp(s - safe_m[:, None]), 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o_acc * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    o_acc, m, l = jax.lax.fori_loop(0, nk_eff, body, (o0, m0, l0))

    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (o_acc / l_safe[:, None]).astype(o_ref.dtype)
    lse = jnp.where(jnp.isfinite(m), m + jnp.log(l_safe), NEG_INF)
    lse_ref[0] = lse


def _dkdv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, scale, causal, block_q, t_real, off,
):
    """One key block against all query blocks. k/v/dk/dv [1, bk, D];
    q/do [1, Tq, D]; lse/delta [1, Tq]."""
    jk = pl.program_id(1)
    bk = k_ref.shape[1]
    t_pad = q_ref.shape[1]
    nq = t_pad // block_q

    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    k_pos = jk * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)

    start_q = jnp.clip(jax.lax.div(jk * bk - off, block_q), 0, nq) if causal else 0

    def body(iq, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[0, pl.ds(iq * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[0, pl.ds(iq * block_q, block_q), :].astype(jnp.float32)
        lse_blk = lse_ref[0, pl.ds(iq * block_q, block_q)]
        delta_blk = delta_ref[0, pl.ds(iq * block_q, block_q)]

        s = scale * jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        q_pos = iq * block_q + off + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
        mask = k_pos < t_real
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        safe_lse = jnp.where(jnp.isfinite(lse_blk), lse_blk, 0.0)
        p = jnp.where(mask, jnp.exp(s - safe_lse[:, None]), 0.0)

        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_blk[:, None])  # [bq, bk]
        dk_new = dk_acc + scale * jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bk, D]
        dv_new = dv_acc + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk_new, dv_new

    dk0 = jnp.zeros(dk_ref.shape[1:], jnp.float32)
    dv0 = jnp.zeros(dv_ref.shape[1:], jnp.float32)
    dk, dv = jax.lax.fori_loop(start_q, nq, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
    *, scale, causal, block_k, t_real, off,
):
    """One query block against all key blocks, accumulating dq."""
    iq = pl.program_id(1)
    bq = q_ref.shape[1]
    t_pad = k_ref.shape[1]
    nk = t_pad // block_k

    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]
    delta = delta_ref[0]
    q_pos = iq * bq + off + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    if causal:
        nk_eff = jnp.clip(
            jax.lax.div((iq + 1) * bq + off + block_k - 1, block_k), 0, nk
        )
    else:
        nk_eff = nk

    def body(jk, dq_acc):
        k_blk = k_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        k_pos = jk * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        mask = k_pos < t_real
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        safe_lse = jnp.where(jnp.isfinite(lse), lse, 0.0)
        p = jnp.where(mask, jnp.exp(s - safe_lse[:, None]), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None])
        return dq_acc + scale * jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = jax.lax.fori_loop(0, nk_eff, body, jnp.zeros(dq_ref.shape[1:], jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _pad_t(x: jnp.ndarray, block: int) -> jnp.ndarray:
    t = x.shape[1]
    pad = (-t) % block
    if pad == 0:
        return x
    return jnp.pad(x, ((0, 0), (0, pad), (0, 0)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret):
    """q: [BH, Tq, D]; k, v: [BH, Tk, D] (head-flattened). Returns (out, lse).

    Rectangular attention follows ``sdpa``'s convention: with
    ``off = Tk - Tq``, query ``i`` attends keys ``j <= i + off``."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    off = tk - tq
    scale = d**-0.5
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    qp, kp, vp = _pad_t(q, block_q), _pad_t(k, block_k), _pad_t(v, block_k)
    tq_pad, tk_pad = qp.shape[1], kp.shape[1]
    nq = tq_pad // block_q

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_k=block_k, t_real=tk, off=off
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, tk_pad, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tk_pad, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq_pad, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tq_pad), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :tq], lse[:, :tq]


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    bh, tq, d = q.shape
    tk = k.shape[1]
    off = tk - tq
    scale = d**-0.5
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)

    # delta_i = rowsum(do * o): the softmax-jacobian correction term.
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    qp, dop = _pad_t(q, block_q), _pad_t(g, block_q)
    kp, vp = _pad_t(k, block_k), _pad_t(v, block_k)
    tq_pad, tk_pad = qp.shape[1], kp.shape[1]
    pad_q = tq_pad - tq
    # Padded rows must not contribute: lse=-inf makes their p rows zero.
    lse_p = jnp.pad(lse, ((0, 0), (0, pad_q)), constant_values=NEG_INF)
    delta_p = jnp.pad(delta, ((0, 0), (0, pad_q)))

    dkdv = functools.partial(
        _dkdv_kernel, scale=scale, causal=causal, block_q=block_q, t_real=tk, off=off
    )
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(bh, tk_pad // block_k),
        in_specs=[
            pl.BlockSpec((1, tq_pad, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, tq_pad, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, tq_pad), lambda b, j: (b, 0)),
            pl.BlockSpec((1, tq_pad), lambda b, j: (b, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk_pad, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk_pad, d), v.dtype),
        ],
        interpret=interpret,
    )(qp, kp, vp, dop, lse_p, delta_p)

    dqk = functools.partial(
        _dq_kernel, scale=scale, causal=causal, block_k=block_k, t_real=tk, off=off
    )
    dq = pl.pallas_call(
        dqk,
        grid=(bh, tq_pad // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, tk_pad, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tk_pad, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq_pad, d), q.dtype),
        interpret=interpret,
    )(qp, kp, vp, dop, lse_p, delta_p)

    return dq[:, :tq], dk[:, :tk], dv[:, :tk]


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Fused attention over ``[B, H, T, D]`` (same contract as ``sdpa``).

    ``interpret=None`` auto-selects Pallas interpret mode off-TPU so the one
    code path runs everywhere; on TPU the kernels compile via Mosaic.
    """
    if interpret is None:
        interpret = _auto_interpret()
    b, h, t, d = q.shape
    flat = lambda x: x.reshape(b * h, x.shape[2], x.shape[-1])
    out = _flash(flat(q), flat(k), flat(v), causal, block_q, block_k, interpret)
    return out.reshape(b, h, t, v.shape[-1])
