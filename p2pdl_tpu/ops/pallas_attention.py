"""Fused flash attention as Pallas TPU kernels (forward + backward).

The reference has no attention at all (its zoo is MLP+CNN, reference
``models/model.py``); our transformer family (ViT, and any long-sequence
model) needs attention that does not materialize the ``[T, T]`` score matrix
in HBM. The fused kernel keeps the online-softmax recurrence in VMEM:
accumulators in float32, logits never leaving the chip — the flash-attention
scheme (Dao et al. 2022) expressed the Pallas way.

Kernel structure: a 3-D grid ``(batch*heads, query blocks, key blocks)``
(outer two parallel, innermost sequential), with the running ``(o, m, l)``
accumulators living in VMEM scratch that persists across the innermost grid
dimension. Both operands are therefore streamed block-by-block by the Pallas
pipeline — VMEM use is O(block_q·d + block_k·d), independent of sequence
length, so the kernel serves exactly the long-sequence regime it exists for
(a full-T BlockSpec would cap T at a few thousand). Fully-masked key blocks
of causal attention are skipped via ``pl.when``.

The backward pass is two more Pallas kernels of the same shape (dk/dv
gridded over key blocks with query blocks innermost, dq the transpose) using
the stored logsumexp — standard flash backward: ``ds = p*(dp - rowsum(do*o))``.
Everything is wrapped in ``jax.custom_vjp`` so ``flash_attention`` drops into
any ``jax.grad`` training step.

Off-TPU, auto mode routes to the dense JAX path (see ``flash_attention``);
kernel math is CPU-tested by forcing Pallas interpret mode explicitly
(tests compare it against the dense reference ``p2pdl_tpu.ops.attention.sdpa``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float("-inf")
# Scalar-per-row accumulators (m, l) are stored broadcast across one lane
# register of width 128 — Mosaic's native vector layout for row statistics.
_LANES = 128


def _on_tpu() -> bool:
    """True on any TPU device — keyed on the device, not the backend *name*:
    TPU PJRT plugins can be registered under a different platform name (this
    image's tunnel registers the TPU as platform "axon"), and interpret mode
    there would silently run the kernels in the Python-level Pallas
    interpreter on real hardware."""
    dev = jax.devices()[0]
    return "tpu" in dev.platform.lower() or "tpu" in dev.device_kind.lower()


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, o_acc, m_acc, l_acc,
    *, scale, causal, t_real, off,
):
    """Grid (bh, nq, nk), innermost sequential over key blocks.

    Refs: q/o [1, bq, D]; k/v [1, bk, D]; lse [1, bq, 1]; scratch o_acc
    [bq, D], m/l_acc [bq, LANES] (row stats broadcast over lanes). The lse
    trailing singleton exists for Mosaic's tiling rule: the last two dims of
    a block must be (divisible by 8, divisible by 128) or equal to the array
    dims — a 2-D [BH, T] layout would put the size-1 BH block in the
    second-minor slot, which is neither. ``off = Tk - Tq`` aligns causal
    positions for rectangular attention (sdpa's convention: query i attends
    keys j <= i + off)."""
    iq, jk = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]

    @pl.when(jk == 0)
    def _():
        o_acc[:] = jnp.zeros_like(o_acc)
        m_acc[:] = jnp.full_like(m_acc, NEG_INF)
        l_acc[:] = jnp.zeros_like(l_acc)

    def compute():
        q = q_ref[0].astype(jnp.float32) * scale  # [bq, D]
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        q_pos = iq * bq + off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = jk * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = k_pos < t_real
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        s = jnp.where(mask, s, NEG_INF)

        m = m_acc[:, 0]
        l = l_acc[:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(mask, jnp.exp(s - safe_m[:, None]), 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_acc[:] = o_acc[:] * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_acc[:] = jnp.broadcast_to(m_new[:, None], m_acc.shape)
        l_acc[:] = jnp.broadcast_to(l_new[:, None], l_acc.shape)

    if causal:
        # Key blocks strictly after this query block's last allowed key are
        # fully masked — skip their compute (operand streaming still occurs).
        pl.when(jk * bk <= (iq + 1) * bq - 1 + off)(compute)
    else:
        compute()

    @pl.when(jk == nk - 1)
    def _():
        m = m_acc[:, 0]
        l = l_acc[:, 0]
        l_safe = jnp.maximum(l, 1e-30)
        o_ref[0] = (o_acc[:] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0] = jnp.where(jnp.isfinite(m), m + jnp.log(l_safe), NEG_INF)[:, None]


def _dkdv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
    *, scale, causal, t_real, off,
):
    """Grid (bh, nk, nq), innermost sequential over query blocks.

    k/v/dk/dv [1, bk, D]; q/do [1, bq, D]; lse/delta [1, bq, 1]; scratch
    dk/dv_acc [bk, D] float32."""
    jk, iq = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)
    bk = k_ref.shape[1]
    bq = q_ref.shape[1]

    @pl.when(iq == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def compute():
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        q_blk = q_ref[0].astype(jnp.float32)
        do_blk = do_ref[0].astype(jnp.float32)
        lse_blk = lse_ref[0][:, 0]
        delta_blk = delta_ref[0][:, 0]

        s = scale * jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        q_pos = iq * bq + off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = jk * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = k_pos < t_real
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        safe_lse = jnp.where(jnp.isfinite(lse_blk), lse_blk, 0.0)
        p = jnp.where(mask, jnp.exp(s - safe_lse[:, None]), 0.0)

        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_blk[:, None])  # [bq, bk]
        dk_acc[:] += scale * jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bk, D]
        dv_acc[:] += jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        # Query blocks that end before this key block starts can't attend it.
        pl.when(iq * bq + bq - 1 + off >= jk * bk)(compute)
    else:
        compute()

    @pl.when(iq == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, scale, causal, t_real, off,
):
    """Grid (bh, nq, nk), innermost sequential over key blocks, accumulating
    dq for one query block in scratch [bq, D]."""
    iq, jk = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]

    @pl.when(jk == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def compute():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, 0]
        delta = delta_ref[0][:, 0]
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        q_pos = iq * bq + off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = jk * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = k_pos < t_real
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        safe_lse = jnp.where(jnp.isfinite(lse), lse, 0.0)
        p = jnp.where(mask, jnp.exp(s - safe_lse[:, None]), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None])
        dq_acc[:] += scale * jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(jk * bk <= (iq + 1) * bq - 1 + off)(compute)
    else:
        compute()

    @pl.when(jk == nk - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _vma(x) -> frozenset:
    """Varying-manual-axes of ``x`` (non-empty only under ``shard_map``).

    ``pallas_call`` output avals must carry the same vma as the operands when
    the kernel runs inside ``shard_map`` with vma checking on; outside, this
    is the empty set and has no effect."""
    try:
        return frozenset(jax.typeof(x).vma)
    except Exception:  # non-traced input or backend without vma support
        return frozenset()


def _pad_t(x: jnp.ndarray, block: int) -> jnp.ndarray:
    t = x.shape[1]
    pad = (-t) % block
    if pad == 0:
        return x
    return jnp.pad(x, ((0, 0), (0, pad), (0, 0)))


_SEMANTICS = pltpu.CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary")
)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret):
    """q: [BH, Tq, D]; k, v: [BH, Tk, D] (head-flattened). Returns (out, lse).

    Rectangular attention follows ``sdpa``'s convention: with
    ``off = Tk - Tq``, query ``i`` attends keys ``j <= i + off``."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    off = tk - tq
    scale = d**-0.5
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    qp, kp, vp = _pad_t(q, block_q), _pad_t(k, block_k), _pad_t(v, block_k)
    tq_pad, tk_pad = qp.shape[1], kp.shape[1]

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, t_real=tk, off=off
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, tq_pad // block_q, tk_pad // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq_pad, d), q.dtype, vma=_vma(q)),
            jax.ShapeDtypeStruct((bh, tq_pad, 1), jnp.float32, vma=_vma(q)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :tq], lse[:, :tq, 0]


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    return _flash_bwd_impl(causal, block_q, block_k, interpret, res, g, None)


def _flash_bwd_impl(causal, block_q, block_k, interpret, res, g, g_lse):
    q, k, v, out, lse = res
    bh, tq, d = q.shape
    tk = k.shape[1]
    off = tk - tq
    scale = d**-0.5
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)

    # delta_i = rowsum(do * o): the softmax-jacobian correction term. An lse
    # cotangent folds into the same term: d lse/d s_j = p_j, so
    # ds = p*(dp - delta) + g_lse*p = p*(dp - (delta - g_lse)).
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)

    qp, dop = _pad_t(q, block_q), _pad_t(g, block_q)
    kp, vp = _pad_t(k, block_k), _pad_t(v, block_k)
    tq_pad, tk_pad = qp.shape[1], kp.shape[1]
    pad_q = tq_pad - tq
    # Padded q rows: lse=-inf gives well-defined (finite) p rows, and their
    # do rows are zero, so they contribute nothing to dk/dv.
    # Trailing singleton for the Mosaic block-tiling rule (see _fwd_kernel).
    lse_p = jnp.pad(lse, ((0, 0), (0, pad_q)), constant_values=NEG_INF)[:, :, None]
    delta_p = jnp.pad(delta, ((0, 0), (0, pad_q)))[:, :, None]

    dkdv = functools.partial(
        _dkdv_kernel, scale=scale, causal=causal, t_real=tk, off=off
    )
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(bh, tk_pad // block_k, tq_pad // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk_pad, d), k.dtype, vma=_vma(k)),
            jax.ShapeDtypeStruct((bh, tk_pad, d), v.dtype, vma=_vma(v)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(qp, kp, vp, dop, lse_p, delta_p)

    dqk = functools.partial(_dq_kernel, scale=scale, causal=causal, t_real=tk, off=off)
    dq = pl.pallas_call(
        dqk,
        grid=(bh, tq_pad // block_q, tk_pad // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq_pad, d), q.dtype, vma=_vma(q)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(qp, kp, vp, dop, lse_p, delta_p)

    return dq[:, :tq], dk[:, :tk], dv[:, :tk]


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_lse(q, k, v, causal, block_q, block_k, interpret):
    return _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)


def _flash_lse_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_bwd(causal, block_q, block_k, interpret, res, g):
    g_out, g_lse = g
    return _flash_bwd_impl(causal, block_q, block_k, interpret, res, g_out, g_lse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _dense_with_lse(q, k, v, causal):
    """Dense (out, lse) with ``sdpa``'s exact masking semantics — the
    off-TPU route for ``flash_attention_with_lse``; also the oracle in
    tests. ``q, k, v``: [B, H, T, D]."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    s = s * scale
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((t_q, t_k), bool), k=t_k - t_q)
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.where(jnp.isfinite(s), jnp.exp(s - safe_m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    lse = jnp.where(jnp.isfinite(m), m + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF)
    out = jnp.einsum("bhqk,bhkd->bhqd", p / jnp.maximum(l, 1e-30)[..., None], v.astype(jnp.float32))
    return out.astype(q.dtype), lse


# Block-size selection. The kernels take any (block_q, block_k) dividing
# (t_q, t_k) with lane-legal tiles; the best choice is hardware-empirical.
# ``bench.py --tune-flash`` sweeps the grid with on-device chained-step
# timing (the only trustworthy clock through the remote-dispatch tunnel)
# and its findings get baked here, keyed by (seq_len, head_dim); unknown
# shapes fall back to 128x128 (the MXU-native tile, never illegal).
# ``P2PDL_FLASH_BLOCKS="bq,bk"`` overrides everything for experiments.
_BLOCK_TABLE: dict[tuple[int, int], tuple[int, int]] = {
    # (seq_len, head_dim): (block_q, block_k) — fill from TUNE_FLASH.json.
}


def _default_blocks(t: int, d: int) -> tuple[int, int]:
    import os

    env = os.environ.get("P2PDL_FLASH_BLOCKS")
    if env:
        bq, bk = (int(x) for x in env.split(","))
    else:
        bq, bk = _BLOCK_TABLE.get((t, d), (128, 128))
    # Clamp BOTH paths: an oversized block (table or override) reaching the
    # kernel at a shorter sequence length is an illegal Mosaic grid.
    return min(bq, t), min(bk, t)


def flash_attention_with_lse(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret=None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fused attention returning ``(out [B,H,T,D], lse [B,H,T])`` — the
    per-row logsumexp lets callers merge partial attention over key blocks
    exactly (flash-inside-ring: ``ops.ring_attention`` with impl='flash').
    Differentiable in both outputs. Same auto-routing as
    :func:`flash_attention`. ``block_q``/``block_k`` default per-shape via
    the tuned ``_BLOCK_TABLE``."""
    if interpret is None:
        if not _on_tpu():
            return _dense_with_lse(q, k, v, causal)
        interpret = False
    b, h, t, d = q.shape
    if block_q is None or block_k is None:
        dq, dk = _default_blocks(t, d)
        block_q = block_q or dq
        block_k = block_k or dk
    flat = lambda x: x.reshape(b * h, x.shape[2], x.shape[-1])
    out, lse = _flash_lse(flat(q), flat(k), flat(v), causal, block_q, block_k, interpret)
    return out.reshape(b, h, t, v.shape[-1]), lse.reshape(b, h, t)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret=None,
) -> jnp.ndarray:
    """Fused attention over ``[B, H, T, D]`` (same contract as ``sdpa``).

    ``interpret=None`` auto-selects: Mosaic-compiled kernels on TPU, the
    dense JAX path (``sdpa``, numerically the same attention) elsewhere.
    The off-TPU default is dense rather than Pallas-interpret because the
    two interpreters have complementary composition bugs in current JAX
    (generic ``interpret=True`` breaks under ``shard_map`` vma typing;
    ``pltpu.InterpretParams`` breaks under ``vmap``), and the peer-mesh
    round wraps models in both. Kernel *math* is still CPU-tested by
    passing ``interpret`` explicitly (tests/test_pallas_attention.py).
    """
    if interpret is None:
        if not _on_tpu():
            from p2pdl_tpu.ops.attention import sdpa

            return sdpa(q, k, v, causal=causal)
        interpret = False
    b, h, t, d = q.shape
    if block_q is None or block_k is None:
        dq, dk = _default_blocks(t, d)
        block_q = block_q or dq
        block_k = block_k or dk
    flat = lambda x: x.reshape(b * h, x.shape[2], x.shape[-1])
    out = _flash(flat(q), flat(k), flat(v), causal, block_q, block_k, interpret)
    return out.reshape(b, h, t, v.shape[-1])
