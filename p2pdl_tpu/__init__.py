"""p2pdl_tpu — a TPU-native peer-to-peer decentralized learning framework.

A ground-up JAX/XLA re-design of the capabilities of the reference
``yoontaeung/p2pdl`` project (peer-to-peer decentralized learning with local
SGD, authenticated update exchange via Byzantine Reliable Broadcast, and
FedAvg-style aggregation — see reference ``main.py``, ``node/node.py``).

Architecture (TPU-first, not a port):

- The *peer axis lives on the device mesh*: every peer's parameters are one
  slice of a leading ``num_peers`` dimension of a single pytree, sharded over a
  ``jax.sharding.Mesh`` axis and vmapped within each device for peers > devices.
- Local SGD is a single ``jit``-compiled, ``lax.scan``-based step — no
  per-batch host sync (the reference's per-batch ``.item()`` at
  ``training/train.py:17`` is the anti-pattern this kills).
- Every exchange pattern is an XLA collective over ICI: FedAvg = masked
  ``psum``; robust aggregation (Krum / trimmed-mean / median) over
  ``all_gather``-ed deltas; gossip = ``lax.ppermute`` rings; secure
  aggregation = pairwise PRNG masks that cancel under ``psum``.
- The trust plane (ECDSA signatures, Bracha-style reliable broadcast) stays
  host-side, operating on digests of canonically-serialized updates, and never
  serializes the device pipeline.
"""

__version__ = "0.1.0"

from p2pdl_tpu.utils import jax_compat  # noqa: F401  (P2PDL_JAX_COMPAT=1 installs shard_map/pcast aliases)
from p2pdl_tpu.config import Config  # noqa: F401
