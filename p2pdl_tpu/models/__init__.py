"""Model zoo.

The reference's complete model zoo is an MLP and a CIFAR-locked CNN
(reference ``models/model.py:3-33``). Ours reproduces those two and extends to
the benchmark families (ResNet-18, char-LSTM, ViT-Tiny). All models are
``flax.linen`` modules: ``init`` yields a pure param pytree that stacks
cleanly along a leading peer axis and shards over the mesh.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from p2pdl_tpu.models.mlp import MLP
from p2pdl_tpu.models.cnn import SimpleCNN

__all__ = ["MLP", "SimpleCNN", "get_model", "model_input_spec"]


def get_model(name: str, **kwargs: Any):
    """Build a model by config name (see ``config.MODELS``)."""
    if name == "mlp":
        return MLP(**kwargs)
    if name == "simple_cnn":
        return SimpleCNN(**kwargs)
    if name == "resnet18":
        from p2pdl_tpu.models.resnet import ResNet18

        return ResNet18(**kwargs)
    if name == "char_lstm":
        from p2pdl_tpu.models.lstm import CharLSTM

        return CharLSTM(**kwargs)
    if name == "vit_tiny":
        from p2pdl_tpu.models.vit import ViTTiny

        return ViTTiny(**kwargs)
    if name == "char_gpt":
        from p2pdl_tpu.models.gpt import CharGPT

        return CharGPT(**kwargs)
    raise ValueError(f"unknown model {name!r}")


def model_input_spec(model_name: str, dataset: str, seq_len: int = 128) -> tuple[tuple[int, ...], Any]:
    """(example input shape without batch dim, dtype) for a model/dataset pair.

    Image models take the dataset's native shape (MLP flattens internally, so
    it serves both 28x28x1 and 32x32x3); sequence models take int tokens.
    """
    if model_name in ("char_lstm", "char_gpt"):
        return (seq_len,), jnp.int32
    image_shape = (32, 32, 3) if dataset == "cifar10" else (28, 28, 1)
    if model_name in ("mlp", "simple_cnn"):
        return image_shape, jnp.float32
    if model_name in ("resnet18", "vit_tiny"):
        if dataset not in ("cifar10",):
            # Conv stem / patch geometry is sized for 32x32x3.
            raise ValueError(f"{model_name} requires dataset='cifar10', got {dataset!r}")
        return (32, 32, 3), jnp.float32
    raise ValueError(f"unknown model {model_name!r}")


def init_params(model: Any, input_shape: tuple[int, ...], dtype: Any, key: jax.Array):
    """Initialize one peer's params for ``model`` on a dummy batch of 1."""
    dummy = jnp.zeros((1, *input_shape), dtype=dtype)
    return model.init(key, dummy)["params"]
