"""Character-level LSTM for the Shakespeare-style gossip benchmark config.

Beyond the reference's model zoo; required by the BASELINE.json
Shakespeare-LSTM config. Next-character prediction: ``[B, T]`` int tokens ->
``[B, T, vocab]`` logits. The recurrence uses ``flax.linen.RNN`` (a
``lax.scan`` under the hood) so the whole sequence unrolls inside one
compiled loop with static shapes.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


def _match_vma(carry, ref: jnp.ndarray):
    """Give the fresh zero carry the same varying-manual-axes type as the
    activations it will be scanned with. Inside ``shard_map`` the scan body
    produces peer-varying carries, and a vma-invariant initial carry would
    fail the scan's carry type check; outside ``shard_map`` this is a no-op.
    """
    try:
        vma = tuple(jax.typeof(ref).vma)
    except Exception:
        return carry
    if not vma:
        return carry
    return jax.tree.map(lambda c: lax.pcast(c, vma, to="varying"), carry)


class CharLSTM(nn.Module):
    vocab_size: int = 80
    embed_dim: int = 64
    hidden: int = 256
    num_layers: int = 2

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        h = nn.Embed(self.vocab_size, self.embed_dim)(x)
        for _ in range(self.num_layers):
            cell = nn.OptimizedLSTMCell(self.hidden)
            carry = _match_vma(
                cell.initialize_carry(jax.random.PRNGKey(0), h[:, 0].shape), h
            )
            h = nn.RNN(cell)(h, initial_carry=carry)
        return nn.Dense(self.vocab_size)(h)
