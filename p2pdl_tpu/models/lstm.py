"""Character-level LSTM for the Shakespeare-style gossip benchmark config.

Beyond the reference's model zoo; required by the BASELINE.json
Shakespeare-LSTM config. Next-character prediction: ``[B, T]`` int tokens ->
``[B, T, vocab]`` logits. The recurrence uses ``flax.linen.RNN`` (a
``lax.scan`` under the hood) so the whole sequence unrolls inside one
compiled loop with static shapes.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class CharLSTM(nn.Module):
    vocab_size: int = 80
    embed_dim: int = 64
    hidden: int = 256
    num_layers: int = 2

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        h = nn.Embed(self.vocab_size, self.embed_dim)(x)
        for _ in range(self.num_layers):
            h = nn.RNN(nn.OptimizedLSTMCell(self.hidden))(h)
        return nn.Dense(self.vocab_size)(h)
