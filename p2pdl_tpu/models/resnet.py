"""ResNet-18 (CIFAR variant) for the 32-peer non-IID benchmark config.

Beyond the reference's model zoo (reference ``models/model.py`` stops at a
2-conv CNN); required by the BASELINE.json CIFAR-10/ResNet-18 config.

Uses GroupNorm rather than BatchNorm: batch statistics do not aggregate
meaningfully across federated peers (averaging running stats from disjoint
non-IID shards is a known FedAvg failure mode), and GroupNorm keeps model
state a pure params pytree — no mutable batch_stats collection to shard.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class ResidualBlock(nn.Module):
    features: int
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        residual = x
        y = nn.Conv(self.features, (3, 3), self.strides, padding="SAME", use_bias=False)(x)
        y = nn.GroupNorm(num_groups=min(32, self.features))(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), padding="SAME", use_bias=False)(y)
        y = nn.GroupNorm(num_groups=min(32, self.features))(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.features, (1, 1), self.strides, padding="SAME", use_bias=False
            )(residual)
            residual = nn.GroupNorm(num_groups=min(32, self.features))(residual)
        return nn.relu(y + residual)


class ResNet18(nn.Module):
    """CIFAR-style ResNet-18: 3x3 stem (no maxpool), stages (64,128,256,512)x2."""

    stage_sizes: Sequence[int] = (2, 2, 2, 2)
    features: Sequence[int] = (64, 128, 256, 512)
    num_classes: int = 10

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = nn.Conv(64, (3, 3), padding="SAME", use_bias=False)(x)
        x = nn.GroupNorm(num_groups=32)(x)
        x = nn.relu(x)
        for stage, (blocks, feats) in enumerate(zip(self.stage_sizes, self.features)):
            for block in range(blocks):
                strides = (2, 2) if stage > 0 and block == 0 else (1, 1)
                x = ResidualBlock(feats, strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)
