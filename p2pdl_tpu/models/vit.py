"""ViT-Tiny for the 1024-peer secure-aggregation benchmark config.

Beyond the reference's model zoo; required by the BASELINE.json ViT-Tiny
config. Standard ViT-Tiny geometry (dim 192, depth 12, 3 heads) with a 4x4
patch stem sized for 32x32 inputs. Attention is factored through
``p2pdl_tpu.ops.attention`` so the same blocks run single-device or
sequence-parallel (ring attention) over a mesh axis.

Sequence parallelism (``seq_axis`` set, called inside ``shard_map`` with the
input's HEIGHT dimension sharded on that axis): the 4x4 patch stem is
stride-aligned so each shard patchifies its own row block locally (no halo),
patch order is row-major so shard blocks concatenate to the global token
sequence in mesh order, position embeddings are the full (replicated) table
sliced per shard, attention runs as exact ring attention, and the head
mean-pools with a ``psum`` over the axis. Requires ``pool='mean'`` — a CLS
token lives on one shard and would break the uniform block layout. Param
shapes are identical to the dense ``seq_axis=None`` twin, so one init/eval
model serves both.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

from p2pdl_tpu.ops.attention import MultiHeadAttention


class TransformerBlock(nn.Module):
    dim: int
    heads: int
    mlp_ratio: int = 4
    # Causal masking (decoder-only LMs — models/gpt.py); the ViT uses the
    # default bidirectional attention.
    causal: bool = False
    attn_impl: str = "dense"
    seq_axis: str | None = None
    seq_impl: str = "ring"  # "ring" | "ulysses" (with seq_axis set)
    # Tensor parallelism: heads + MLP hidden sharded over this mesh axis
    # (megatron column/row decomposition; placement in ops/tp.py).
    # tp_shards sizes the declared features to the local slice.
    tp_axis: str | None = None
    tp_shards: int = 1
    # Mixture-of-experts: > 0 replaces this block's MLP with a top-1
    # mixture of that many experts (ops/moe.py); under expert parallelism
    # the experts shard over ``ep_axis``.
    moe_experts: int = 0
    moe_capacity_factor: float = 2.0
    ep_axis: str | None = None
    ep_shards: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        if (self.tp_shards != 1) != (self.tp_axis is not None):
            raise ValueError("tp_shards and tp_axis must be set together")
        y = nn.LayerNorm()(x)
        x = x + MultiHeadAttention(
            self.dim,
            self.heads,
            causal=self.causal,
            impl=self.attn_impl,
            seq_axis=self.seq_axis,
            seq_impl=self.seq_impl,
            tp_axis=self.tp_axis,
            tp_shards=self.tp_shards,
        )(y)
        y = nn.LayerNorm()(x)
        if self.moe_experts > 0:
            from p2pdl_tpu.ops.moe import MoEFFN

            y = MoEFFN(
                num_experts=self.moe_experts,
                dim=self.dim,
                hidden=self.dim * self.mlp_ratio,
                capacity_factor=self.moe_capacity_factor,
                ep_axis=self.ep_axis,
                ep_shards=self.ep_shards,
            )(y)
            return x + y
        # Column-parallel fc1 under tp (declared width = local slice).
        y = nn.Dense(self.dim * self.mlp_ratio // self.tp_shards)(y)
        y = nn.gelu(y)
        y = nn.Dense(self.dim)(y)  # row-parallel under tp
        if self.tp_axis is not None:
            # Completes the row-parallel fc2 (its bias is pre-scaled by
            # 1/tp_shards before apply — ops/tp.scale_row_parallel_biases).
            y = lax.psum(y, self.tp_axis)
        return x + y


class ViTTiny(nn.Module):
    patch: int = 4
    dim: int = 192
    depth: int = 12
    heads: int = 3
    num_classes: int = 10
    attn_impl: str = "dense"  # "flash" fuses attention via Pallas on TPU
    pool: str = "cls"  # "cls" | "mean"
    seq_axis: str | None = None  # mesh axis the token sequence is sharded on
    seq_impl: str = "ring"  # "ring" | "ulysses" (with seq_axis set)
    tp_axis: str | None = None  # mesh axis heads/MLP-hidden are sharded on
    tp_shards: int = 1
    # Mixture-of-experts: every ``moe_every``-th block (1-based from block
    # moe_every - 1) swaps its MLP for a top-1 mixture of ``moe_experts``
    # experts; ``ep_axis`` shards the experts (expert parallelism).
    moe_experts: int = 0
    moe_every: int = 2
    moe_capacity_factor: float = 2.0
    ep_axis: str | None = None
    ep_shards: int = 1
    # Pipeline parallelism: ``scan_blocks`` stores the trunk as ONE nn.scan
    # stack (param leaves lead with a depth dim); ``pp_axis`` shards that
    # dim — each shard runs depth/pp_shards blocks and microbatch
    # activations rotate by ppermute (ops/pipeline.py). The scan-blocks
    # param tree differs from the unstacked default (depth-stacked leaves),
    # so the dense twin of a pp run must also set scan_blocks.
    scan_blocks: bool = False
    pp_axis: str | None = None
    pp_shards: int = 1
    pp_microbatches: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        if self.seq_axis is not None and self.pool != "mean":
            raise ValueError("sequence-parallel ViT requires pool='mean'")
        if x.shape[1] % self.patch != 0:
            # Without this, nn.Conv's SAME padding would silently pad each
            # (shard-local) height block, breaking the exact equivalence to
            # the dense twin.
            raise ValueError(
                f"input height {x.shape[1]} (the per-shard block under "
                f"sequence parallelism) must be divisible by patch={self.patch}"
            )
        b = x.shape[0]
        x = nn.Conv(self.dim, (self.patch, self.patch), strides=(self.patch, self.patch))(x)
        x = x.reshape(b, -1, self.dim)  # [B, local tokens, dim]
        t_local = x.shape[1]
        if self.seq_axis is not None:
            n_shards = lax.axis_size(self.seq_axis)
            t_global = t_local * n_shards
        else:
            t_global = t_local

        if self.pool == "cls":
            cls = self.param("cls", nn.initializers.zeros, (1, 1, self.dim))
            x = jnp.concatenate([jnp.broadcast_to(cls, (b, 1, self.dim)), x], axis=1)
            t_global += 1
            t_local += 1
        # Full position table regardless of sharding (identical param shapes
        # for the dense and sequence-parallel twins); each shard reads its
        # row-major block.
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, t_global, self.dim)
        )
        if self.seq_axis is not None:
            start = lax.axis_index(self.seq_axis) * t_local
            pos = lax.dynamic_slice(pos, (0, start, 0), (1, t_local, self.dim))
        x = x + pos

        if self.scan_blocks:
            if self.moe_experts > 0 or self.tp_axis is not None or self.seq_axis is not None:
                raise ValueError(
                    "scan_blocks (pipeline parallelism) does not compose "
                    "with MoE / tensor / sequence parallelism yet"
                )
            from p2pdl_tpu.ops.pipeline import PipelinedBlocks

            x = PipelinedBlocks(
                make_block=TransformerBlock,
                block_kwargs=(
                    ("dim", self.dim),
                    ("heads", self.heads),
                    ("attn_impl", self.attn_impl),
                ),
                local_depth=self.depth // self.pp_shards,
                microbatches=self.pp_microbatches,
                pp_axis=self.pp_axis,
            )(x)
        else:
            for i in range(self.depth):
                is_moe = (
                    self.moe_experts > 0 and i % self.moe_every == self.moe_every - 1
                )
                x = TransformerBlock(
                    self.dim,
                    self.heads,
                    attn_impl=self.attn_impl,
                    seq_axis=self.seq_axis,
                    seq_impl=self.seq_impl,
                    tp_axis=self.tp_axis,
                    tp_shards=self.tp_shards,
                    moe_experts=self.moe_experts if is_moe else 0,
                    moe_capacity_factor=self.moe_capacity_factor,
                    ep_axis=self.ep_axis if is_moe else None,
                    ep_shards=self.ep_shards if is_moe else 1,
                )(x)
        x = nn.LayerNorm()(x)
        if self.pool == "cls":
            pooled = x[:, 0]
        else:
            pooled = jnp.mean(x, axis=1)
            if self.seq_axis is not None:
                # Tokens are split over the axis: the global mean is the
                # mean of per-shard means (equal block sizes).
                pooled = lax.pmean(pooled, self.seq_axis)
        return nn.Dense(self.num_classes)(pooled)
