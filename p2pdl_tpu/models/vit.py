"""ViT-Tiny for the 1024-peer secure-aggregation benchmark config.

Beyond the reference's model zoo; required by the BASELINE.json ViT-Tiny
config. Standard ViT-Tiny geometry (dim 192, depth 12, 3 heads) with a 4x4
patch stem sized for 32x32 inputs. Attention is factored through
``p2pdl_tpu.ops.attention`` so the same blocks can run single-device or
sequence-parallel (ring attention) over a mesh axis.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from p2pdl_tpu.ops.attention import MultiHeadAttention


class TransformerBlock(nn.Module):
    dim: int
    heads: int
    mlp_ratio: int = 4
    attn_impl: str = "dense"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        y = nn.LayerNorm()(x)
        x = x + MultiHeadAttention(self.dim, self.heads, impl=self.attn_impl)(y)
        y = nn.LayerNorm()(x)
        y = nn.Dense(self.dim * self.mlp_ratio)(y)
        y = nn.gelu(y)
        y = nn.Dense(self.dim)(y)
        return x + y


class ViTTiny(nn.Module):
    patch: int = 4
    dim: int = 192
    depth: int = 12
    heads: int = 3
    num_classes: int = 10
    attn_impl: str = "dense"  # "flash" fuses attention via Pallas on TPU

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b = x.shape[0]
        x = nn.Conv(self.dim, (self.patch, self.patch), strides=(self.patch, self.patch))(x)
        x = x.reshape(b, -1, self.dim)  # [B, tokens, dim]
        cls = self.param("cls", nn.initializers.zeros, (1, 1, self.dim))
        x = jnp.concatenate([jnp.broadcast_to(cls, (b, 1, self.dim)), x], axis=1)
        x = x + self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, x.shape[1], self.dim)
        )
        for _ in range(self.depth):
            x = TransformerBlock(self.dim, self.heads, attn_impl=self.attn_impl)(x)
        x = nn.LayerNorm()(x)
        return nn.Dense(self.num_classes)(x[:, 0])
