"""Small conv net.

Capability parity with reference ``models/model.py:17-33`` (two 3x3 conv +
ReLU + 2x2 maxpool stages, 32 then 64 channels, then 512-unit head). Unlike
the reference — whose flatten is hard-wired to 32x32x3 inputs and silently
breaks on MNIST — this flattens whatever spatial extent it is given, so one
module serves both MNIST and CIFAR-10.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class SimpleCNN(nn.Module):
    channels: tuple[int, int] = (32, 64)
    hidden: int = 512
    num_classes: int = 10

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for ch in self.channels:
            x = nn.Conv(ch, kernel_size=(3, 3), padding="SAME")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(self.num_classes)(x)
