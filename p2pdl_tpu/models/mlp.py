"""MLP classifier.

Capability parity with reference ``models/model.py:3-15`` (784 -> 512 -> 256
-> 10, ReLU). Compute runs in a configurable dtype (bfloat16 by default via
the train step) so the matmuls tile onto the MXU; params stay float32.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    features: Sequence[int] = (512, 256)
    num_classes: int = 10

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.reshape((x.shape[0], -1))
        for f in self.features:
            x = nn.Dense(f)(x)
            x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)
