"""CharGPT: a small causal (decoder-only) transformer LM for next-char
prediction on the shakespeare task.

The CAUSAL training counterpart to the CharLSTM (the reference has no
sequence model of any kind — its model zoo is MLP + SimpleCNN,
``/root/reference/models/models.py``; both sequence families here are
beyond-reference): token + learned position embeddings, pre-LN
transformer blocks with causally-masked attention (the same
``MultiHeadAttention`` the ViT uses, ``causal=True`` — dense SDPA or the
fused Pallas flash kernels, whose causal path otherwise only ran in the
attention microbench), and a tied-free vocab head. Logits are ``[B, T,
vocab]``; the loss/eval plumbing already handles sequence outputs (the
CharLSTM path).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from p2pdl_tpu.models.vit import TransformerBlock


class CharGPT(nn.Module):
    vocab_size: int
    dim: int = 192
    depth: int = 4
    heads: int = 3
    max_len: int = 512
    attn_impl: str = "dense"  # "dense" | "flash" (fused Pallas kernels)

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:  # [B, T] int tokens
        t = x.shape[-1]
        if t > self.max_len:
            raise ValueError(f"sequence length {t} exceeds max_len {self.max_len}")
        h = nn.Embed(self.vocab_size, self.dim)(x)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (self.max_len, self.dim)
        )
        h = h + pos[None, :t].astype(h.dtype)
        for _ in range(self.depth):
            h = TransformerBlock(
                self.dim, self.heads, causal=True, attn_impl=self.attn_impl
            )(h)
        h = nn.LayerNorm()(h)
        return nn.Dense(self.vocab_size)(h)
