"""Command-line entry point.

The reference lists a CLI as TODO (reference ``README.md:11``); its only
entry is ``python main.py`` + curl. Here every config knob is a flag:

    python -m p2pdl_tpu.cli --num-peers 8 --aggregator krum --rounds 5
    python -m p2pdl_tpu.cli serve --port 5000      # HTTP orchestrator
    python -m p2pdl_tpu.cli chaos --brb --fault-plan crash_drop_partition
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from p2pdl_tpu.config import AGGREGATORS, DATASETS, MODELS, PARTITIONS, Config


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="p2pdl_tpu", description="TPU-native peer-to-peer decentralized learning"
    )
    p.add_argument(
        "mode", nargs="?", default="run",
        choices=[
            "run", "serve", "serve-metrics", "bench", "report", "chaos",
            "lint", "perf-diff", "audit", "tower", "divergence",
        ],
    )
    p.add_argument("--num-peers", type=int, default=8)
    p.add_argument("--trainers-per-round", type=int, default=3)
    p.add_argument("--byzantine-f", type=int, default=1)
    p.add_argument("--rounds", type=int, default=5)
    p.add_argument("--local-epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--samples-per-peer", type=int, default=512)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--momentum", type=float, default=0.0)
    p.add_argument(
        "--optimizer",
        choices=["sgd", "adam"],
        default="sgd",
        help="local optimizer (per-peer state persists across rounds)",
    )
    p.add_argument(
        "--weight-decay",
        type=float,
        default=0.0,
        help="L2 into the sgd update / decoupled AdamW for adam; 0=off",
    )
    p.add_argument("--server-lr", type=float, default=0.1)
    p.add_argument(
        "--fedprox-mu", type=float, default=0.0,
        help="FedProx proximal coefficient (0 = plain FedAvg local objective)",
    )
    p.add_argument(
        "--compress", choices=("none", "topk", "qsgd"), default="none",
        help="update compression: topk = EF sparsification (ship only the "
        "largest compress-ratio fraction of each delta; unsent mass "
        "carries in a per-peer residual), qsgd = unbiased stochastic "
        "quantization to qsgd-levels levels (no residual state)",
    )
    p.add_argument(
        "--compress-ratio", type=float, default=0.1,
        help="fraction of coordinates kept per shipped update, in (0, 1] "
        "(only with --compress topk)",
    )
    p.add_argument(
        "--qsgd-levels", type=int, default=256,
        help="quantization levels for --compress qsgd (256 ~ 8-bit)",
    )
    p.add_argument(
        "--delta-compression", choices=("none", "int8", "bf16", "topk"),
        default="none",
        help="compressed-delta WIRE format for the BRB trust pipeline "
        "(requires --brb): the pack/digest/ship bytes are int8-quantized, "
        "bf16-truncated, or magnitude top-k sparsified (fraction from "
        "--compress-ratio), and aggregation consumes the codec roundtrip — "
        "digests are computed over the compressed bytes",
    )
    p.add_argument(
        "--selection", choices=("uniform", "random", "power_of_choice"),
        default="uniform",
        help="trainer sampler: uniform (reference semantics; 'random' is "
        "an alias) or power_of_choice (Cho et al. 2020 — poc-candidates "
        "uniform candidates, keep the highest-loss trainers)",
    )
    p.add_argument(
        "--poc-candidates", type=int, default=0,
        help="power_of_choice candidate pool size d (0 = auto: "
        "min(2 x trainers, peers))",
    )
    p.add_argument(
        "--hetero-min-epochs", type=int, default=0,
        help="straggler simulation: each peer runs tau_i ~ U[this, "
        "local-epochs] local epochs per round (0 = homogeneous)",
    )
    p.add_argument(
        "--fednova", action="store_true",
        help="FedNova normalized averaging: trainer deltas divide by their "
        "local step count a_i, the mean rescales by tau_eff = mean(a_i) — "
        "objective-consistent aggregation under heterogeneous local work",
    )
    p.add_argument(
        "--scaffold", action="store_true",
        help="SCAFFOLD control variates (per-peer c_i + server c correct "
        "client drift at every local step; plain-SGD fedavg only)",
    )
    p.add_argument(
        "--dp-clip", type=float, default=0.0,
        help="DP-FedAvg per-trainer L2 clip bound (0 = off)",
    )
    p.add_argument(
        "--dp-noise-multiplier", type=float, default=0.0,
        help="Gaussian noise multiplier z (std = z * clip / trainers on the "
        "mean); per-round JSONL records carry the cumulative epsilon",
    )
    p.add_argument(
        "--dp-delta", type=float, default=1e-5,
        help="DP failure probability for the epsilon accounting",
    )
    p.add_argument(
        "--server-momentum", type=float, default=0.0,
        help="FedAvgM server-momentum decay (0 = reference semantics; "
        "non-IID convergence aid — for the Karimireddy momentum+clip "
        "Byzantine defense use local --momentum with --aggregator "
        "centered_clip)",
    )
    p.add_argument(
        "--server-opt", choices=("sgd", "adam", "yogi"), default="sgd",
        help="FedOpt server optimizer over the aggregated delta (sgd = "
        "reference semantics; adam = FedAdam; yogi = FedYogi)",
    )
    p.add_argument("--server-beta1", type=float, default=0.9)
    p.add_argument("--server-beta2", type=float, default=0.99)
    p.add_argument("--server-eps", type=float, default=1e-3)
    p.add_argument("--model", choices=MODELS, default="mlp")
    p.add_argument("--dataset", choices=DATASETS, default="mnist")
    p.add_argument("--partition", choices=PARTITIONS, default="iid")
    p.add_argument("--dirichlet-alpha", type=float, default=0.5)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--aggregator", choices=AGGREGATORS, default="fedavg")
    p.add_argument(
        "--gossip-graph",
        choices=["ring", "exponential"],
        default="ring",
        help="gossip mixing graph: static ±1 ring or round-cycled ±2^k "
        "exponential strides (O(log P) consensus)",
    )
    p.add_argument("--trimmed-mean-beta", type=float, default=0.1)
    p.add_argument("--multi-krum-m", type=int, default=0)
    p.add_argument(
        "--secure-agg-neighbors",
        type=int,
        default=0,
        help="secure_fedavg mask graph: 0 = all trainer pairs (Bonawitz), "
        "k = k-regular ring graph (Bell et al.; scales to 1024+ trainers)",
    )
    p.add_argument(
        "--secure-agg-keys",
        choices=("ecdh", "shared"),
        default="ecdh",
        help="secure_fedavg mask PRF keys: ecdh = pairwise ECDH(P-256)+HKDF "
        "seeds, Shamir-recoverable on dropout; shared = legacy shared "
        "experiment key (A/B benchmarking only)",
    )
    p.add_argument(
        "--secure-agg-rekey",
        choices=("never", "round"),
        default="never",
        help="key freshness: never = per-experiment keyring (gated-out peers "
        "rotated after recovery); round = fresh ECDH keys + Shamir shares "
        "every round (full Bonawitz per-execution semantics; BRB-gated "
        "secure_fedavg; <= 256 peers with the full mask graph, unlimited "
        "with --secure-agg-neighbors k)",
    )
    p.add_argument(
        "--peer-chunk",
        type=int,
        default=0,
        help="stream the vmapped peer stack through chunks of this size "
        "(O(chunk x model) transient HBM — fits 1024 ViT peers on one "
        "chip); 0 = full vmap",
    )
    p.add_argument(
        "--robust-impl",
        choices=["blockwise", "gathered"],
        default="blockwise",
        help="robust-reducer strategy: blockwise streams O(peers x block) "
        "transients; gathered all-gathers the full update stack",
    )
    p.add_argument(
        "--pallas-aggregators",
        action="store_true",
        help="route the distance-based robust reducers (krum family, "
        "bulyan, centered_clip, geometric_median) through the fused Pallas "
        "distance/Gram kernels; falls back to the XLA path off-TPU and on "
        "JAX builds running the compat shims, so it is safe to enable "
        "anywhere",
    )
    p.add_argument("--brb", action="store_true", help="enable the BRB trust plane")
    p.add_argument(
        "--brb-committee",
        type=int,
        default=0,
        help="scope the Bracha quorum to a deterministic m-member committee "
        "(O(m^2) control messages per broadcast instead of O(P^2) — the "
        "trust plane at 1024+ peers); 0 = every peer votes",
    )
    p.add_argument("--round-timeout-s", type=float, default=30.0)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--compute-dtype", default="bfloat16")
    p.add_argument("--param-dtype", default="float32")
    p.add_argument("--remat", action="store_true")
    p.add_argument(
        "--attn-impl",
        choices=["dense", "flash"],
        default="dense",
        help="attention implementation for transformer models "
        "(flash = fused Pallas TPU kernels)",
    )
    p.add_argument(
        "--seq-shards",
        type=int,
        default=1,
        help="sequence/context parallelism: shard each peer's token "
        "sequence over a mesh axis of this size (ring attention); 1=off",
    )
    p.add_argument(
        "--seq-impl",
        choices=["ring", "ulysses"],
        default="ring",
        help="sequence-parallel attention: ring (blockwise k/v rotation) or "
        "ulysses (all-to-all heads<->sequence re-shard; needs "
        "--seq-shards | --vit-heads)",
    )
    p.add_argument(
        "--vit-pool",
        choices=["cls", "mean"],
        default="cls",
        help="ViT head pooling (mean required under --seq-shards > 1)",
    )
    p.add_argument(
        "--vit-heads",
        type=int,
        default=3,
        help="ViT attention head count (4 divides evenly for --tp-shards "
        "on power-of-two meshes)",
    )
    p.add_argument(
        "--vit-depth",
        type=int,
        default=12,
        help="ViT trunk depth (12 = standard ViT-Tiny)",
    )
    p.add_argument(
        "--tp-shards",
        type=int,
        default=1,
        help="tensor parallelism: shard attention heads + MLP hidden over "
        "a mesh axis of this size (megatron column/row); 1=off",
    )
    p.add_argument(
        "--moe-experts",
        type=int,
        default=0,
        help="mixture-of-experts: swap every --moe-every-th ViT block's MLP "
        "for a top-1 mixture of this many experts; 0=dense MLPs",
    )
    p.add_argument("--moe-every", type=int, default=2)
    p.add_argument(
        "--moe-capacity-factor",
        type=float,
        default=2.0,
        help="per-expert slots = factor * tokens / experts (tokens past "
        "capacity drop; >= experts makes dropping impossible)",
    )
    p.add_argument(
        "--ep-shards",
        type=int,
        default=1,
        help="expert parallelism: shard the MoE experts over a mesh axis of "
        "this size (tokens routed by all_to_all); 1=off",
    )
    p.add_argument(
        "--pp-shards",
        type=int,
        default=1,
        help="pipeline parallelism: shard the ViT trunk depth over a mesh "
        "axis of this size (microbatch ppermute schedule); 1=off",
    )
    p.add_argument(
        "--pp-microbatches",
        type=int,
        default=0,
        help="microbatches per batch for the pipeline schedule; 0=pp-shards",
    )
    p.add_argument(
        "--vit-scan-blocks",
        action="store_true",
        help="store the ViT trunk as one nn.scan stack (faster compile; "
        "the pytree-identical dense twin of a --pp-shards run)",
    )
    p.add_argument("--attack", default="none", help="Byzantine attack for injected peers")
    p.add_argument("--byz-ids", default="", help="comma-separated adversarial peer ids")
    p.add_argument(
        "--log-path", default=None,
        help="JSONL metrics output (run mode) / input (report mode)",
    )
    p.add_argument(
        "--trace-events", default=None, metavar="PATH",
        help="capture host control-plane spans and write Chrome trace-event "
        "JSON here (load in Perfetto / chrome://tracing)",
    )
    p.add_argument(
        "--telemetry-path", default=None, metavar="PATH",
        help="write the telemetry registry snapshot (counters/gauges/"
        "histograms JSON) here at exit; report mode reads it back",
    )
    p.add_argument(
        "--json", action="store_true", dest="lint_json",
        help="lint mode: emit findings as a JSON document instead of text; "
        "report mode: emit the digest as machine-readable JSON instead of "
        "Markdown (same sections, same numbers)",
    )
    p.add_argument(
        "--flight-path", default=None, metavar="PATH",
        help="flight-recorder JSONL: run/chaos modes enable the recorder "
        "and dump its ring here at exit; report mode folds the dump into "
        "a '## Flight recorder' section; serve-metrics loads it so "
        "/flight serves a recorded run",
    )
    p.add_argument(
        "--inputs", action="append", default=None, metavar="SRC",
        help="audit mode: an event stream to merge — a flight JSONL dump "
        "path or a live server base URL (http://host:port, its /flight "
        "endpoint is scraped); repeatable, one per peer process. "
        "tower mode: a live endpoint base URL to tail; repeatable. "
        "divergence mode: exactly two recorded streams (flight JSONL "
        "dumps or RoundRecord JSONLs) to align and diff",
    )
    p.add_argument(
        "--interval", type=float, default=0.5, metavar="S",
        help="tower mode: poll interval in seconds between endpoint sweeps",
    )
    p.add_argument(
        "--once", action="store_true",
        help="tower mode: tail every endpoint to exhaustion, finalize the "
        "merge, print one report, and exit (replay/CI mode) instead of "
        "polling until interrupted",
    )
    p.add_argument(
        "--archive", default=None, metavar="PATH",
        help="tower mode: append every merged event (causal order, "
        "time-stripped JSONL) here, sealed by a trailer line carrying the "
        "rolling causal digest",
    )
    p.add_argument(
        "--kind", default=None, metavar="K[,K]",
        help="tower mode: server-side /flight?kind= filter — tail only "
        "these event kinds (note: the causal digest then covers only the "
        "filtered events)",
    )
    p.add_argument(
        "--max-polls", type=int, default=64, metavar="N",
        help="tower --once: upper bound on poll sweeps before finalizing "
        "(a flapping endpoint cannot wedge the exit)",
    )
    p.add_argument(
        "--registered-peers", type=int, default=None, metavar="N",
        help="audit mode: size of the registered-key universe (voters must "
        "be in range(N)); default: infer the peer universe from the "
        "streams themselves",
    )
    p.add_argument(
        "--audit", action="store_true",
        help="run/chaos modes: run the protocol conformance auditor live "
        "over the flight stream each round (forces the recorder on); "
        "violations surface as audit_violation flight anomalies and "
        "audit.violations counters",
    )
    p.add_argument(
        "--write-baseline", action="store_true",
        help="lint mode: rewrite the baseline file to cover every current "
        "finding (existing reasons preserved; new entries get a TODO "
        "reason a human must replace)",
    )
    p.add_argument(
        "--baseline", default=None, metavar="PATH",
        help="lint mode: baseline file (default: the committed "
        "p2pdl_tpu/analysis/baseline.json)",
    )
    p.add_argument(
        "--lint-root", default=None, metavar="PATH",
        help="lint mode: directory tree to lint (default: the installed "
        "p2pdl_tpu package)",
    )
    p.add_argument(
        "--only", default=None, metavar="RULE[,RULE]",
        help="lint mode: run only the named rule(s); names may be fnmatch "
        "globs (e.g. async-*) selecting a whole family. Baseline entries "
        "for other rules are ignored rather than reported stale. Unknown "
        "names or patterns matching nothing exit 2",
    )
    p.add_argument(
        "--changed", action="store_true",
        help="lint mode: lint only .py files changed vs HEAD (plus "
        "untracked) under the lint root; program rules see just that "
        "subset, so cross-file attribution degrades conservatively",
    )
    p.add_argument(
        "--sarif", action="store_true",
        help="lint mode: emit new findings as a SARIF 2.1.0 document "
        "instead of text/JSON (for code-review tooling)",
    )
    p.add_argument(
        "--perf", action="store_true",
        help="enable the cost-model plane: AOT-compile each program once "
        "more to extract XLA FLOPs/HBM-bytes/peak-memory and publish the "
        "driver.mfu / driver.model_flops_per_sec gauges (one extra compile "
        "per program; the recompile sentinel and phase timers are always on)",
    )
    p.add_argument(
        "--old", default=None, metavar="PATH",
        help="perf-diff mode: baseline perf/bench JSON (default: the "
        "second-newest BENCH_r*.json in the current directory)",
    )
    p.add_argument(
        "--new", default=None, metavar="PATH", dest="new_path",
        help="perf-diff mode: candidate perf/bench JSON (default: the "
        "newest BENCH_r*.json in the current directory)",
    )
    p.add_argument(
        "--threshold", action="append", default=None, metavar="[METRIC=]FRAC",
        help="perf-diff mode: allowed relative regression before the exit "
        "code goes nonzero — a bare fraction sets the default (0.05), "
        "METRIC=FRAC overrides one metric (repeatable)",
    )
    p.add_argument("--checkpoint-dir", default=None, help="checkpoint/resume directory")
    p.add_argument("--checkpoint-every", type=int, default=1, help="rounds between checkpoints")
    p.add_argument("--profile-dir", default=None, help="jax.profiler trace output dir")
    p.add_argument(
        "--fused-rounds",
        type=int,
        default=0,
        help="high-throughput mode: scan N rounds per device dispatch "
        "(requires --brb off); 0 = one round per dispatch",
    )
    p.add_argument(
        "--failure-cooldown",
        type=int,
        default=0,
        help="rounds a BRB-failed peer is excluded from trainer sampling (0=off)",
    )
    p.add_argument(
        "--fault-plan",
        default=None,
        metavar="PLAN",
        help="chaos plane: a named scenario (baseline, lossy, "
        "partition_heal, crash_drop_partition, crash_churn), inline "
        "FaultPlan JSON, or a path to a FaultPlan JSON file; chaos mode "
        "defaults to crash_drop_partition",
    )
    p.add_argument(
        "--suspicion-threshold",
        type=int,
        default=2,
        help="consecutive missed heartbeats before the failure detector "
        "suspects a peer (excluded from sampling and BRB quorums)",
    )
    p.add_argument(
        "--no-control-batching",
        action="store_true",
        help="use the v1 per-message BRB control framing instead of the "
        "coalesced signed batch frames (wire v2); protocol outcomes are "
        "identical, only message/signature counts differ",
    )
    p.add_argument(
        "--no-pipeline",
        action="store_true",
        help="disable the pipelined round loop (eval/loss readbacks fetched "
        "up to --pipeline-depth rounds late); the record stream is "
        "bit-identical either way minus duration_s",
    )
    p.add_argument(
        "--pipeline-depth",
        type=int,
        default=2,
        help="bounded in-flight round window for the pipelined loop "
        "(default 2); readbacks resolve up to k rounds late, records stay "
        "bit-identical at every depth — watch driver.overlap_efficiency "
        "to see whether a deeper window still buys anything",
    )
    p.add_argument(
        "--autotune",
        action="store_true",
        help="hill-climb the overlap knob online from measured round "
        "durations (pipeline_depth for the round loop, rounds_per_call "
        "for --fused-rounds); deterministic given the record stream, "
        "recompile-sentinel quiet, chosen value lands in the perf summary",
    )
    p.add_argument("--port", type=int, default=5000, help="HTTP port (serve mode)")
    p.add_argument("--n-devices", type=int, default=None, help="mesh size (default: all)")
    p.add_argument(
        "--platform",
        default=None,
        choices=["cpu", "tpu"],
        help="force the JAX platform; needed because an environment may pin "
        "JAX to a TPU backend at interpreter start, in which case "
        "JAX_PLATFORMS=cpu in the env arrives too late — this flag applies "
        "jax.config.update before any device is touched",
    )
    return p


def config_from_args(args: argparse.Namespace) -> Config:
    return Config(
        num_peers=args.num_peers,
        trainers_per_round=args.trainers_per_round,
        byzantine_f=args.byzantine_f,
        rounds=args.rounds,
        local_epochs=args.local_epochs,
        batch_size=args.batch_size,
        samples_per_peer=args.samples_per_peer,
        lr=args.lr,
        momentum=args.momentum,
        optimizer=args.optimizer,
        weight_decay=args.weight_decay,
        server_lr=args.server_lr,
        server_momentum=args.server_momentum,
        server_opt=args.server_opt,
        server_beta1=args.server_beta1,
        server_beta2=args.server_beta2,
        server_eps=args.server_eps,
        fedprox_mu=args.fedprox_mu,
        scaffold=args.scaffold,
        selection=args.selection,
        poc_candidates=args.poc_candidates,
        hetero_min_epochs=args.hetero_min_epochs,
        fednova=args.fednova,
        compress=args.compress,
        compress_ratio=args.compress_ratio,
        delta_compression=args.delta_compression,
        qsgd_levels=args.qsgd_levels,
        dp_clip=args.dp_clip,
        dp_noise_multiplier=args.dp_noise_multiplier,
        dp_delta=args.dp_delta,
        model=args.model,
        dataset=args.dataset,
        partition=args.partition,
        dirichlet_alpha=args.dirichlet_alpha,
        seq_len=args.seq_len,
        aggregator=args.aggregator,
        gossip_graph=args.gossip_graph,
        trimmed_mean_beta=args.trimmed_mean_beta,
        multi_krum_m=args.multi_krum_m,
        robust_impl=args.robust_impl,
        pallas_aggregators=args.pallas_aggregators,
        secure_agg_neighbors=args.secure_agg_neighbors,
        secure_agg_keys=args.secure_agg_keys,
        secure_agg_rekey=args.secure_agg_rekey,
        peer_chunk=args.peer_chunk,
        brb_enabled=args.brb,
        brb_committee=args.brb_committee,
        round_timeout_s=args.round_timeout_s,
        suspicion_threshold=args.suspicion_threshold,
        control_batching=not args.no_control_batching,
        seed=args.seed,
        compute_dtype=args.compute_dtype,
        param_dtype=args.param_dtype,
        remat=args.remat,
        attn_impl=args.attn_impl,
        seq_shards=args.seq_shards,
        seq_impl=args.seq_impl,
        vit_pool=args.vit_pool,
        vit_heads=args.vit_heads,
        vit_depth=args.vit_depth,
        tp_shards=args.tp_shards,
        moe_experts=args.moe_experts,
        moe_every=args.moe_every,
        moe_capacity_factor=args.moe_capacity_factor,
        ep_shards=args.ep_shards,
        pp_shards=args.pp_shards,
        pp_microbatches=args.pp_microbatches,
        vit_scan_blocks=args.vit_scan_blocks,
    )


def _warn(msg: str) -> None:
    """JSON warning on stderr — stdout stays a clean JSONL record stream."""
    print(json.dumps({"warning": msg}), file=sys.stderr)


def _md_table(headers: list[str], rows: list[list[str]]) -> list[str]:
    out = ["| " + " | ".join(headers) + " |"]
    out.append("|" + "|".join(" --- " for _ in headers) + "|")
    for row in rows:
        out.append("| " + " | ".join(row) + " |")
    return out


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def flight_summary_from_events(events: list[dict]) -> dict:
    """Summarize a dumped flight JSONL (kind mix + anomaly counts) — the
    offline twin of ``FlightRecorder.summary()`` for report mode."""
    kinds: dict[str, int] = {}
    anomalies: dict[str, int] = {}
    for ev in events:
        kind = ev.get("kind", "?")
        kinds[kind] = kinds.get(kind, 0) + 1
        if ev.get("anomaly"):
            anomalies[kind] = anomalies.get(kind, 0) + 1
    return {
        "events": len(events),
        "kinds": dict(sorted(kinds.items())),
        "anomaly_count": sum(anomalies.values()),
        "anomalies_by_kind": dict(sorted(anomalies.items())),
    }


# ---- perf-diff: offline regression gate over perf/bench JSON ---------------
#
# Pure host path (stdlib json only — no jax), so the gate runs in CI or on a
# laptop against committed BENCH_r*.json history or two `--perf` run outputs.

# Substring → direction. First match wins; names matching neither direction
# are carried as informational rows that can never fail the gate.
_HIGHER_BETTER = (
    "per_sec", "mfu", "efficiency", "flops_per_sec", "_acc", "speedup",
    "compression_ratio",
)
_LOWER_BETTER = (
    "latency", "recompile", "loss", "bytes", "_memory", "duration", "_s",
)
# Wall-clock-free or meaningless-to-compare counters (suffix match on the
# final path component). The autotuner outputs (chosen knob values, retune
# counts, settle flag) are measured optima / controller bookkeeping, not
# quality metrics — a different chosen depth on different hardware is the
# tuner WORKING, so they must never fail the gate.
_DIFF_SKIP = (
    "count", "rounds", "expected", "monitored", "available", "n", "rc",
    "chosen_pipeline_depth", "chosen_rounds_per_call", "retunes", "settled",
)

# Built-in per-metric default thresholds (matched on the leaf path
# component) for ratio metrics whose noise floor differs from the 5%
# default: mfu divides throughput by a fixed chip peak, so it inherits
# per_sec jitter but is reported to fewer digits; overlap efficiency is a
# quotient of two wall-clock estimates (hidden / tail) and jitters hardest
# of anything the gate sees. The aggregator-microbench kernel timings
# (bench.py's fused-vs-dense block) are steady-state best-of-N but still
# single-kernel wall clocks, so they get a wider band than whole-round
# durations, and the derived speedup ratio compounds both sides' jitter.
# An explicit ``--threshold METRIC=FRAC`` override still wins; a bare
# ``--threshold FRAC`` only moves the generic default.
_LEAF_THRESHOLDS = {
    "mfu": 0.10,
    "efficiency": 0.15,
    "overlap_efficiency": 0.15,
    "dense_s": 0.25,
    "fused_s": 0.25,
    "speedup": 0.20,
    # Compression-block leaves: byte counts are deterministic for a given
    # layout, so any growth at all is a real wire regression — keep the
    # band tight. The ratio divides two such counts and inherits the same.
    "bytes_per_round": 0.01,
    "compressed_bytes": 0.01,
    "compression_ratio": 0.01,
}


def metric_direction(name: str) -> str:
    """'up' (bigger is better), 'down' (smaller is better), or 'info'."""
    leaf = name.rsplit(".", 1)[-1]
    if leaf in _DIFF_SKIP or leaf.endswith("hidden_s"):
        # hidden_s is the GOOD half of the overlap split — judged via
        # `efficiency`, not on its own.
        return "info"
    low = name.lower()
    for pat in _HIGHER_BETTER:
        if pat in low:
            return "up"
    for pat in _LOWER_BETTER:
        if pat in low:
            return "down"
    return "info"


def flatten_perf_metrics(doc: object, prefix: str = "") -> dict[str, float]:
    """Flatten a perf/bench JSON document into dotted-path numeric leaves.

    Understands the repo's two shapes natively and degrades to a generic
    recursive flatten for anything else:

    - bench records: ``{"metric": name, "value": v, ...}`` map to
      ``name: v`` (plus numeric siblings as ``name.sibling``); a record
      carrying ``error`` + ``last_good`` means the backend was unreachable
      — its 0.0 headline is a probe artifact, so the last-good record is
      flattened instead.
    - driver history wrappers: ``{"parsed": {...}}`` unwrap to the parsed
      record; run-mode perf output flattens as plain nesting
      (``phases.round.per_sec``, ``overlap.efficiency``, ...).
    """
    out: dict[str, float] = {}
    if isinstance(doc, dict):
        if "parsed" in doc and isinstance(doc["parsed"], dict):
            return flatten_perf_metrics(doc["parsed"], prefix)
        if doc.get("error") and isinstance(doc.get("last_good"), dict):
            return flatten_perf_metrics(doc["last_good"], prefix)
        if isinstance(doc.get("metric"), str) and isinstance(
            doc.get("value"), (int, float)
        ):
            base = (prefix + "." if prefix else "") + doc["metric"]
            out[base] = float(doc["value"])
            for k, v in doc.items():
                if k in ("metric", "value"):
                    continue
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    out[f"{base}.{k}"] = float(v)
            # The fused-vs-dense aggregator microbench rides inside the
            # headline bench record and IS gate material (its leaves carry
            # their own _LEAF_THRESHOLDS bands); other nested blocks (probe
            # forensics, flight samples, last_good provenance) stay out of
            # the diff as before.
            if isinstance(doc.get("aggregators"), dict):
                out.update(
                    flatten_perf_metrics(
                        doc["aggregators"], f"{base}.aggregators"
                    )
                )
            return out
        for k, v in sorted(doc.items()):
            key = f"{prefix}.{k}" if prefix else str(k)
            if isinstance(v, bool):
                continue
            if isinstance(v, (int, float)):
                out[key] = float(v)
            elif isinstance(v, (dict, list)):
                out.update(flatten_perf_metrics(v, key))
    elif isinstance(doc, list):
        for i, v in enumerate(doc):
            out.update(flatten_perf_metrics(v, f"{prefix}[{i}]" if prefix else f"[{i}]"))
    return out


def perf_diff(
    old: dict[str, float],
    new: dict[str, float],
    default_threshold: float = 0.05,
    per_metric: dict[str, float] | None = None,
) -> dict:
    """Compare two flattened metric maps with direction-aware thresholds.

    A metric regresses when it moves in its bad direction by more than its
    threshold, *relatively* (``|delta| / |old|``; an old value of exactly 0
    compares absolutely so a 0 → 0.1s latency still trips). Threshold
    resolution: exact-name ``per_metric`` override, else the built-in
    ``_LEAF_THRESHOLDS`` default for noisy ratio leaves (mfu, overlap
    efficiency), else ``default_threshold``. Metrics present on only one
    side are reported but never fail the gate — perf planes grow sections
    over time and the gate must not punish that.
    """
    per_metric = per_metric or {}
    rows = []
    regressions = 0
    for name in sorted(set(old) | set(new)):
        if name not in old or name not in new:
            rows.append({
                "metric": name, "old": old.get(name), "new": new.get(name),
                "status": "only-old" if name in old else "only-new",
            })
            continue
        o, n = old[name], new[name]
        direction = metric_direction(name)
        delta = n - o
        rel = abs(delta) / abs(o) if o != 0 else (0.0 if delta == 0 else abs(delta))
        threshold = per_metric.get(
            name,
            _LEAF_THRESHOLDS.get(name.rsplit(".", 1)[-1], default_threshold),
        )
        bad = (direction == "up" and delta < 0) or (direction == "down" and delta > 0)
        status = "ok"
        if direction == "info":
            status = "info"
        elif bad and rel > threshold:
            status = "regression"
            regressions += 1
        rows.append({
            "metric": name, "old": o, "new": n, "rel_change": rel if o != 0 else None,
            "direction": direction, "threshold": threshold, "status": status,
        })
    return {"regressions": regressions, "rows": rows}


def _parse_thresholds(specs: list[str] | None) -> tuple[float, dict[str, float]]:
    """``--threshold`` values: bare fraction = new default, METRIC=FRAC =
    one metric's override. Raises ValueError on garbage (usage error)."""
    default = 0.05
    per_metric: dict[str, float] = {}
    for spec in specs or []:
        if "=" in spec:
            name, _, frac = spec.rpartition("=")
            per_metric[name] = float(frac)
        else:
            default = float(spec)
    return default, per_metric


def _latest_bench_history(n: int = 2) -> list[str]:
    import glob

    return sorted(glob.glob("BENCH_r*.json"))[-n:]


def run_perf_diff(args: argparse.Namespace) -> int:
    old_path, new_path = args.old, args.new_path
    if old_path is None and new_path is None:
        hist = _latest_bench_history()
        if len(hist) < 2:
            _warn(
                "perf-diff needs --old/--new, or >= 2 BENCH_r*.json files "
                "in the current directory"
            )
            return 2
        old_path, new_path = hist
    if old_path is None or new_path is None:
        _warn("perf-diff needs both --old and --new (or neither)")
        return 2
    try:
        default_threshold, per_metric = _parse_thresholds(args.threshold)
    except ValueError as e:
        _warn(f"bad --threshold: {e}")
        return 2
    try:
        with open(old_path) as f:
            old_doc = json.load(f)
        with open(new_path) as f:
            new_doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        _warn(f"perf-diff could not load inputs: {e}")
        return 2
    diff = perf_diff(
        flatten_perf_metrics(old_doc), flatten_perf_metrics(new_doc),
        default_threshold, per_metric,
    )
    diff["old"], diff["new"] = old_path, new_path
    if args.lint_json:
        json.dump(diff, sys.stdout, sort_keys=True)
        sys.stdout.write("\n")
    else:
        lines = [f"# perf-diff: {old_path} -> {new_path}", ""]
        rows = [
            [r["metric"], _fmt(r.get("old")), _fmt(r.get("new")),
             _fmt(r.get("rel_change")), r["status"]]
            for r in diff["rows"]
        ]
        lines += _md_table(["metric", "old", "new", "rel", "status"], rows)
        lines += ["", f"regressions: {diff['regressions']}"]
        sys.stdout.write("\n".join(lines) + "\n")
    return 1 if diff["regressions"] else 0


def build_report_data(
    records: list[dict],
    telemetry_snapshot: dict | None = None,
    flight_summary: dict | None = None,
) -> dict:
    """The report's numbers as one JSON-ready dict — the Markdown digest
    and ``report --json`` both render from this, so they can never drift."""
    data: dict = {}
    rounds = [r for r in records if "round" in r]
    if rounds:
        evals = [r for r in rounds if r.get("eval_acc") is not None]
        durations = [r["duration_s"] for r in rounds if r.get("duration_s")]
        # Steady-state throughput excludes the first round (jit compile).
        steady = durations[1:] if len(durations) > 1 else durations
        data["rounds"] = {
            "count": len(rounds),
            "train_loss_first": rounds[0].get("train_loss"),
            "train_loss_last": rounds[-1].get("train_loss"),
            "final_eval_acc": evals[-1]["eval_acc"] if evals else None,
            "best_eval_acc": max(r["eval_acc"] for r in evals) if evals else None,
            "final_eval_loss": evals[-1]["eval_loss"] if evals else None,
            "total_wall_s": sum(durations),
            "first_round_s": durations[0] if durations else None,
            "steady_rounds_per_sec": (
                len(steady) / sum(steady) if steady and sum(steady) > 0 else None
            ),
        }
        brb_rounds = [r for r in rounds if r.get("brb_delivered") is not None]
        if brb_rounds:
            failed: dict[int, int] = {}
            excluded: dict[int, int] = {}
            for r in brb_rounds:
                for p in r.get("brb_failed_peers") or []:
                    failed[p] = failed.get(p, 0) + 1
                for t in r.get("brb_excluded_trainers") or []:
                    excluded[t] = excluded.get(t, 0) + 1
            data["trust_plane"] = {
                "rounds_with_brb": len(brb_rounds),
                "min_peers_delivered": min(r["brb_delivered"] for r in brb_rounds),
                "mean_peers_delivered": (
                    sum(r["brb_delivered"] for r in brb_rounds) / len(brb_rounds)
                ),
                "delivery_failures": {str(p): n for p, n in sorted(failed.items())},
                "gated_trainers": {str(t): n for t, n in sorted(excluded.items())},
                "control_messages": sum(
                    r.get("control_messages") or 0 for r in brb_rounds
                ),
                "control_bytes": sum(r.get("control_bytes") or 0 for r in brb_rounds),
            }
        health = [r["protocol_health"] for r in rounds if r.get("protocol_health")]
        if health:
            margins = [
                h["quorum_margin_min"]
                for h in health
                if h.get("quorum_margin_min") is not None
            ]
            p50s = [
                (h.get("brb_latency_s") or {}).get("p50")
                for h in health
                if (h.get("brb_latency_s") or {}).get("p50") is not None
            ]
            p99s = [
                (h.get("brb_latency_s") or {}).get("p99")
                for h in health
                if (h.get("brb_latency_s") or {}).get("p99") is not None
            ]
            data["protocol_health"] = {
                "rounds_with_health": len(health),
                "quorum_margin_min": min(margins) if margins else None,
                "deliveries_total": sum(h.get("deliveries") or 0 for h in health),
                "anomalies_total": sum(h.get("anomalies") or 0 for h in health),
                "brb_latency_p50_worst_s": max(p50s) if p50s else None,
                "brb_latency_p99_worst_s": max(p99s) if p99s else None,
            }
    # The run appends one {"profile": ..., "perf": ...} record to the JSONL
    # after the round stream; fold the last one into the digest.
    prof_recs = [r for r in records if isinstance(r, dict) and "profile" in r]
    if prof_recs:
        phases = prof_recs[-1].get("profile")
        if phases:
            data["phases"] = phases
        perf = prof_recs[-1].get("perf")
        if perf:
            data["perf"] = perf
    if telemetry_snapshot:
        data["telemetry"] = telemetry_snapshot
        # The cardinality cap folds overflow label sets into __other__ and
        # counts each redirected lookup — surface that as an explicit
        # warning instead of leaving capped series silently folded.
        prefix = "telemetry.series_dropped{metric="
        dropped = {
            k[len(prefix):-1]: v
            for k, v in (telemetry_snapshot.get("counters") or {}).items()
            if k.startswith(prefix) and k.endswith("}")
        }
        if dropped:
            data["warnings"] = [
                f"telemetry cardinality cap hit: {int(n)} lookup(s) on "
                f"'{m}' folded into the __other__ series (per-label "
                "detail lost past the cap)"
                for m, n in sorted(dropped.items())
            ]
    if flight_summary:
        data["flight"] = flight_summary
    return data


def render_report(
    records: list[dict],
    telemetry_snapshot: dict | None = None,
    flight_summary: dict | None = None,
) -> str:
    """Markdown digest of a metrics JSONL + optional telemetry snapshot
    and flight-recorder dump.

    Pure host-side rendering: no jax import, so ``report`` runs anywhere
    the JSONL landed (a laptop, a CI artifact view) without a backend.
    """
    data = build_report_data(records, telemetry_snapshot, flight_summary)
    lines = ["# p2pdl_tpu run report", ""]
    for w in data.get("warnings") or []:
        lines.append(f"**WARNING:** {w}")
    if data.get("warnings"):
        lines.append("")
    rd = data.get("rounds")
    if rd:
        rows = [
            ["rounds", _fmt(rd["count"])],
            ["train loss (first -> last)",
             f"{_fmt(rd['train_loss_first'])} -> {_fmt(rd['train_loss_last'])}"],
            ["final eval acc", _fmt(rd["final_eval_acc"])],
            ["best eval acc", _fmt(rd["best_eval_acc"])],
            ["final eval loss", _fmt(rd["final_eval_loss"])],
            ["total wall time (s)", _fmt(rd["total_wall_s"])],
            ["first round (s, incl. compile)", _fmt(rd["first_round_s"])],
            ["steady rounds/sec", _fmt(rd["steady_rounds_per_sec"])],
        ]
        lines += ["## Rounds", ""] + _md_table(["metric", "value"], rows) + [""]

        tp = data.get("trust_plane")
        if tp:
            rows = [
                ["rounds with BRB", _fmt(tp["rounds_with_brb"])],
                ["min / mean peers delivered",
                 f"{tp['min_peers_delivered']} / {_fmt(tp['mean_peers_delivered'])}"],
                ["peers with delivery failures (id: rounds)",
                 ", ".join(f"{p}: {n}" for p, n in tp["delivery_failures"].items())
                 or "none"],
                ["trainers gated out (id: rounds)",
                 ", ".join(f"{t}: {n}" for t, n in tp["gated_trainers"].items())
                 or "none"],
                ["control messages (total)", _fmt(tp["control_messages"])],
                ["control bytes (total)", _fmt(tp["control_bytes"])],
            ]
            lines += ["## Trust plane (BRB)", ""] + _md_table(["metric", "value"], rows) + [""]

        ph = data.get("protocol_health")
        if ph:
            rows = [
                ["rounds with health summary", _fmt(ph["rounds_with_health"])],
                ["min quorum margin", _fmt(ph["quorum_margin_min"])],
                ["deliveries (total)", _fmt(ph["deliveries_total"])],
                ["recorder anomalies (total)", _fmt(ph["anomalies_total"])],
                ["BRB latency p50 (s, worst round)",
                 _fmt(ph["brb_latency_p50_worst_s"])],
                ["BRB latency p99 (s, worst round)",
                 _fmt(ph["brb_latency_p99_worst_s"])],
            ]
            lines += ["## Protocol health", ""] + _md_table(["metric", "value"], rows) + [""]
    else:
        lines += ["_No round records found._", ""]

    phases = data.get("phases")
    if phases:
        rows = [
            [name, _fmt(s.get("count")), _fmt(s.get("mean_s")),
             _fmt(s.get("p99_s")), _fmt(s.get("per_sec"))]
            for name, s in phases.items()
        ]
        lines += ["## Phase timing", ""] + _md_table(
            ["phase", "count", "mean (s)", "p99 (s)", "per sec"], rows
        ) + [""]

    perf = data.get("perf")
    if perf:
        rows = []
        ov = perf.get("overlap") or {}
        if ov.get("rounds"):
            rows += [
                ["pipelined flushes", _fmt(ov.get("rounds"))],
                ["device tail hidden / exposed (s)",
                 f"{_fmt(ov.get('hidden_s'))} / {_fmt(ov.get('exposed_s'))}"],
                ["overlap efficiency", _fmt(ov.get("efficiency"))],
            ]
        rc = perf.get("recompile") or {}
        rows.append(["recompile anomalies", _fmt(rc.get("recompiles"))])
        progs = rc.get("programs") or {}
        if progs:
            rows.append([
                "compiles per program (actual/expected)",
                ", ".join(
                    f"{n}: {p.get('compiles')}/{p.get('expected')}"
                    for n, p in progs.items()
                ),
            ])
        cm = perf.get("cost_model") or {}
        if cm:
            rows += [
                ["model FLOPs / round (XLA cost model)",
                 _fmt(cm.get("flops_per_round"))],
                ["HBM bytes / round", _fmt(cm.get("hbm_bytes_per_round"))],
                ["device peak memory (bytes)",
                 _fmt(cm.get("device_peak_memory_bytes"))],
            ]
        lines += ["## Performance attribution", ""] + _md_table(
            ["metric", "value"], rows
        ) + [""]

    fl = data.get("flight")
    if fl:
        rows = [
            ["events", _fmt(fl.get("events"))],
            ["event kinds",
             ", ".join(f"{k}: {n}" for k, n in (fl.get("kinds") or {}).items())
             or "none"],
            ["anomalies", _fmt(fl.get("anomaly_count"))],
            ["anomalies by kind",
             ", ".join(
                 f"{k}: {n}" for k, n in (fl.get("anomalies_by_kind") or {}).items()
             ) or "none"],
        ]
        lines += ["## Flight recorder", ""] + _md_table(["metric", "value"], rows) + [""]

    if telemetry_snapshot:
        counters = telemetry_snapshot.get("counters") or {}
        gauges = telemetry_snapshot.get("gauges") or {}
        hists = telemetry_snapshot.get("histograms") or {}
        if counters:
            lines += ["## Telemetry counters", ""] + _md_table(
                ["series", "count"],
                [[k, _fmt(v)] for k, v in counters.items()],
            ) + [""]
        if gauges:
            lines += ["## Telemetry gauges", ""] + _md_table(
                ["series", "value"],
                [[k, _fmt(v)] for k, v in gauges.items()],
            ) + [""]
        if hists:
            lines += ["## Telemetry histograms", ""] + _md_table(
                ["series", "count", "mean", "p50", "p99", "max"],
                [
                    [k, _fmt(h.get("count")), _fmt(h.get("mean")),
                     _fmt(h.get("p50")), _fmt(h.get("p99")), _fmt(h.get("max"))]
                    for k, h in hists.items()
                ],
            ) + [""]
    return "\n".join(lines).rstrip() + "\n"


def _load_flight_events(path: str) -> list[dict]:
    """Load a flight-recorder JSONL dump (one event object per line)."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return events


def run_audit(args: argparse.Namespace) -> int:
    """Offline protocol conformance audit: merge N event streams (flight
    JSONL dumps and/or live ``/flight`` endpoints) by causal order, run the
    ``ProtocolAuditor`` over the merged stream, and report the cross-peer
    causal determinism digest. Exit 1 on any violated invariant, 2 on
    usage/load errors — pure host path, no jax import."""
    from p2pdl_tpu.protocol.audit import (
        ProtocolAuditor,
        causal_digest,
        merge_streams,
    )

    inputs = list(args.inputs or [])
    if args.flight_path:
        inputs.append(args.flight_path)
    if not inputs:
        _warn(
            "audit mode needs --inputs (flight JSONL path or "
            "http://host:port base URL; repeatable)"
        )
        return 2
    streams = []
    for src in inputs:
        try:
            if src.startswith(("http://", "https://")):
                from urllib.request import urlopen

                with urlopen(src.rstrip("/") + "/flight", timeout=10) as resp:
                    payload = json.load(resp)
                streams.append(payload.get("events") or [])
            else:
                streams.append(_load_flight_events(src))
        except (OSError, ValueError) as e:
            _warn(f"audit could not load {src}: {e}")
            return 2
    merged = merge_streams(streams)
    auditor = ProtocolAuditor(
        registered=(
            range(args.registered_peers)
            if args.registered_peers is not None
            else None
        )
    )
    violations = auditor.audit(merged)
    digest = causal_digest(merged)
    out = {
        "inputs": inputs,
        "events": len(merged),
        "causal_digest": digest,
        "summary": auditor.summary(),
        "violations": [v.to_dict() for v in violations],
    }
    if args.lint_json:
        json.dump(out, sys.stdout, sort_keys=True)
        sys.stdout.write("\n")
    else:
        lines = [
            f"# protocol audit: {len(merged)} events "
            f"from {len(inputs)} stream(s)",
            "",
            f"causal digest: {digest}",
        ]
        if violations:
            lines.append("")
            for v in violations:
                where = f" (round {v.round})" if v.round is not None else ""
                lines.append(f"VIOLATION [{v.invariant}]{where}: {v.detail}")
            lines += ["", f"audit FAILED: {len(violations)} violation(s)"]
        else:
            lines.append("audit clean: all invariants hold")
        sys.stdout.write("\n".join(lines) + "\n")
    return 1 if violations else 0


def run_tower(args: argparse.Namespace) -> int:
    """Cluster control tower: tail N live observability endpoints, merge
    their flight streams causally, audit incrementally, and render the
    cluster-health dashboard. Exit 1 on audit violations, 2 on usage
    errors — pure host path, no jax import."""
    from p2pdl_tpu.runtime.tower import ControlTower

    endpoints = list(args.inputs or [])
    if not endpoints:
        _warn(
            "tower mode needs --inputs (http://host:port endpoint base "
            "URL; repeatable, one per peer process)"
        )
        return 2
    kinds = None
    if args.kind:
        kinds = [k for k in args.kind.split(",") if k]
    try:
        tower = ControlTower(
            endpoints,
            poll_interval=args.interval,
            kinds=kinds,
            registered=(
                range(args.registered_peers)
                if args.registered_peers is not None
                else None
            ),
            archive_path=args.archive,
        )
    except OSError as e:
        _warn(f"tower could not open --archive: {e}")
        return 2

    def emit(snap: dict) -> None:
        if args.lint_json:
            json.dump(snap, sys.stdout, sort_keys=True)
            sys.stdout.write("\n")
        else:
            sys.stdout.write(tower.render_dashboard() + "\n")
        sys.stdout.flush()

    if args.once:
        snap = tower.run_to_exhaustion(max_polls=max(1, args.max_polls))
        emit(snap)
        return 1 if snap["audit"]["violations"] else 0
    try:
        while True:
            emit(tower.poll_once())
            time.sleep(tower.poll_interval)
    except KeyboardInterrupt:
        pass
    snap = tower.finalize()
    emit(snap)
    return 1 if snap["audit"]["violations"] else 0


def run_divergence(args: argparse.Namespace) -> int:
    """First-divergence forensics between two recorded streams: align by
    the canonical causal key, report the first differing event with a
    field-level diff and (for flight streams) the causal blame chain.
    Exit 0 identical, 1 divergent, 2 usage — pure host path, no jax."""
    from p2pdl_tpu.runtime.tower import diverge, load_jsonl

    inputs = list(args.inputs or [])
    if len(inputs) != 2:
        _warn(
            "divergence mode needs exactly two --inputs (flight JSONL "
            "dumps or RoundRecord JSONLs)"
        )
        return 2
    try:
        a_events = load_jsonl(inputs[0])
        b_events = load_jsonl(inputs[1])
    except (OSError, ValueError) as e:
        _warn(f"divergence could not load inputs: {e}")
        return 2
    report = diverge(a_events, b_events)
    report["inputs"] = {"a": inputs[0], "b": inputs[1]}
    if args.lint_json:
        json.dump(report, sys.stdout, sort_keys=True)
        sys.stdout.write("\n")
        return 0 if report["identical"] else 1
    if report["identical"]:
        sys.stdout.write(
            f"streams identical: {report['a_len']} aligned "
            f"{report['kind']} events\n"
        )
        return 0
    lines = [
        f"# divergence: first differing {report['kind']} event at aligned "
        f"index {report['index']} (a: {report['a_len']} events, "
        f"b: {report['b_len']})",
        "",
    ]
    first = report["first_divergent"]
    if "only_in" in first:
        lines.append(
            f"stream {first['only_in']} has extra events from index "
            f"{report['index']}:"
        )
        lines.append(f"  {json.dumps(first[first['only_in']], sort_keys=True)}")
    else:
        ev = first["a"]
        label = ev.get("kind", f"round {ev.get('round')}")
        lines.append(f"first divergent event: {label}")
        for field, d in sorted(first["diff"].items()):
            lines.append(f"  {field}: a={d['a']!r}  b={d['b']!r}")
    chain = report.get("blame_chain") or []
    if chain:
        lines += ["", f"causal blame chain ({len(chain)} link(s), earliest first):"]
        for i, link in enumerate(chain):
            ev = link["a"]
            where = (
                f"{ev.get('kind')} peer={ev.get('peer')} "
                f"lamport={ev.get('lamport')} n={ev.get('n')}"
            )
            fields = ", ".join(sorted(link["diff"])) or "(cause tag only)"
            lines.append(f"  [{i}] {where}: differs in {fields}")
    sys.stdout.write("\n".join(lines) + "\n")
    return 1


def run_report(args: argparse.Namespace) -> int:
    from p2pdl_tpu.utils.metrics import load_results

    if not args.log_path:
        _warn("report mode needs --log-path pointing at a metrics JSONL")
        return 2
    records = load_results(args.log_path)
    snapshot = None
    if args.telemetry_path:
        with open(args.telemetry_path) as f:
            snapshot = json.load(f)
    flight_summary = None
    if args.flight_path:
        flight_summary = flight_summary_from_events(
            _load_flight_events(args.flight_path)
        )
    if args.lint_json:
        # Machine-readable mirror of the Markdown digest: same numbers,
        # same sections, one JSON object.
        json.dump(
            build_report_data(records, snapshot, flight_summary),
            sys.stdout,
            sort_keys=True,
        )
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render_report(records, snapshot, flight_summary))
    return 0


def run_serve_metrics(args: argparse.Namespace) -> int:
    """Standalone exposition server — jax-free: serves either the live
    process registry or a recorded run (--telemetry-path / --flight-path)."""
    from p2pdl_tpu.runtime.server import serve_metrics
    from p2pdl_tpu.utils import flight, telemetry

    snapshot_fn = telemetry.snapshot
    if args.telemetry_path:
        with open(args.telemetry_path) as f:
            snap = json.load(f)
        snapshot_fn = lambda: snap  # noqa: E731 -- frozen snapshot server
    if args.flight_path:
        flight.set_enabled(True)
        rec = flight.recorder()
        for ev in _load_flight_events(args.flight_path):
            ev = dict(ev)
            ev.pop("n", None)
            ev.pop("ts", None)
            kind = ev.pop("kind", "?")
            if ev.pop("anomaly", False):
                rec.anomaly(kind, **ev)
            else:
                rec.record(kind, **ev)
    server = serve_metrics(port=args.port, snapshot_fn=snapshot_fn)
    print(
        json.dumps({"serving": True, "port": server.server_address[1]}),
        flush=True,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.shutdown()
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.mode == "report":
        # Pure host path: no jax/backend init, just JSONL + JSON rendering.
        return run_report(args)
    if args.mode == "serve-metrics":
        # Pure host path: the exposition server never imports jax.
        return run_serve_metrics(args)
    if args.mode == "perf-diff":
        # Pure host path: the regression gate is stdlib-json only.
        return run_perf_diff(args)
    if args.mode == "audit":
        # Pure host path: stream merge + invariant checks, stdlib-json only.
        return run_audit(args)
    if args.mode == "tower":
        # Pure host path: the control tower tails remote processes over
        # HTTP; it must never pay a jax import itself.
        return run_tower(args)
    if args.mode == "divergence":
        # Pure host path: JSONL alignment + diff, stdlib-json only.
        return run_divergence(args)
    if args.mode == "lint":
        # Pure host path: p2plint is stdlib-ast only, no jax/backend init.
        from p2pdl_tpu.analysis import cli_lint

        return cli_lint(
            root=args.lint_root,
            baseline_path=args.baseline,
            json_out=args.lint_json,
            write_baseline=args.write_baseline,
            sarif_out=args.sarif,
            only=args.only,
            changed=args.changed,
        )
    # Every other mode dispatches compiled programs — install the
    # shard_map/pcast aliases if this JAX build needs them (no-op otherwise).
    from p2pdl_tpu.utils import jax_compat

    jax_compat.install()
    if args.platform is not None:
        import jax

        # Backend choice is effectively final once any device has been
        # queried (e.g. a sitecustomize that touches jax at interpreter
        # start): jax_num_cpu_devices raises RuntimeError post-init, while
        # jax_platforms silently no-ops. Handle both — warn and continue on
        # whatever backend exists instead of crashing the CLI.
        try:
            jax.config.update("jax_platforms", args.platform)
            if args.platform == "cpu" and args.n_devices is not None:
                try:
                    jax.config.update("jax_num_cpu_devices", args.n_devices)
                except AttributeError:
                    # Older builds lack the config option; their only knob is
                    # the XLA flag, read from the env at CPU-client init —
                    # still ahead of us as long as no device was queried.
                    import os

                    flags = os.environ.get("XLA_FLAGS", "")
                    if "xla_force_host_platform_device_count" not in flags:
                        os.environ["XLA_FLAGS"] = (
                            flags
                            + f" --xla_force_host_platform_device_count={args.n_devices}"
                        ).strip()
        except RuntimeError as e:
            _warn(f"--n-devices not applied: {e}")
        if jax.default_backend() != args.platform:
            _warn(
                f"--platform {args.platform} not honored; "
                f"running on {jax.default_backend()}"
            )
    if args.n_devices is not None:
        import jax

        if args.n_devices > len(jax.devices()):
            _warn(
                f"--n-devices {args.n_devices} unavailable; "
                f"using all {len(jax.devices())} devices"
            )
            args.n_devices = None
    cfg = config_from_args(args)
    byz_ids = tuple(int(x) for x in args.byz_ids.split(",") if x.strip())

    if args.mode == "serve":
        from p2pdl_tpu.runtime.server import serve

        server = serve(
            cfg, port=args.port, attack=args.attack, byz_ids=byz_ids,
            log_path=args.log_path, n_devices=args.n_devices,
        )
        print(json.dumps({"serving": True, "port": server.server_address[1]}))
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            server.shutdown()
        return 0

    if args.mode == "bench":
        # bench.py lives at the repo root (driver contract), not inside the
        # package — load it by path so the CLI works from any CWD.
        import importlib.util
        import os

        bench_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py"
        )
        spec = importlib.util.spec_from_file_location("bench", bench_path)
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        bench.main()
        return 0

    from p2pdl_tpu.runtime.driver import Experiment
    from p2pdl_tpu.utils import telemetry

    if args.trace_events:
        telemetry.start_tracing()
    # Chaos: `chaos` mode is `run` with a fault plan active (defaulting to
    # the acceptance scenario) plus a survival-summary line at the end;
    # --fault-plan on plain run mode injects faults without the summary
    # framing. Either way the fused fast path is off — fault state advances
    # per round on the host.
    fault_plan = args.fault_plan
    if args.mode == "chaos" and fault_plan is None:
        fault_plan = "crash_drop_partition"
    if args.flight_path:
        from p2pdl_tpu.utils import flight

        flight.set_enabled(True)
    if args.fused_rounds > 0 and cfg.selection == "power_of_choice":
        _warn(
            "power_of_choice needs per-round loss feedback; "
            "ignoring --fused-rounds"
        )
        args.fused_rounds = 0
    exp = Experiment(
        cfg, attack=args.attack, byz_ids=byz_ids,
        log_path=args.log_path, n_devices=args.n_devices,
        checkpoint_dir=args.checkpoint_dir, checkpoint_every=args.checkpoint_every,
        profile_dir=args.profile_dir, failure_cooldown_rounds=args.failure_cooldown,
        fault_plan=fault_plan, pipeline=not args.no_pipeline,
        pipeline_depth=args.pipeline_depth,
        perf=args.perf, audit=args.audit, autotune=args.autotune,
    )
    # Omission-only plans (crashes/drops/partitions) now run fused via the
    # precomputed schedule arrays; only content/ordering faults still need
    # per-round driving (they act on in-flight control messages).
    if (
        args.fused_rounds > 0
        and exp.faults is not None
        and not exp.faults.plan.is_omission_only()
    ):
        _warn(
            "content/ordering faults require per-round driving; "
            "ignoring --fused-rounds"
        )
        args.fused_rounds = 0
    emit = lambda rec: print(json.dumps(rec.to_dict()), flush=True)  # noqa: E731
    with exp.profiler.trace():
        if args.fused_rounds > 0:
            exp.run_fused(rounds_per_call=args.fused_rounds, on_record=emit)
        else:
            exp.run_rounds(on_record=emit)
    exp.save_checkpoint()
    if args.trace_events:
        telemetry.write_trace(args.trace_events)
    if args.telemetry_path:
        with open(args.telemetry_path, "w") as f:
            json.dump(telemetry.snapshot(), f)
    if args.flight_path:
        from p2pdl_tpu.utils import flight

        flight.dump(args.flight_path)
    if exp.faults is not None:
        print(json.dumps({
            "survival": exp.survival_summary(),
            "fault_plan": exp.faults.plan.to_dict(),
        }))
    perf_record = {
        "profile": exp.profiler.summary(),
        "perf": exp.perf_summary(),
    }
    if args.log_path:
        # Trailing perf record in the metrics JSONL: report mode renders
        # it as '## Phase timing' / '## Performance attribution', and
        # perf-diff can gate on two of these files. Round consumers filter
        # on the 'round' key, so the extra record is invisible to them.
        with open(args.log_path, "a") as f:
            f.write(json.dumps(perf_record) + "\n")
    print(json.dumps({**perf_record, "telemetry": telemetry.snapshot()}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
