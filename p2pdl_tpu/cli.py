"""Command-line entry point.

The reference lists a CLI as TODO (reference ``README.md:11``); its only
entry is ``python main.py`` + curl. Here every config knob is a flag:

    python -m p2pdl_tpu.cli --num-peers 8 --aggregator krum --rounds 5
    python -m p2pdl_tpu.cli serve --port 5000      # HTTP orchestrator
"""

from __future__ import annotations

import argparse
import json
import sys

from p2pdl_tpu.config import AGGREGATORS, DATASETS, MODELS, PARTITIONS, Config


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="p2pdl_tpu", description="TPU-native peer-to-peer decentralized learning"
    )
    p.add_argument("mode", nargs="?", default="run", choices=["run", "serve", "bench"])
    p.add_argument("--num-peers", type=int, default=8)
    p.add_argument("--trainers-per-round", type=int, default=3)
    p.add_argument("--byzantine-f", type=int, default=1)
    p.add_argument("--rounds", type=int, default=5)
    p.add_argument("--local-epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--samples-per-peer", type=int, default=512)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--momentum", type=float, default=0.0)
    p.add_argument(
        "--optimizer",
        choices=["sgd", "adam"],
        default="sgd",
        help="local optimizer (per-peer state persists across rounds)",
    )
    p.add_argument(
        "--weight-decay",
        type=float,
        default=0.0,
        help="L2 into the sgd update / decoupled AdamW for adam; 0=off",
    )
    p.add_argument("--server-lr", type=float, default=0.1)
    p.add_argument(
        "--fedprox-mu", type=float, default=0.0,
        help="FedProx proximal coefficient (0 = plain FedAvg local objective)",
    )
    p.add_argument(
        "--compress", choices=("none", "topk", "qsgd"), default="none",
        help="update compression: topk = EF sparsification (ship only the "
        "largest compress-ratio fraction of each delta; unsent mass "
        "carries in a per-peer residual), qsgd = unbiased stochastic "
        "quantization to qsgd-levels levels (no residual state)",
    )
    p.add_argument(
        "--compress-ratio", type=float, default=0.1,
        help="fraction of coordinates kept per shipped update, in (0, 1] "
        "(only with --compress topk)",
    )
    p.add_argument(
        "--qsgd-levels", type=int, default=256,
        help="quantization levels for --compress qsgd (256 ~ 8-bit)",
    )
    p.add_argument(
        "--selection", choices=("uniform", "power_of_choice"), default="uniform",
        help="trainer sampler: uniform (reference semantics) or "
        "power_of_choice (Cho et al. 2020 — poc-candidates uniform "
        "candidates, keep the highest-loss trainers)",
    )
    p.add_argument(
        "--poc-candidates", type=int, default=0,
        help="power_of_choice candidate pool size d (0 = auto: "
        "min(2 x trainers, peers))",
    )
    p.add_argument(
        "--hetero-min-epochs", type=int, default=0,
        help="straggler simulation: each peer runs tau_i ~ U[this, "
        "local-epochs] local epochs per round (0 = homogeneous)",
    )
    p.add_argument(
        "--fednova", action="store_true",
        help="FedNova normalized averaging: trainer deltas divide by their "
        "local step count a_i, the mean rescales by tau_eff = mean(a_i) — "
        "objective-consistent aggregation under heterogeneous local work",
    )
    p.add_argument(
        "--scaffold", action="store_true",
        help="SCAFFOLD control variates (per-peer c_i + server c correct "
        "client drift at every local step; plain-SGD fedavg only)",
    )
    p.add_argument(
        "--dp-clip", type=float, default=0.0,
        help="DP-FedAvg per-trainer L2 clip bound (0 = off)",
    )
    p.add_argument(
        "--dp-noise-multiplier", type=float, default=0.0,
        help="Gaussian noise multiplier z (std = z * clip / trainers on the "
        "mean); per-round JSONL records carry the cumulative epsilon",
    )
    p.add_argument(
        "--dp-delta", type=float, default=1e-5,
        help="DP failure probability for the epsilon accounting",
    )
    p.add_argument(
        "--server-momentum", type=float, default=0.0,
        help="FedAvgM server-momentum decay (0 = reference semantics; "
        "non-IID convergence aid — for the Karimireddy momentum+clip "
        "Byzantine defense use local --momentum with --aggregator "
        "centered_clip)",
    )
    p.add_argument(
        "--server-opt", choices=("sgd", "adam", "yogi"), default="sgd",
        help="FedOpt server optimizer over the aggregated delta (sgd = "
        "reference semantics; adam = FedAdam; yogi = FedYogi)",
    )
    p.add_argument("--server-beta1", type=float, default=0.9)
    p.add_argument("--server-beta2", type=float, default=0.99)
    p.add_argument("--server-eps", type=float, default=1e-3)
    p.add_argument("--model", choices=MODELS, default="mlp")
    p.add_argument("--dataset", choices=DATASETS, default="mnist")
    p.add_argument("--partition", choices=PARTITIONS, default="iid")
    p.add_argument("--dirichlet-alpha", type=float, default=0.5)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--aggregator", choices=AGGREGATORS, default="fedavg")
    p.add_argument(
        "--gossip-graph",
        choices=["ring", "exponential"],
        default="ring",
        help="gossip mixing graph: static ±1 ring or round-cycled ±2^k "
        "exponential strides (O(log P) consensus)",
    )
    p.add_argument("--trimmed-mean-beta", type=float, default=0.1)
    p.add_argument("--multi-krum-m", type=int, default=0)
    p.add_argument(
        "--secure-agg-neighbors",
        type=int,
        default=0,
        help="secure_fedavg mask graph: 0 = all trainer pairs (Bonawitz), "
        "k = k-regular ring graph (Bell et al.; scales to 1024+ trainers)",
    )
    p.add_argument(
        "--secure-agg-keys",
        choices=("ecdh", "shared"),
        default="ecdh",
        help="secure_fedavg mask PRF keys: ecdh = pairwise ECDH(P-256)+HKDF "
        "seeds, Shamir-recoverable on dropout; shared = legacy shared "
        "experiment key (A/B benchmarking only)",
    )
    p.add_argument(
        "--secure-agg-rekey",
        choices=("never", "round"),
        default="never",
        help="key freshness: never = per-experiment keyring (gated-out peers "
        "rotated after recovery); round = fresh ECDH keys + Shamir shares "
        "every round (full Bonawitz per-execution semantics; BRB-gated "
        "secure_fedavg; <= 256 peers with the full mask graph, unlimited "
        "with --secure-agg-neighbors k)",
    )
    p.add_argument(
        "--peer-chunk",
        type=int,
        default=0,
        help="stream the vmapped peer stack through chunks of this size "
        "(O(chunk x model) transient HBM — fits 1024 ViT peers on one "
        "chip); 0 = full vmap",
    )
    p.add_argument(
        "--robust-impl",
        choices=["blockwise", "gathered"],
        default="blockwise",
        help="robust-reducer strategy: blockwise streams O(peers x block) "
        "transients; gathered all-gathers the full update stack",
    )
    p.add_argument("--brb", action="store_true", help="enable the BRB trust plane")
    p.add_argument(
        "--brb-committee",
        type=int,
        default=0,
        help="scope the Bracha quorum to a deterministic m-member committee "
        "(O(m^2) control messages per broadcast instead of O(P^2) — the "
        "trust plane at 1024+ peers); 0 = every peer votes",
    )
    p.add_argument("--round-timeout-s", type=float, default=30.0)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--compute-dtype", default="bfloat16")
    p.add_argument("--param-dtype", default="float32")
    p.add_argument("--remat", action="store_true")
    p.add_argument(
        "--attn-impl",
        choices=["dense", "flash"],
        default="dense",
        help="attention implementation for transformer models "
        "(flash = fused Pallas TPU kernels)",
    )
    p.add_argument(
        "--seq-shards",
        type=int,
        default=1,
        help="sequence/context parallelism: shard each peer's token "
        "sequence over a mesh axis of this size (ring attention); 1=off",
    )
    p.add_argument(
        "--seq-impl",
        choices=["ring", "ulysses"],
        default="ring",
        help="sequence-parallel attention: ring (blockwise k/v rotation) or "
        "ulysses (all-to-all heads<->sequence re-shard; needs "
        "--seq-shards | --vit-heads)",
    )
    p.add_argument(
        "--vit-pool",
        choices=["cls", "mean"],
        default="cls",
        help="ViT head pooling (mean required under --seq-shards > 1)",
    )
    p.add_argument(
        "--vit-heads",
        type=int,
        default=3,
        help="ViT attention head count (4 divides evenly for --tp-shards "
        "on power-of-two meshes)",
    )
    p.add_argument(
        "--vit-depth",
        type=int,
        default=12,
        help="ViT trunk depth (12 = standard ViT-Tiny)",
    )
    p.add_argument(
        "--tp-shards",
        type=int,
        default=1,
        help="tensor parallelism: shard attention heads + MLP hidden over "
        "a mesh axis of this size (megatron column/row); 1=off",
    )
    p.add_argument(
        "--moe-experts",
        type=int,
        default=0,
        help="mixture-of-experts: swap every --moe-every-th ViT block's MLP "
        "for a top-1 mixture of this many experts; 0=dense MLPs",
    )
    p.add_argument("--moe-every", type=int, default=2)
    p.add_argument(
        "--moe-capacity-factor",
        type=float,
        default=2.0,
        help="per-expert slots = factor * tokens / experts (tokens past "
        "capacity drop; >= experts makes dropping impossible)",
    )
    p.add_argument(
        "--ep-shards",
        type=int,
        default=1,
        help="expert parallelism: shard the MoE experts over a mesh axis of "
        "this size (tokens routed by all_to_all); 1=off",
    )
    p.add_argument(
        "--pp-shards",
        type=int,
        default=1,
        help="pipeline parallelism: shard the ViT trunk depth over a mesh "
        "axis of this size (microbatch ppermute schedule); 1=off",
    )
    p.add_argument(
        "--pp-microbatches",
        type=int,
        default=0,
        help="microbatches per batch for the pipeline schedule; 0=pp-shards",
    )
    p.add_argument(
        "--vit-scan-blocks",
        action="store_true",
        help="store the ViT trunk as one nn.scan stack (faster compile; "
        "the pytree-identical dense twin of a --pp-shards run)",
    )
    p.add_argument("--attack", default="none", help="Byzantine attack for injected peers")
    p.add_argument("--byz-ids", default="", help="comma-separated adversarial peer ids")
    p.add_argument("--log-path", default=None, help="JSONL metrics output")
    p.add_argument("--checkpoint-dir", default=None, help="checkpoint/resume directory")
    p.add_argument("--checkpoint-every", type=int, default=1, help="rounds between checkpoints")
    p.add_argument("--profile-dir", default=None, help="jax.profiler trace output dir")
    p.add_argument(
        "--fused-rounds",
        type=int,
        default=0,
        help="high-throughput mode: scan N rounds per device dispatch "
        "(requires --brb off); 0 = one round per dispatch",
    )
    p.add_argument(
        "--failure-cooldown",
        type=int,
        default=0,
        help="rounds a BRB-failed peer is excluded from trainer sampling (0=off)",
    )
    p.add_argument("--port", type=int, default=5000, help="HTTP port (serve mode)")
    p.add_argument("--n-devices", type=int, default=None, help="mesh size (default: all)")
    p.add_argument(
        "--platform",
        default=None,
        choices=["cpu", "tpu"],
        help="force the JAX platform; needed because an environment may pin "
        "JAX to a TPU backend at interpreter start, in which case "
        "JAX_PLATFORMS=cpu in the env arrives too late — this flag applies "
        "jax.config.update before any device is touched",
    )
    return p


def config_from_args(args: argparse.Namespace) -> Config:
    return Config(
        num_peers=args.num_peers,
        trainers_per_round=args.trainers_per_round,
        byzantine_f=args.byzantine_f,
        rounds=args.rounds,
        local_epochs=args.local_epochs,
        batch_size=args.batch_size,
        samples_per_peer=args.samples_per_peer,
        lr=args.lr,
        momentum=args.momentum,
        optimizer=args.optimizer,
        weight_decay=args.weight_decay,
        server_lr=args.server_lr,
        server_momentum=args.server_momentum,
        server_opt=args.server_opt,
        server_beta1=args.server_beta1,
        server_beta2=args.server_beta2,
        server_eps=args.server_eps,
        fedprox_mu=args.fedprox_mu,
        scaffold=args.scaffold,
        selection=args.selection,
        poc_candidates=args.poc_candidates,
        hetero_min_epochs=args.hetero_min_epochs,
        fednova=args.fednova,
        compress=args.compress,
        compress_ratio=args.compress_ratio,
        qsgd_levels=args.qsgd_levels,
        dp_clip=args.dp_clip,
        dp_noise_multiplier=args.dp_noise_multiplier,
        dp_delta=args.dp_delta,
        model=args.model,
        dataset=args.dataset,
        partition=args.partition,
        dirichlet_alpha=args.dirichlet_alpha,
        seq_len=args.seq_len,
        aggregator=args.aggregator,
        gossip_graph=args.gossip_graph,
        trimmed_mean_beta=args.trimmed_mean_beta,
        multi_krum_m=args.multi_krum_m,
        robust_impl=args.robust_impl,
        secure_agg_neighbors=args.secure_agg_neighbors,
        secure_agg_keys=args.secure_agg_keys,
        secure_agg_rekey=args.secure_agg_rekey,
        peer_chunk=args.peer_chunk,
        brb_enabled=args.brb,
        brb_committee=args.brb_committee,
        round_timeout_s=args.round_timeout_s,
        seed=args.seed,
        compute_dtype=args.compute_dtype,
        param_dtype=args.param_dtype,
        remat=args.remat,
        attn_impl=args.attn_impl,
        seq_shards=args.seq_shards,
        seq_impl=args.seq_impl,
        vit_pool=args.vit_pool,
        vit_heads=args.vit_heads,
        vit_depth=args.vit_depth,
        tp_shards=args.tp_shards,
        moe_experts=args.moe_experts,
        moe_every=args.moe_every,
        moe_capacity_factor=args.moe_capacity_factor,
        ep_shards=args.ep_shards,
        pp_shards=args.pp_shards,
        pp_microbatches=args.pp_microbatches,
        vit_scan_blocks=args.vit_scan_blocks,
    )


def _warn(msg: str) -> None:
    """JSON warning on stderr — stdout stays a clean JSONL record stream."""
    print(json.dumps({"warning": msg}), file=sys.stderr)


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.platform is not None:
        import jax

        # Backend choice is effectively final once any device has been
        # queried (e.g. a sitecustomize that touches jax at interpreter
        # start): jax_num_cpu_devices raises RuntimeError post-init, while
        # jax_platforms silently no-ops. Handle both — warn and continue on
        # whatever backend exists instead of crashing the CLI.
        try:
            jax.config.update("jax_platforms", args.platform)
            if args.platform == "cpu" and args.n_devices is not None:
                jax.config.update("jax_num_cpu_devices", args.n_devices)
        except RuntimeError as e:
            _warn(f"--n-devices not applied: {e}")
        if jax.default_backend() != args.platform:
            _warn(
                f"--platform {args.platform} not honored; "
                f"running on {jax.default_backend()}"
            )
    if args.n_devices is not None:
        import jax

        if args.n_devices > len(jax.devices()):
            _warn(
                f"--n-devices {args.n_devices} unavailable; "
                f"using all {len(jax.devices())} devices"
            )
            args.n_devices = None
    cfg = config_from_args(args)
    byz_ids = tuple(int(x) for x in args.byz_ids.split(",") if x.strip())

    if args.mode == "serve":
        from p2pdl_tpu.runtime.server import serve

        server = serve(
            cfg, port=args.port, attack=args.attack, byz_ids=byz_ids,
            log_path=args.log_path, n_devices=args.n_devices,
        )
        print(json.dumps({"serving": True, "port": server.server_address[1]}))
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            server.shutdown()
        return 0

    if args.mode == "bench":
        # bench.py lives at the repo root (driver contract), not inside the
        # package — load it by path so the CLI works from any CWD.
        import importlib.util
        import os

        bench_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py"
        )
        spec = importlib.util.spec_from_file_location("bench", bench_path)
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        bench.main()
        return 0

    from p2pdl_tpu.runtime.driver import Experiment

    exp = Experiment(
        cfg, attack=args.attack, byz_ids=byz_ids,
        log_path=args.log_path, n_devices=args.n_devices,
        checkpoint_dir=args.checkpoint_dir, checkpoint_every=args.checkpoint_every,
        profile_dir=args.profile_dir, failure_cooldown_rounds=args.failure_cooldown,
    )
    with exp.profiler.trace():
        if args.fused_rounds > 0:
            exp.run_fused(
                rounds_per_call=args.fused_rounds,
                on_record=lambda rec: print(json.dumps(rec.to_dict()), flush=True),
            )
        else:
            while int(exp.state.round_idx) < cfg.rounds:
                record = exp.run_round()
                print(json.dumps(record.to_dict()))
    exp.save_checkpoint()
    print(json.dumps({"profile": exp.profiler.summary()}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
