"""HTTP orchestration facade + live telemetry exposition.

API parity with the reference's Flask app (reference ``main.py``):
``POST /start_training`` runs the configured number of rounds and returns
the per-round learning progress JSON (reference ``main.py:45-109``);
``GET /status`` is the liveness probe (reference ``main.py:112-115``).
Membership rides the same facade: ``GET /membership`` is the failure
detector's live view plus the administratively-stopped set, and ``POST
/join`` / ``POST /leave`` re-admit or stop a KNOWN node (static membership
— an unknown peer_id is a 400, the cluster never grows past its
provisioned key/data/mesh footprint).
Built on ``http.server`` (stdlib) so the framework adds no web-framework
dependency; single worker thread — the driver is intentionally
single-threaded (SURVEY §5 race-detection note).

Observability plane (shared between the orchestrator and the standalone
``cli serve-metrics`` server):

- ``GET /metrics``  — Prometheus text exposition 0.0.4 over the live
  registry (``telemetry.render_prometheus``), scrapeable mid-run: the
  registry's own lock snapshots the series while the driver keeps writing.
- ``GET /healthz``  — JSON liveness: flight-recorder anomaly totals plus
  (on the orchestrator) training state.
- ``GET /flight``   — the flight recorder's summary and time-stripped
  event ring as JSON (the debugging surface for a run in flight).

Every handler replies with a JSON body and a correct status code: unknown
paths are 404, malformed POST bodies 400, a busy trainer 409, and an
internal failure 500 — never a bare connection reset.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional
from urllib.parse import unquote

from p2pdl_tpu.config import Config
from p2pdl_tpu.utils import flight, telemetry

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# /flight paging: default and hard page caps for cursor scrapes, so a live
# tail never re-ships the whole ring (and a hostile ?limit can't either).
FLIGHT_PAGE_LIMIT = 512
FLIGHT_PAGE_LIMIT_MAX = 2048


def _flight_page_params(
    query: str,
) -> tuple[Optional[dict[str, Any]], Optional[str]]:
    """Parse ``since``/``limit``/``kind`` from a /flight query string;
    returns ``(params, None)`` or ``(None, error)`` — the PR 6 error matrix
    says a bad request gets a JSON body naming the problem, not a silent
    default. ``kind`` is a comma-separated subset of ``flight.KNOWN_KINDS``
    (a typo'd filter fails loudly instead of tailing nothing)."""
    params: dict[str, Any] = {
        "since": 0,
        "limit": FLIGHT_PAGE_LIMIT,
        "kinds": None,
    }
    for part in query.split("&"):
        if not part:
            continue
        key, sep, raw = part.partition("=")
        raw = unquote(raw)  # standard clients %-encode the kind-list commas
        if key == "kind" and sep:
            kinds = tuple(k for k in raw.split(",") if k)
            if not kinds:
                return None, "/flight ?kind must name at least one event kind"
            unknown = sorted(set(kinds) - set(flight.KNOWN_KINDS))
            if unknown:
                return None, (
                    "/flight ?kind names unknown event kind(s): "
                    + ", ".join(unknown)
                )
            params["kinds"] = kinds
            continue
        if key not in ("since", "limit") or not sep:
            return None, f"unknown /flight query parameter: {part!r}"
        try:
            val = int(raw)
        except ValueError:
            return None, f"/flight ?{key} must be a non-negative integer, got {raw!r}"
        if val < 0:
            return None, f"/flight ?{key} must be a non-negative integer, got {raw!r}"
        params[key] = val
    params["limit"] = min(params["limit"], FLIGHT_PAGE_LIMIT_MAX)
    return params, None


class OrchestratorState:
    def __init__(self, cfg: Config, **experiment_kwargs) -> None:
        # Lazy import: Cluster pulls in the jax-backed driver, which the
        # jax-free exposition path (serve_metrics) must never pay for.
        from p2pdl_tpu.runtime.cluster import Cluster

        self.cfg = cfg
        self.cluster = Cluster(cfg, **experiment_kwargs)
        self.lock = threading.Lock()
        self.training = False

    def start_training(self) -> tuple[int, dict]:
        """Run ``cfg.rounds`` rounds; returns ``(status_code, payload)``
        with learning progress per round (reference ``main.py:96-109``
        shape: per-TESTER ``{accuracy, addr, port}`` entries under
        ``results``, each tester's accuracy measured on its own shard, plus
        our held-out global metrics)."""
        with self.lock:
            if self.training:
                return 409, {"error": "training already in progress"}
            self.training = True
        try:
            progress = []
            for _ in range(self.cfg.rounds):
                record = self.cluster.run_round()
                testers = [
                    i
                    for i in range(self.cfg.num_peers)
                    if i not in record.trainers
                ]
                progress.append(
                    {
                        "round": record.round,
                        "trainers": record.trainers,
                        "train_loss": record.train_loss,
                        "eval_loss": record.eval_loss,
                        "accuracy": record.eval_acc,
                        "results": self.cluster.per_node_results(testers),
                        "duration_s": record.duration_s,
                        "brb_delivered": record.brb_delivered,
                        "protocol_health": record.protocol_health,
                    }
                )
            return 200, {"status": "completed", "learning_progress": progress}
        finally:
            with self.lock:
                self.training = False


def _label_match(key: str, label: str, value: str) -> bool:
    """Exact label match inside a ``name{k=v,...}`` series key (substring
    checks would conflate ``event=sent`` with ``event=send_failed``)."""
    probe = f"{label}={value}"
    return f"{{{probe}}}" in key or f"{{{probe}," in key or (
        f",{probe}," in key or f",{probe}}}" in key
    )


def _transport_health(snap: dict) -> dict:
    """The /healthz ``transport`` block, derived from the ``transport.*``
    telemetry series (summed across transports when both planes ran).
    Per-peer queue depth is NOT here — that would be a per-peer identity
    label (cardinality lint); live servers with a transport handle pass
    ``transport_stats`` for the full per-peer view instead."""
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})

    def total(name: str, event: Optional[str] = None) -> float:
        out = 0
        for key, val in sorted(counters.items()):
            if key != name and not key.startswith(name + "{"):
                continue
            if event is not None and not _label_match(key, "event", event):
                continue
            out += val
        return out

    return {
        "open_connections": sum(
            v
            for k, v in sorted(gauges.items())
            if k.startswith("transport.connections_open")
        ),
        "dialed": total("transport.connections", "dialed"),
        "accepted": total("transport.connections", "accepted"),
        "retries": total("transport.messages", "retry"),
        "sent": total("transport.messages", "sent"),
        "delivered": total("transport.messages", "delivered"),
        "send_failed": total("transport.messages", "send_failed"),
        "tx_bytes": total("transport.bytes", "sent"),
        "rx_bytes": total("transport.bytes", "delivered"),
        "rejected": total("transport.messages", "rejected"),
        "backpressure_dropped": total("transport.backpressure_dropped"),
    }


def _observability_get(
    path: str,
    snapshot_fn: Callable[[], dict],
    extra_health: Optional[Callable[[], dict]] = None,
    recorder: Optional[flight.FlightRecorder] = None,
    transport_stats: Optional[Callable[[], dict]] = None,
) -> Optional[tuple[int, str, bytes]]:
    """Route the shared observability GETs; returns ``(status, content_type,
    body)`` or None when ``path`` is not an observability endpoint.

    ``recorder`` defaults to the process-wide flight recorder; the replay
    path (``cli serve-metrics --flight-path``, the tower's tests/bench)
    passes a dedicated instance so one process can expose N distinct
    recorded streams on N ports."""
    path, _, query = path.partition("?")
    if path == "/metrics":
        body = telemetry.render_prometheus(snapshot_fn()).encode()
        return 200, PROMETHEUS_CONTENT_TYPE, body
    rec = recorder if recorder is not None else flight.recorder()
    if path == "/healthz":
        snap = snapshot_fn()
        payload: dict[str, Any] = {
            "status": "ok",
            "anomaly_count": rec.anomaly_count,
            "anomalies_by_kind": dict(sorted(rec.anomalies_by_kind.items())),
            # A server holding a live transport handle reports the full
            # per-peer view (queue depths included); otherwise the block is
            # reconstructed from the transport.* telemetry series.
            "transport": (
                transport_stats() if transport_stats is not None
                else _transport_health(snap)
            ),
        }
        # Cheap training-progress liveness (no /metrics scrape needed):
        # the driver's round gauges, absent until the first round lands.
        gauges = snap.get("gauges", {})
        for field, series in (
            ("round_index", "driver.round_index"),
            ("rounds_per_sec", "driver.rounds_per_sec"),
        ):
            if series in gauges:
                payload[field] = gauges[series]
        if extra_health is not None:
            payload.update(extra_health())
        return 200, "application/json", json.dumps(payload).encode()
    if path == "/flight":
        if query:
            # Cursor-paged tail: ?since=<n> resumes where the last scrape
            # stopped, ?limit bounds the page (default FLIGHT_PAGE_LIMIT,
            # hard cap FLIGHT_PAGE_LIMIT_MAX), ?kind=a,b filters
            # server-side — live tailing without re-shipping the whole
            # ring each scrape.
            params, err = _flight_page_params(query)
            if err is not None:
                return 400, "application/json", json.dumps({"error": err}).encode()
            payload = rec.events_page(
                since=params["since"],
                limit=params["limit"],
                strip_time=True,
                kinds=params["kinds"],
            )
            payload["summary"] = rec.summary()
            return 200, "application/json", json.dumps(payload).encode()
        payload = {
            "summary": rec.summary(),
            "events": rec.events(strip_time=True),
        }
        return 200, "application/json", json.dumps(payload).encode()
    return None


class _JSONHandler(BaseHTTPRequestHandler):
    """Base handler: JSON replies, JSON errors, no connection-killing
    exceptions (a handler bug answers 500, it does not reset the socket)."""

    def _send(self, code: int, content_type: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply(self, code: int, payload: dict) -> None:
        self._send(code, "application/json", json.dumps(payload).encode())

    def _guarded(self, fn) -> None:
        try:
            fn()
        except BrokenPipeError:
            pass  # client went away mid-reply; nothing to answer
        except Exception as e:  # noqa: BLE001 -- the 500 body IS the report
            try:
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            except Exception:
                pass

    def _read_json_body(self) -> tuple[Optional[dict], Optional[str]]:
        """Parse an optional JSON POST body; ``(None, error)`` on garbage."""
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            return None, "malformed Content-Length"
        if length == 0:
            return {}, None
        raw = self.rfile.read(length)
        try:
            doc = json.loads(raw)
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            return None, f"malformed JSON body: {e}"
        if not isinstance(doc, dict):
            return None, "JSON body must be an object"
        return doc, None

    def log_message(self, *args) -> None:  # quiet
        pass


def make_handler(state: OrchestratorState):
    class Handler(_JSONHandler):
        def do_GET(self) -> None:
            self._guarded(self._get)

        def _get(self) -> None:
            def extra_health() -> dict:
                with state.lock:
                    training = state.training
                return {
                    "status": "training" if training else "idle",
                    "rounds_completed": len(state.cluster.experiment.records),
                }

            routed = _observability_get(
                self.path, telemetry.snapshot, extra_health
            )
            if routed is not None:
                self._send(*routed)
            elif self.path == "/status":
                with state.lock:
                    training = state.training
                rounds_done = len(state.cluster.experiment.records)
                self._reply(
                    200,
                    {
                        "status": "training" if training else "idle",
                        "rounds_completed": rounds_done,
                        "num_peers": state.cfg.num_peers,
                    },
                )
            elif self.path == "/membership":
                self._reply(
                    200,
                    {
                        "num_peers": state.cfg.num_peers,
                        **state.cluster.membership(),
                    },
                )
            else:
                self._reply(404, {"error": f"not found: {self.path}"})

        def do_POST(self) -> None:
            self._guarded(self._post)

        def _membership_change(self, action: str) -> None:
            """POST /join and /leave: membership is STATIC — the peer set
            (keys, data shards, mesh) is provisioned at cluster build, so
            /join can only re-admit a known, stopped node (the Node.start /
            Node.stop lifecycle); an unknown peer_id is a 400, not a grow."""
            doc, err = self._read_json_body()
            if err is not None:
                self._reply(400, {"error": err})
                return
            pid = doc.get("peer_id")
            if not isinstance(pid, int) or isinstance(pid, bool):
                self._reply(400, {"error": "peer_id must be an integer"})
                return
            if not 0 <= pid < state.cfg.num_peers:
                self._reply(
                    400,
                    {
                        "error": (
                            f"unknown peer_id {pid}: membership is static "
                            f"(cluster provisioned with num_peers="
                            f"{state.cfg.num_peers}); /join re-admits a "
                            "known stopped node, it cannot grow the cluster"
                        )
                    },
                )
                return
            node = state.cluster.nodes[pid]
            if action == "join":
                already = node.running
                node.start()
                status = "already-live" if already else "joined"
            else:
                already = not node.running
                node.stop()
                status = "already-stopped" if already else "left"
            self._reply(
                200,
                {
                    "status": status,
                    "peer_id": pid,
                    **state.cluster.membership(),
                },
            )

        def _post(self) -> None:
            if self.path == "/start_training":
                _, err = self._read_json_body()
                if err is not None:
                    self._reply(400, {"error": err})
                    return
                self._reply(*state.start_training())
            elif self.path == "/join":
                self._membership_change("join")
            elif self.path == "/leave":
                self._membership_change("leave")
            else:
                self._reply(404, {"error": f"not found: {self.path}"})

    return Handler


def serve(
    cfg: Config, host: str = "127.0.0.1", port: int = 5000, **experiment_kwargs
) -> ThreadingHTTPServer:
    """Start the orchestrator HTTP server (reference ``main.py:119`` runs on
    port 5000); returns the server (caller controls serve_forever/shutdown)."""
    state = OrchestratorState(cfg, **experiment_kwargs)
    server = ThreadingHTTPServer((host, port), make_handler(state))
    server.orchestrator = state  # type: ignore[attr-defined]
    return server


def serve_metrics(
    host: str = "127.0.0.1",
    port: int = 9090,
    snapshot_fn: Optional[Callable[[], dict]] = None,
    recorder: Optional[flight.FlightRecorder] = None,
    transport_stats_fn: Optional[Callable[[], dict]] = None,
) -> ThreadingHTTPServer:
    """Standalone exposition server: ``/metrics`` + ``/healthz`` +
    ``/flight`` with no orchestrator (and no jax import) attached.

    ``snapshot_fn`` defaults to the live process registry; ``cli
    serve-metrics --telemetry-path`` passes a loader over a snapshot JSON on
    disk instead, turning any recorded run into a scrape target.
    ``recorder`` likewise defaults to the process-wide flight recorder; a
    dedicated instance lets one process replay N distinct recorded streams
    on N ports (the tower's test/bench topology). ``transport_stats_fn``
    (e.g. a live ``AsyncTCPTransport.transport_stats``) upgrades the
    /healthz ``transport`` block to the full per-peer view — queue depths
    included — instead of the telemetry-derived aggregate."""
    if snapshot_fn is None:
        snapshot_fn = telemetry.snapshot

    class Handler(_JSONHandler):
        def do_GET(self) -> None:
            self._guarded(self._get)

        def _get(self) -> None:
            routed = _observability_get(
                self.path,
                snapshot_fn,
                recorder=recorder,
                transport_stats=transport_stats_fn,
            )
            if routed is not None:
                self._send(*routed)
            else:
                self._reply(404, {"error": f"not found: {self.path}"})

    server = ThreadingHTTPServer((host, port), Handler)
    return server
