"""HTTP orchestration facade.

API parity with the reference's Flask app (reference ``main.py``):
``POST /start_training`` runs the configured number of rounds and returns
the per-round learning progress JSON (reference ``main.py:45-109``);
``GET /status`` is the liveness probe (reference ``main.py:112-115``).
Built on ``http.server`` (stdlib) so the framework adds no web-framework
dependency; single worker thread — the driver is intentionally
single-threaded (SURVEY §5 race-detection note).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from p2pdl_tpu.config import Config
from p2pdl_tpu.runtime.cluster import Cluster


class OrchestratorState:
    def __init__(self, cfg: Config, **experiment_kwargs) -> None:
        self.cfg = cfg
        self.cluster = Cluster(cfg, **experiment_kwargs)
        self.lock = threading.Lock()
        self.training = False

    def start_training(self) -> dict:
        """Run ``cfg.rounds`` rounds; returns learning progress per round
        (reference ``main.py:96-109`` shape: per-TESTER ``{accuracy, addr,
        port}`` entries under ``results``, each tester's accuracy measured
        on its own shard, plus our held-out global metrics)."""
        with self.lock:
            if self.training:
                return {"error": "training already in progress"}
            self.training = True
        try:
            progress = []
            for _ in range(self.cfg.rounds):
                record = self.cluster.run_round()
                testers = [
                    i
                    for i in range(self.cfg.num_peers)
                    if i not in record.trainers
                ]
                progress.append(
                    {
                        "round": record.round,
                        "trainers": record.trainers,
                        "train_loss": record.train_loss,
                        "eval_loss": record.eval_loss,
                        "accuracy": record.eval_acc,
                        "results": self.cluster.per_node_results(testers),
                        "duration_s": record.duration_s,
                        "brb_delivered": record.brb_delivered,
                    }
                )
            return {"status": "completed", "learning_progress": progress}
        finally:
            with self.lock:
                self.training = False


def make_handler(state: OrchestratorState):
    class Handler(BaseHTTPRequestHandler):
        def _reply(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self) -> None:
            if self.path == "/status":
                with state.lock:
                    training = state.training
                rounds_done = len(state.cluster.experiment.records)
                self._reply(
                    200,
                    {
                        "status": "training" if training else "idle",
                        "rounds_completed": rounds_done,
                        "num_peers": state.cfg.num_peers,
                    },
                )
            else:
                self._reply(404, {"error": "not found"})

        def do_POST(self) -> None:
            if self.path == "/start_training":
                self._reply(200, state.start_training())
            else:
                self._reply(404, {"error": "not found"})

        def log_message(self, *args) -> None:  # quiet
            pass

    return Handler


def serve(
    cfg: Config, host: str = "127.0.0.1", port: int = 5000, **experiment_kwargs
) -> ThreadingHTTPServer:
    """Start the orchestrator HTTP server (reference ``main.py:119`` runs on
    port 5000); returns the server (caller controls serve_forever/shutdown)."""
    state = OrchestratorState(cfg, **experiment_kwargs)
    server = ThreadingHTTPServer((host, port), make_handler(state))
    server.orchestrator = state  # type: ignore[attr-defined]
    return server
