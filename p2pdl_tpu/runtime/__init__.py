"""Host-side runtime: round driver, node API, HTTP facade.

The thin control plane above the compiled data plane — the role the
reference's Flask app + ``Node`` threads play (reference ``main.py``,
``node/node.py``), minus the shared-mutable-state races (single-threaded
driver, message-passing protocol layer).
"""

from p2pdl_tpu.runtime.driver import Experiment, RoundRecord, run_experiment
from p2pdl_tpu.runtime.cluster import Cluster, Node

__all__ = ["Experiment", "RoundRecord", "run_experiment", "Cluster", "Node"]
