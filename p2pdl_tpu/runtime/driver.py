"""The experiment driver: rounds, roles, trust plane, metrics.

Equivalent of the reference's ``start_training`` orchestration loop
(reference ``main.py:45-109``): per round it samples trainer/tester roles
(``main.py:52-54``), runs local training + aggregation + global sync (here:
one compiled device program instead of 3 trainer threads + pickled TCP
fan-out + 4 sequential tester aggregations), runs the BRB trust plane over
update fingerprints when enabled, evaluates, and records structured metrics
(resurrecting the reference's dead ``save_results``, ``utils/log.py:4-21``,
as JSONL that is actually written).

Failure detection the reference lacks (its round stalls forever on one
silent tester — ``node/node.py:73`` waits with no timeout, and
``utils/waiting.py``'s 30 s timeout is inoperative, SURVEY §2 #13): BRB
delivery here is checked against ``cfg.round_timeout_s`` and per-peer
delivery failures are recorded rather than hanging the experiment.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from p2pdl_tpu.config import Config
from p2pdl_tpu.data import make_federated_data
from p2pdl_tpu.parallel import (
    build_eval_fn,
    build_round_fn,
    init_peer_state,
    make_mesh,
    peer_sharding,
    shard_state,
)
from p2pdl_tpu.protocol.brb import BRBConfig, Broadcaster
from p2pdl_tpu.protocol.crypto import KeyServer, generate_key_pair
from p2pdl_tpu.protocol.transport import InMemoryHub, brb_from_wire, brb_to_wire
from p2pdl_tpu.utils.metrics import MetricsLogger
from p2pdl_tpu.utils.profiling import Profiler


@dataclasses.dataclass
class RoundRecord:
    round: int
    trainers: list[int]
    train_loss: float
    eval_loss: float
    eval_acc: float
    duration_s: float
    brb_delivered: Optional[int] = None  # peers that delivered all trainer broadcasts
    brb_failed_peers: Optional[list[int]] = None
    control_messages: Optional[int] = None
    control_bytes: Optional[int] = None

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


class _TrustPlane:
    """Host-side BRB over update fingerprints for one experiment.

    Each round, every trainer BRB-broadcasts the digest of its on-device
    update fingerprint; every peer must deliver every trainer's broadcast.
    Runs over the deterministic in-memory hub (the TCP transport serves the
    multi-host control plane; simulation never needs sockets).
    """

    def __init__(self, cfg: Config, byz_ids: tuple[int, ...] = ()) -> None:
        self.cfg = cfg
        self.key_server = KeyServer()
        self.hub = InMemoryHub()
        self.byz_ids = set(byz_ids)
        self.broadcasters: list[Broadcaster] = []
        brb_cfg = BRBConfig(cfg.num_peers, cfg.byzantine_f)
        self._keys = []
        for pid in range(cfg.num_peers):
            priv, pub = generate_key_pair()
            self.key_server.register_key(pid, pub)
            self._keys.append(priv)
            self.broadcasters.append(Broadcaster(brb_cfg, pid, self.key_server, priv))
        for pid in range(cfg.num_peers):
            self.hub.register(pid, self._make_handler(pid))

    def _make_handler(self, pid: int):
        def handler(src: int, data: bytes) -> None:
            msg = brb_from_wire(data)
            if msg is None:
                return
            for out in self.broadcasters[pid].handle(msg):
                self._fan_out(pid, out)

        return handler

    def _fan_out(self, src: int, msg) -> None:
        # Fan out to every peer INCLUDING self: in Bracha each peer (the
        # originator too) echoes, readies, and counts its own votes.
        wire = brb_to_wire(msg)
        for dst in range(self.cfg.num_peers):
            self.hub.send(src, dst, wire)

    def run_round(
        self, round_idx: int, trainer_ids: list[int], fingerprints: np.ndarray
    ) -> tuple[int, list[int]]:
        """Broadcast each trainer's fingerprint; returns (#peers that
        delivered every *honest* trainer's broadcast, ids of peers that did
        not). Byzantine trainers equivocate: half the peers receive a forged
        fingerprint — correct BRB then either delivers one payload
        consistently or (echo vote split) delivers nothing; a Byzantine
        trainer's broadcast is therefore excluded from the delivery check."""
        for tid in trainer_ids:
            payload = json.dumps(
                {"round": round_idx, "trainer": tid, "fingerprint": fingerprints[tid].tolist()}
            ).encode()
            if tid in self.byz_ids:
                forged = json.dumps(
                    {"round": round_idx, "trainer": tid, "fingerprint": "forged"}
                ).encode()
                send_a, send_b = self.broadcasters[tid].broadcast_equivocating(
                    round_idx, payload, forged
                )
                half = self.cfg.num_peers // 2
                for dst in range(self.cfg.num_peers):
                    wire = brb_to_wire(send_a if dst < half else send_b)
                    self.hub.send(tid, dst, wire)
            else:
                for msg in self.broadcasters[tid].broadcast(round_idx, payload):
                    self._fan_out(tid, msg)
        deadline = time.monotonic() + self.cfg.round_timeout_s
        while self.hub.pump() and time.monotonic() < deadline:
            pass
        honest_trainers = [t for t in trainer_ids if t not in self.byz_ids]
        failed = []
        for pid in range(self.cfg.num_peers):
            ok = all(
                self.broadcasters[pid].delivered(tid, round_idx) is not None
                for tid in honest_trainers
            )
            if not ok:
                failed.append(pid)
        for bc in self.broadcasters:
            bc.prune(round_idx)
        return self.cfg.num_peers - len(failed), failed


class Experiment:
    """One configured federated experiment: data, state, compiled round."""

    def __init__(
        self,
        cfg: Config,
        attack: str = "none",
        byz_ids: tuple[int, ...] = (),
        log_path: Optional[str] = None,
        n_devices: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 1,
        profile_dir: Optional[str] = None,
        failure_cooldown_rounds: int = 0,
    ) -> None:
        self.cfg = cfg
        self.attack = attack
        self.byz_ids = tuple(byz_ids)
        # Failure detection -> exclusion (reference has none: one silent peer
        # stalls its round forever, reference ``node/node.py:73`` +
        # ``utils/waiting.py``). Peers whose BRB delivery failed are excluded
        # from trainer sampling for this many subsequent rounds, then
        # re-admitted. Suspicion is runtime-ephemeral (a resumed experiment
        # starts with a clean slate, like any real failure detector).
        self.failure_cooldown_rounds = failure_cooldown_rounds
        self._suspect_until: dict[int, int] = {}
        self.mesh = make_mesh(n_devices)
        self.data = make_federated_data(cfg)
        self.round_fn = build_round_fn(cfg, self.mesh, attack=attack)
        self.eval_fn = build_eval_fn(cfg)
        self.metrics = MetricsLogger(log_path)
        self.trust = _TrustPlane(cfg, byz_ids) if cfg.brb_enabled else None
        self.profiler = Profiler(profile_dir)

        self.checkpointer = None
        self.checkpoint_every = max(1, checkpoint_every)
        # Experiment identity beyond the Config — validated on resume so a
        # Byzantine run's checkpoint can't silently continue as an honest one.
        self._ckpt_extra = {"attack": attack, "byz_ids": list(self.byz_ids)}
        state = None
        if checkpoint_dir is not None:
            from p2pdl_tpu.utils.checkpoint import Checkpointer

            self.checkpointer = Checkpointer(checkpoint_dir)
            if self.checkpointer.latest_step() is not None:
                state = self.checkpointer.restore(cfg, extra=self._ckpt_extra)
        if state is None:
            state = init_peer_state(cfg)

        sh = peer_sharding(self.mesh)
        self.state = shard_state(state, cfg, self.mesh)
        self.x = jax.device_put(self.data.x, sh)
        self.y = jax.device_put(self.data.y, sh)
        byz_gate = np.zeros(cfg.num_peers, np.float32)
        for i in self.byz_ids:
            byz_gate[i] = 1.0
        self.byz_gate = jnp.asarray(byz_gate)
        self.records: list[RoundRecord] = []

    def sample_roles(self, round_idx: Optional[int] = None) -> np.ndarray:
        """Random trainer sample per round (reference ``main.py:52-54``).

        Keyed by ``(seed, round_idx)`` — not by a stateful generator — so a
        resumed experiment samples the exact roles the uninterrupted run
        would have (checkpoint/resume determinism). Exception: with
        ``failure_cooldown_rounds`` active, the suspicion table is runtime
        state, so a resume right after a peer failure can sample that peer
        where the uninterrupted run would not — suspicion is observational,
        not part of the training state."""
        if round_idx is None:
            round_idx = int(self.state.round_idx)
        rng = np.random.default_rng([self.cfg.seed, round_idx])
        eligible = np.asarray(
            [
                p
                for p in range(self.cfg.num_peers)
                if self._suspect_until.get(p, -1) < round_idx
            ]
        )
        if len(eligible) < self.cfg.trainers_per_round:
            if self.cfg.aggregator in ("fedavg", "secure_fedavg") and len(eligible) > 0:
                # Shrink participation: run the round with the survivors; the
                # compiled round accepts -1 vacancy padding and normalizes by
                # the live count, so no recompile.
                chosen = np.sort(eligible)
                pad = np.full(self.cfg.trainers_per_round - len(chosen), -1, chosen.dtype)
                return np.concatenate([chosen, pad])
            # Robust reducers need their full [T] update matrix: degrade to
            # the full peer set rather than shrinking the trainer quorum.
            eligible = np.arange(self.cfg.num_peers)
        return np.sort(rng.choice(eligible, self.cfg.trainers_per_round, replace=False))

    def run_round(self, trainers: Optional[np.ndarray] = None) -> RoundRecord:
        """Run one round. ``trainers`` overrides role sampling (the Cluster
        facade passes the set its Nodes consented to, reference
        ``main.py:59-76``); default samples per ``sample_roles``."""
        r = int(self.state.round_idx)
        if trainers is None:
            trainers = self.sample_roles(r)
        else:
            trainers = np.sort(np.asarray(trainers, dtype=np.int64))
            if len(trainers) != self.cfg.trainers_per_round:
                raise ValueError(
                    f"explicit trainer list has {len(trainers)} entries, "
                    f"config expects trainers_per_round={self.cfg.trainers_per_round}"
                )
        # -1 entries are vacancy padding for a shrunken round (see
        # sample_roles); the device program consumes the padded vector, the
        # host plane (trust, metrics, records) only the live peers.
        live = trainers[trainers >= 0]
        t0 = time.perf_counter()
        with self.profiler.phase("round"):
            self.state, m = self.round_fn(
                self.state,
                self.x,
                self.y,
                jnp.asarray(trainers, jnp.int32),
                self.byz_gate,
                jax.random.fold_in(jax.random.PRNGKey(self.cfg.seed), r),
            )
            # Mean over this round's trainers only — non-trainers' local
            # losses exist on-device but the reference's progress metric is
            # trainer loss (``main.py:90-94`` collects from trainer runs).
            # Gossip has no roles: every peer trains, so every loss counts.
            losses = np.asarray(m["train_loss"])
            if self.cfg.aggregator != "gossip":
                losses = losses[live]
            train_loss = float(np.mean(losses))

        brb_delivered = brb_failed = msgs = nbytes = None
        if self.trust is not None:
            with self.profiler.phase("brb"):
                fingerprints = np.asarray(m["fingerprint"])
                m0, b0 = self.trust.hub.messages_sent, self.trust.hub.bytes_sent
                delivered, failed = self.trust.run_round(r, live.tolist(), fingerprints)
                brb_delivered, brb_failed = delivered, failed
                msgs = self.trust.hub.messages_sent - m0
                nbytes = self.trust.hub.bytes_sent - b0
                if self.failure_cooldown_rounds > 0:
                    for pid in failed:
                        self._suspect_until[pid] = r + self.failure_cooldown_rounds

        with self.profiler.phase("eval"):
            ev = self.eval_fn(self.state, self.data.eval_x, self.data.eval_y)
        record = RoundRecord(
            round=r,
            trainers=live.tolist(),
            train_loss=train_loss,
            eval_loss=float(ev["eval_loss"]),
            eval_acc=float(ev["eval_acc"]),
            duration_s=time.perf_counter() - t0,
            brb_delivered=brb_delivered,
            brb_failed_peers=brb_failed,
            control_messages=msgs,
            control_bytes=nbytes,
        )
        self.records.append(record)
        self.metrics.log(record.to_dict())
        if self.checkpointer is not None and (r + 1) % self.checkpoint_every == 0:
            self.checkpointer.save(self.state, self.cfg, extra=self._ckpt_extra)
        return record

    def save_checkpoint(self) -> None:
        """Checkpoint the current state (no-op without a dir; idempotent —
        skips if the current round is already the latest saved step)."""
        if self.checkpointer is not None and self.checkpointer.latest_step() != int(
            self.state.round_idx
        ):
            self.checkpointer.save(self.state, self.cfg, extra=self._ckpt_extra)

    def run(self) -> list[RoundRecord]:
        """Run the remaining rounds (resume-aware: a restored experiment
        continues from its checkpointed round, reference has no equivalent).

        Always checkpoints the final state, whatever ``checkpoint_every`` —
        otherwise tail rounds would be lost and a re-launch would re-execute
        them, duplicating their JSONL metrics records. Device traces go to
        ``profile_dir`` when configured (the ``jax.profiler`` trace wraps the
        whole run here, not only in the CLI)."""
        with self.profiler.trace():
            while int(self.state.round_idx) < self.cfg.rounds:
                self.run_round()
        self.save_checkpoint()
        return self.records


def run_experiment(cfg: Config, **kwargs: Any) -> list[RoundRecord]:
    return Experiment(cfg, **kwargs).run()
