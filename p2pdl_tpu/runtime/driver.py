"""The experiment driver: rounds, roles, trust plane, metrics.

Equivalent of the reference's ``start_training`` orchestration loop
(reference ``main.py:45-109``): per round it samples trainer/tester roles
(``main.py:52-54``), runs local training + aggregation + global sync (here:
one compiled device program instead of 3 trainer threads + pickled TCP
fan-out + 4 sequential tester aggregations), runs the BRB trust plane over
update fingerprints when enabled, evaluates, and records structured metrics
(resurrecting the reference's dead ``save_results``, ``utils/log.py:4-21``,
as JSONL that is actually written).

Failure detection the reference lacks (its round stalls forever on one
silent tester — ``node/node.py:73`` waits with no timeout, and
``utils/waiting.py``'s 30 s timeout is inoperative, SURVEY §2 #13): BRB
delivery here is checked against ``cfg.round_timeout_s`` and per-peer
delivery failures are recorded rather than hanging the experiment.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import json
import os
import threading
import time
from collections.abc import Mapping
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from p2pdl_tpu.config import Config
from p2pdl_tpu.data import make_federated_data
from p2pdl_tpu.parallel import (
    build_compressed_pack_fn,
    build_digest_pack_fn,
    build_eval_fn,
    build_round_fn,
    build_gossip_trust_round_fns,
    build_trust_round_fns,
    init_peer_state,
    make_mesh,
    params_layout,
    peer_sharding,
    shard_state,
)
from p2pdl_tpu.protocol.brb import BRBBatch, BRBConfig, Broadcaster
from p2pdl_tpu.protocol.crypto import KeyServer, generate_key_pair
from p2pdl_tpu.protocol.faults import FailureDetector, FaultInjector, resolve_plan
from p2pdl_tpu.protocol.transport import (
    InMemoryHub,
    batch_to_wire,
    brb_to_wire,
    control_from_wire,
)
from p2pdl_tpu.utils import devprof, flight, telemetry
from p2pdl_tpu.utils.metrics import MetricsLogger
from p2pdl_tpu.utils.profiling import Profiler

# One process-wide pool for per-row digest hashing: the jobs are stateless
# (pure SHA-256 over a host buffer), so Experiments share it rather than
# each leaking a never-shut-down executor for the life of the process.
_DIGEST_POOL: Optional[ThreadPoolExecutor] = None
_DIGEST_POOL_LOCK = threading.Lock()


def _digest_pool() -> ThreadPoolExecutor:
    global _DIGEST_POOL
    if _DIGEST_POOL is None:
        with _DIGEST_POOL_LOCK:
            if _DIGEST_POOL is None:
                _DIGEST_POOL = ThreadPoolExecutor(
                    max_workers=min(8, os.cpu_count() or 1),
                    thread_name_prefix="p2pdl-digest",
                )
    return _DIGEST_POOL


@dataclasses.dataclass
class RoundRecord:
    round: int
    trainers: list[int]
    train_loss: float
    # None (-> JSON null) on interior rounds of a fused block, where held-out
    # eval intentionally does not run (see Experiment.run_fused).
    eval_loss: Optional[float]
    eval_acc: Optional[float]
    duration_s: float
    brb_delivered: Optional[int] = None  # peers that delivered all trainer broadcasts
    brb_failed_peers: Optional[list[int]] = None
    # Trainers whose commitment did not deliver+verify; under fedavg-family
    # aggregation they were gated out of THIS round's aggregate.
    brb_excluded_trainers: Optional[list[int]] = None
    control_messages: Optional[int] = None
    control_bytes: Optional[int] = None
    # Cumulative (eps, delta)-DP guarantee through THIS round (None unless
    # dp_noise_multiplier > 0): utils/dp.rdp_epsilon over round+1 releases.
    dp_epsilon: Optional[float] = None
    # Chaos plane (None unless a FaultPlan is active). All deterministic —
    # duration_s and protocol_health["brb_latency_s"] are the only wall-clock
    # fields, so a same-seed rerun's record stream is bit-identical once
    # those two are stripped.
    fault_events: Optional[list[dict]] = None  # crash/recover/partition/heal/suspect
    suspected_peers: Optional[list[int]] = None  # failure detector's view this round
    excluded_peers: Optional[list[int]] = None  # ineligible for sampling this round
    faults_injected: Optional[dict[str, int]] = None  # per-round message-fault counts
    mask_recoveries: Optional[list[int]] = None  # peers whose seeds Shamir-recovered
    # Per-round protocol health (None when the trust plane is off): quorum
    # sizes/margins and the flight recorder's anomaly delta are deterministic;
    # the nested "brb_latency_s" block is wall-clock quantiles and sits
    # outside the bit-identity contract alongside duration_s.
    protocol_health: Optional[dict[str, Any]] = None

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


def _latency_block(latencies: list[float]) -> dict[str, Any]:
    """Exact order-statistic quantiles over one round's BRB delivery
    latencies (a handful of host floats — no need for the registry's
    bucketed estimates). Wall-clock: excluded from the bit-identity
    contract like ``duration_s``."""
    lats = sorted(latencies)
    if not lats:
        return {"count": 0}

    def q(f: float) -> float:
        return lats[min(len(lats) - 1, int(f * len(lats)))]

    return {
        "count": len(lats),
        "p50": q(0.50),
        "p90": q(0.90),
        "p99": q(0.99),
        "max": lats[-1],
    }


class _LazyDigests(Mapping):
    """Deferred digest table backed by an in-flight async D2H copy.

    The driver starts ``copy_to_host_async()`` on the packed digest buffer
    at dispatch time and hands THIS mapping to the trust plane; the first
    key access resolves the copy (by then the transfer has been riding
    under the trust plane's quorum reconfigure / broadcast prep, so the
    blocking ``device_get`` is mostly a completion check) and hashes every
    row once. Resolution is idempotent and the driver force-resolves after
    the round, so ``driver.d2h_transfers`` counts exactly one transfer per
    round whether or not the trust plane touched a digest."""

    def __init__(self, resolve) -> None:
        self._resolve = resolve
        self._digests: Optional[dict[int, bytes]] = None

    def materialize(self) -> dict[int, bytes]:
        if self._digests is None:
            self._digests = self._resolve()
        return self._digests

    def __getitem__(self, key: int) -> bytes:
        return self.materialize()[key]

    def __iter__(self):
        return iter(self.materialize())

    def __len__(self) -> int:
        return len(self.materialize())


class _TrustPlane:
    """Host-side BRB over canonical update digests for one experiment.

    Each round, every trainer BRB-broadcasts ``crypto.digest_update`` of its
    actual delta (a collision-resistant SHA-256 commitment to the update's
    content — not the forgeable norm fingerprint of earlier builds); every
    peer must deliver every trainer's broadcast, and a delivered commitment
    is verified against the update the aggregate would admit. Runs over the
    deterministic in-memory hub (the TCP transport serves the multi-host
    control plane; simulation never needs sockets).

    ``lie_digests``: fault-injection hook — trainer id -> digest it falsely
    (but consistently) commits to, modeling a trainer whose broadcast
    delivers fine but does not match the update it actually submitted.

    ``cfg.brb_committee = m > 0`` scopes the Bracha quorum to a
    deterministic m-member committee instead of all P peers: trainers
    (committee or not) SEND into the committee, whose members echo/ready
    among themselves — O(m^2) control messages per broadcast instead of
    O(P^2), which is what makes the trust plane feasible at 1024+ peers
    (the standard committee-BRB scaling move; tolerance becomes f
    Byzantine COMMITTEE members). The committee is sampled once per
    experiment from ``cfg.seed``; per-round rotation is a deployment
    concern outside the simulation's scope.
    """

    def __init__(self, cfg: Config, byz_ids: tuple[int, ...] = ()) -> None:
        self.cfg = cfg
        self.key_server = KeyServer()
        self.hub = InMemoryHub()
        self.byz_ids = set(byz_ids)
        self.lie_digests: dict[int, bytes] = {}
        self.broadcasters: list[Broadcaster] = []
        # Latest run_round()'s quorum/latency digest (see the assignment
        # there for the schema); None until the first round runs.
        self.last_round_health: Optional[dict[str, Any]] = None
        # Coalesced control frames (wire v2, cfg.control_batching): handler
        # outputs accumulate per emitting peer per (kind, seq) and flush as
        # ONE signed batch frame per (src, dst) pair per phase instead of
        # one frame per vote — O(committee^2) frames per round instead of
        # O(T * committee^2). With batching on, per-vote signatures are dead
        # weight (the batch signature covers them), so the broadcasters skip
        # them (sign_control=False); SENDs stay individually signed.
        self.batching = bool(cfg.control_batching)
        self._pending: dict[int, dict[tuple[str, int], list]] = {}
        if cfg.brb_committee and cfg.brb_committee < cfg.num_peers:
            rng = np.random.default_rng(cfg.seed)
            self.committee = sorted(
                int(p)
                for p in rng.choice(cfg.num_peers, cfg.brb_committee, replace=False)
            )
        else:
            self.committee = list(range(cfg.num_peers))
        brb_cfg = BRBConfig(len(self.committee), cfg.byzantine_f)
        # Live membership view: run_round() shrinks this to the non-suspected
        # committee members so quorums recompute over peers that can actually
        # vote instead of timing out against the dead.
        self._live_committee = list(self.committee)
        self._keys = []
        # Every peer gets a keypair + broadcaster (any peer can be sampled
        # as a trainer and must be able to originate a SEND); only
        # committee members vote — their handlers alone are registered, so
        # a non-member never echoes and cannot count toward any quorum.
        for pid in range(cfg.num_peers):
            priv, pub = generate_key_pair()
            self.key_server.register_key(pid, pub)
            self._keys.append(priv)
            self.broadcasters.append(
                Broadcaster(
                    brb_cfg, pid, self.key_server, priv,
                    sign_control=not self.batching,
                )
            )
        for pid in self.committee:
            self.hub.register(pid, self._make_handler(pid))

    def _make_handler(self, pid: int):
        def handler(src: int, data: bytes) -> None:
            msg = control_from_wire(data)
            if msg is None:
                return
            if isinstance(msg, BRBBatch):
                outs = self.broadcasters[pid].handle_batch(msg)
            else:
                outs = self.broadcasters[pid].handle(msg)
            if self.batching:
                # Buffer this peer's reaction votes; run_round's pump/flush
                # loop coalesces them into one signed frame per (kind, seq).
                buf = self._pending.setdefault(pid, {})
                for out in outs:
                    buf.setdefault((out.kind, out.seq), []).append(
                        (out.sender, out.digest)
                    )
            else:
                for out in outs:
                    self._fan_out(pid, out)

        return handler

    def _fan_out(self, src: int, msg) -> None:
        # Fan out to every LIVE committee member INCLUDING self (when src is
        # one): in Bracha each voting peer echoes, readies, and counts its
        # own votes. With the full committee and no suspicions this is
        # every peer; suspected members get nothing (their links are dead
        # anyway — skipping them keeps control-message accounting honest).
        wire = brb_to_wire(msg)
        telemetry.counter("control.frames", mode="per_message").inc(
            len(self._live_committee)
        )
        for dst in self._live_committee:
            self.hub.send(src, dst, wire)

    def _flush_pending(self) -> int:
        """Drain the vote buffer: one signed batch per (peer, kind, seq)
        group, fanned out to the live committee. Returns frames sent."""
        if not self._pending:
            return 0
        pending, self._pending = self._pending, {}
        frames = 0
        for pid, groups in pending.items():
            for (kind, seq), items in groups.items():
                batch = self.broadcasters[pid].make_batch(kind, seq, items)
                wire = batch_to_wire(batch)
                telemetry.counter("control.frames", mode="batched", kind=kind).inc(
                    len(self._live_committee)
                )
                telemetry.counter("control.batched_digests", kind=kind).inc(
                    len(items)
                )
                for dst in self._live_committee:
                    self.hub.send(pid, dst, wire)
                    frames += 1
        return frames

    def _payload(self, round_idx: int, tid: int, digest: bytes) -> bytes:
        return json.dumps(
            {"round": round_idx, "trainer": tid, "digest": digest.hex()}
        ).encode()

    def run_round(
        self,
        round_idx: int,
        trainer_ids: list[int],
        digests: dict[int, bytes],
        dark: frozenset[int] = frozenset(),
    ) -> tuple[int, list[int], list[int]]:
        """Broadcast each trainer's update digest; returns ``(#peers that
        delivered every honest trainer's broadcast, ids of peers that did
        not, ids of trainers whose commitment both delivered and verified)``.

        A trainer makes the verified list iff (a) every non-failed peer
        delivered its broadcast, and (b) the delivered commitment matches
        ``digests[tid]`` — the digest of the update the aggregate would
        actually admit (each peer's verify step; in simulation all peers
        share the device state, so one recomputation stands for all).
        Byzantine trainers equivocate: half the peers receive a forged
        digest — correct BRB then either delivers one payload consistently
        (caught by (b)) or delivers nothing (caught by (a)).

        ``dark`` is the failure detector's suspicion set: suspected
        committee members are dropped from the round's voting set and the
        Bracha quorums recompute over the survivors (graceful degradation —
        a quorum sized for n voters would wait forever on n - |dark|), as
        long as the live set keeps ``n > 3f``; below that the full
        committee config is kept (shrinking further would let f Byzantine
        voters forge a quorum, so the round is allowed to fail loudly
        instead)."""
        self._pending.clear()  # no votes may leak across round boundaries
        live = [p for p in self.committee if p not in dark]
        if dark and len(live) > 3 * self.cfg.byzantine_f:
            live_cfg = BRBConfig(len(live), self.cfg.byzantine_f)
            if len(live) < len(self.committee):
                flight.record(
                    "quorum_reconfig",
                    round=round_idx,
                    live=len(live),
                    committee=len(self.committee),
                    f=self.cfg.byzantine_f,
                    suspected=sorted(dark),
                )
        else:
            if dark:
                # Suspicion shrank the committee past n > 3f: quorums cannot
                # recompute safely, so the full config is kept and the round
                # is allowed to fail loudly — a health anomaly by definition.
                flight.anomaly(
                    "quorum_collapse",
                    round=round_idx,
                    live=len(live),
                    committee=len(self.committee),
                    f=self.cfg.byzantine_f,
                    suspected=sorted(dark),
                )
            live = list(self.committee)
            live_cfg = BRBConfig(len(self.committee), self.cfg.byzantine_f)
        self._live_committee = live
        for bc in self.broadcasters:
            bc.reconfigure(live_cfg)
        for tid in trainer_ids:
            committed = self.lie_digests.get(tid, digests[tid])
            payload = self._payload(round_idx, tid, committed)
            if tid in self.byz_ids:
                forged = self._payload(
                    round_idx, tid, b"\x00" * 31 + bytes([tid % 256])
                )
                send_a, send_b = self.broadcasters[tid].broadcast_equivocating(
                    round_idx, payload, forged
                )
                half = len(live) // 2
                for rank, dst in enumerate(live):
                    wire = brb_to_wire(send_a if rank < half else send_b)
                    self.hub.send(tid, dst, wire)
            else:
                for msg in self.broadcasters[tid].broadcast(round_idx, payload):
                    self._fan_out(tid, msg)
        # Pump to quiescence, alternating delivery with batch flushes: each
        # pump drains the in-flight frames (handlers buffer their reaction
        # votes under batching), each flush turns the buffered votes into
        # the next wave of signed frames. Done when neither moves anything.
        deadline = time.monotonic() + self.cfg.round_timeout_s
        while time.monotonic() < deadline:
            delivered = self.hub.pump()
            flushed = self._flush_pending()
            if not delivered and not flushed:
                break
        honest_trainers = [t for t in trainer_ids if t not in self.byz_ids]
        delivered_at = {
            tid: [
                pid
                for pid in live
                if self.broadcasters[pid].delivered(tid, round_idx) is not None
            ]
            for tid in trainer_ids
        }
        # Sender vs receiver failure: a broadcast nobody delivered is the
        # SENDER's failure (dead or equivocating trainer) — it must not mark
        # every receiver suspect. A voting peer is failed iff it missed a
        # broadcast its peers did deliver (Bracha totality: once one honest
        # peer delivers, all honest peers do — the hub pumps to quiescence,
        # so non-delivery at quiescence is a real receiver fault).
        sender_failed = {t for t in honest_trainers if not delivered_at[t]}
        failed = [
            pid
            for pid in live
            if any(
                pid not in delivered_at[tid]
                for tid in honest_trainers
                if tid not in sender_failed
            )
        ]
        live_peers = [p for p in live if p not in failed]
        verified: list[int] = []
        for tid in trainer_ids:
            expected = self._payload(round_idx, tid, digests[tid])
            # live_peers can only be empty under total failure — nothing is
            # verified then (no vacuous-truth admits).
            if live_peers and all(
                self.broadcasters[pid].delivered(tid, round_idx) == expected
                for pid in live_peers
            ):
                verified.append(tid)
                # Digest-lineage taint rule: everything the aggregate admits
                # leaves an agg_admit event whose digest the auditor matches
                # against a brb_deliver for the same (trainer, round).
                flight.record(
                    "agg_admit",
                    round=round_idx,
                    trainer=tid,
                    digest=hashlib.sha256(expected).hexdigest(),
                )
        # Per-instance quorum margins and delivery latencies for the round's
        # health summary: margin = ready votes beyond the delivery quorum on
        # the digest that actually delivered (0 = delivered with zero slack).
        margins: list[int] = []
        latencies: list[float] = []
        for pid in live_peers:
            for tid in trainer_ids:
                inst = self.broadcasters[pid].instances.get((tid, round_idx))
                if inst is None or inst.delivered_digest is None:
                    continue
                margins.append(
                    len(inst.readies[inst.delivered_digest])
                    - inst.cfg.deliver_quorum
                )
                if inst.delivery_latency_s is not None:
                    latencies.append(inst.delivery_latency_s)
        self.last_round_health = {
            "live_committee": len(live),
            "deliver_quorum": live_cfg.deliver_quorum,
            "quorum_margin_min": min(margins) if margins else None,
            "deliveries": len(margins),
            "latencies": latencies,  # wall-clock; quantiled by the driver
        }
        for pid, bc in enumerate(self.broadcasters):
            # Committee members report undelivered instances as brb_timeout
            # anomalies; a non-committee trainer's own SEND instance never
            # completes by design and must not count as one.
            bc.prune(round_idx, report_timeouts=pid in live)
        return len(live) - len(failed), failed, verified


class Experiment:
    """One configured federated experiment: data, state, compiled round."""

    def __init__(
        self,
        cfg: Config,
        attack: str = "none",
        byz_ids: tuple[int, ...] = (),
        log_path: Optional[str] = None,
        n_devices: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 1,
        profile_dir: Optional[str] = None,
        failure_cooldown_rounds: int = 0,
        fault_plan: Optional[Any] = None,
        pipeline: bool = True,
        pipeline_depth: int = 2,
        perf: bool = False,
        audit: bool = False,
        autotune: bool = False,
    ) -> None:
        self.cfg = cfg
        self.attack = attack
        self.byz_ids = tuple(byz_ids)
        # Overlap autotuner (parallel/autotune.py): hill-climbs
        # pipeline_depth (run_rounds) or rounds_per_call (run_fused) from
        # the measured RoundRecord durations. Lazily constructed by
        # whichever loop runs — the knob depends on the mode.
        self.autotune = bool(autotune)
        self._autotuner = None
        # Pipelined round loop (run_rounds/run): eval dispatches async and
        # its scalars — plus the per-peer loss readback — are fetched up to
        # ``pipeline_depth`` rounds late, so rounds r+1..r+k's device work
        # overlaps round r's host tail. Each in-flight round parks its
        # readbacks in its own slot of a bounded deque (per-slot buffers:
        # the compiled programs donate the state carry, so k slots hold k
        # rounds' loss/eval buffers, not k copies of the working set). The
        # deferred readbacks land BEFORE a round that needs them samples
        # roles (power_of_choice drains the window first and so degrades
        # to depth 1 — it needs round r-1's losses), at checkpoint
        # boundaries, and at exit, so the RoundRecord stream is
        # bit-identical (minus duration_s) at every depth, pipelining on
        # or off. run_round() stays fully synchronous.
        self.pipeline = bool(pipeline)
        if pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {pipeline_depth}"
            )
        self.pipeline_depth = int(pipeline_depth)
        self._pending_rounds: collections.deque[dict] = collections.deque()
        # Single-transfer digesting state (lazy: built from the first
        # round's delta tree; row hashing runs on the shared module pool).
        self._digest_pack = None
        # Chaos plane: a FaultPlan (object, scenario name, inline JSON, or
        # JSON file path) drives deterministic fault injection; the failure
        # detector always exists (empty suspicion set without faults) so
        # the membership view is one code path, not two.
        self.faults = None
        if fault_plan is not None:
            plan = resolve_plan(
                fault_plan, cfg.num_peers, cfg.rounds,
                f=cfg.byzantine_f, seed=cfg.seed,
            )
            self.faults = FaultInjector(plan, cfg.num_peers)
        self.detector = FailureDetector(cfg.num_peers, cfg.suspicion_threshold)
        # Failure detection -> exclusion (reference has none: one silent peer
        # stalls its round forever, reference ``node/node.py:73`` +
        # ``utils/waiting.py``). Peers whose BRB delivery failed are excluded
        # from trainer sampling for this many subsequent rounds, then
        # re-admitted. Suspicion is runtime-ephemeral (a resumed experiment
        # starts with a clean slate, like any real failure detector).
        self.failure_cooldown_rounds = failure_cooldown_rounds
        self._suspect_until: dict[int, int] = {}
        self.mesh = make_mesh(
            n_devices,
            seq_shards=cfg.seq_shards,
            tp_shards=cfg.tp_shards,
            ep_shards=cfg.ep_shards,
            pp_shards=cfg.pp_shards,
        )
        self.data = make_federated_data(cfg)
        # Secure aggregation keys: real ECDH key agreement over per-peer
        # P-256 keypairs (protocol/secure_keys) — masks underivable from
        # public state, unlike round 3's shared-experiment-key derivation
        # (kept as secure_agg_keys="shared" for A/B benchmarking). Seeded
        # from cfg.seed so checkpoint/resume stays bit-exact; Shamir shares
        # of every private scalar are distributed at setup so a trainer
        # dropping AFTER masking can have its orphaned masks reconstructed
        # and cancelled (the BRB gate-out path in run_round).
        self.secure_keyring = None
        self._seed_mat = None
        self._pair_seeds_dev = None
        pair_seeds = None
        if cfg.aggregator == "secure_fedavg" and cfg.secure_agg_keys == "ecdh":
            from p2pdl_tpu.protocol.secure_keys import SecureAggKeyring

            self.secure_keyring = SecureAggKeyring(cfg.num_peers, seed=cfg.seed)
            if cfg.secure_agg_rekey == "round":
                # Per-round rekey derives a fresh matrix at the top of every
                # round (run_round) — the setup matrix would be dead cost
                # (O(P^2/2) ECDH), so start from a zero placeholder of the
                # right shape/dtype.
                pair_seeds = np.zeros((cfg.num_peers, cfg.num_peers, 2), np.uint32)
            else:
                # O(P^2/2) ECDH once per experiment (~1min at P=1024; a
                # simulation artifact — deployed peers each do O(P) in
                # parallel). Shares only matter where dropout recovery can
                # run (the gated pipeline), so don't pay Shamir on the
                # fused path.
                pair_seeds = self.secure_keyring.seed_matrix()
            self._seed_mat = pair_seeds
        # Layouts with the trust plane on use a split (two-program) round so
        # the BRB verdict lands BETWEEN the phases: sync layouts gate the
        # aggregate, the gossip layout gates the mixing weights (an
        # unverified peer's params never enter any honest peer's round-r
        # mix). Everything else runs the fused single-program round.
        self._gated = cfg.brb_enabled and params_layout(cfg) == "sync"
        self._gated_gossip = cfg.brb_enabled and params_layout(cfg) == "peer"
        self.round_fn = None
        if self._gated:
            if self.secure_keyring is not None:
                committees = None
                if cfg.secure_agg_rekey == "round" and cfg.secure_agg_neighbors:
                    # Bell k-ring at scale: shares live with each peer's
                    # 2k-neighbor committee on the static id ring, so the
                    # per-round share refresh is O(k^2) field ops per
                    # rotated peer instead of O(P x t).
                    from p2pdl_tpu.protocol.secure_keys import ring_committees

                    committees = ring_committees(
                        cfg.num_peers, cfg.secure_agg_neighbors
                    )
                self.secure_keyring.distribute_shares(committees=committees)
                self._pair_seeds_dev = jnp.asarray(pair_seeds)
            self.train_fn, self.agg_fn = build_trust_round_fns(
                cfg, self.mesh, attack=attack, pair_seeds=pair_seeds
            )
        elif self._gated_gossip:
            self.train_fn, self.mix_fn = build_gossip_trust_round_fns(
                cfg, self.mesh, attack=attack
            )
        else:
            self.round_fn = build_round_fn(
                cfg, self.mesh, attack=attack, pair_seeds=pair_seeds
            )
        self.eval_fn = build_eval_fn(cfg)
        self.metrics = MetricsLogger(log_path)
        self.trust = _TrustPlane(cfg, byz_ids) if cfg.brb_enabled else None
        if self.faults is not None and self.trust is not None:
            # Message-fate hooks route every control message through the
            # fault model; partitions are pushed per round (apply_round).
            self.faults.install(self.trust.hub)
        self.profiler = Profiler(profile_dir)
        # Performance-attribution plane. The recompile sentinel is ALWAYS
        # on: its per-round check is a host-side jit-cache-size probe (no
        # device sync), and "no recompile" is a load-bearing invariant that
        # deserves runtime detection, not just comments. The XLA cost-model
        # capture is opt-in (``perf=True`` / ``cli run --perf``): its AOT
        # ``lower().compile()`` snapshot costs one extra backend compile
        # per program (the AOT executable does not share the jit cache).
        self.sentinel = devprof.RecompileSentinel()
        self.cost_model = (
            devprof.CostModel(n_devices=self.mesh.devices.size) if perf else None
        )
        # Conformance auditor (opt-in, ``audit=True`` / ``cli run --audit``):
        # re-checks the BRB safety / quorum / digest-lineage invariants over
        # the live flight stream once per round. It consumes the event ring,
        # so turning it on force-enables recording; honest runs report
        # nothing, which keeps the RoundRecord stream bit-identical with the
        # auditor off (violations are anomalies, and anomalies are counted
        # unconditionally either way).
        self.auditor = None
        self._audit_cursor = 0
        if audit:
            from p2pdl_tpu.protocol.audit import ProtocolAuditor

            flight.set_enabled(True)
            self.auditor = ProtocolAuditor(registered=range(cfg.num_peers))
        for fn in (
            self.round_fn,
            getattr(self, "train_fn", None),
            getattr(self, "agg_fn", None),
            getattr(self, "mix_fn", None),
            self.eval_fn,
        ):
            if fn is not None:
                self.sentinel.register(getattr(fn, "program_name", "round"), fn)

        # Last known per-peer local losses (power_of_choice selection).
        # OBSERVATIONAL runtime state, like the failure-suspicion table:
        # not checkpointed, so the first post-resume round samples
        # uniformly where the uninterrupted run may have biased.
        self._peer_losses = None
        self.checkpointer = None
        self.checkpoint_every = max(1, checkpoint_every)
        # Experiment identity beyond the Config — validated on resume so a
        # Byzantine run's checkpoint can't silently continue as an honest one.
        self._ckpt_extra = {"attack": attack, "byz_ids": list(self.byz_ids)}
        state = None
        if checkpoint_dir is not None:
            from p2pdl_tpu.utils.checkpoint import Checkpointer

            self.checkpointer = Checkpointer(checkpoint_dir)
            if self.checkpointer.latest_step() is not None:
                state = self.checkpointer.restore(cfg, extra=self._ckpt_extra)
        if state is None:
            state = init_peer_state(cfg)

        from p2pdl_tpu.parallel.mesh import data_sharding

        self.state = shard_state(state, cfg, self.mesh)
        self.x = jax.device_put(self.data.x, data_sharding(self.mesh))
        self.y = jax.device_put(self.data.y, peer_sharding(self.mesh))
        byz_gate = np.zeros(cfg.num_peers, np.float32)
        for i in self.byz_ids:
            byz_gate[i] = 1.0
        self.byz_gate = jnp.asarray(byz_gate)
        self.records: list[RoundRecord] = []
        # Host-side round counter mirroring state.round_idx — reading the
        # device copy (int(self.state.round_idx)) would synchronize on the
        # in-flight aggregate, which is exactly what the pipelined loop
        # avoids. Resume-aware: starts at the restored round.
        # p2plint: disable=hostsync-transfer -- one-time readback at construction/resume, before the round loop starts
        self._round_cursor = int(self.state.round_idx)

    def sample_roles(self, round_idx: Optional[int] = None) -> np.ndarray:
        """Random trainer sample per round (reference ``main.py:52-54``).

        Keyed by ``(seed, round_idx)`` — not by a stateful generator — so a
        resumed experiment samples the exact roles the uninterrupted run
        would have (checkpoint/resume determinism). Exception: with
        ``failure_cooldown_rounds`` active, the suspicion table is runtime
        state, so a resume right after a peer failure can sample that peer
        where the uninterrupted run would not — suspicion is observational,
        not part of the training state."""
        if round_idx is None:
            round_idx = self._round_cursor
        rng = np.random.default_rng([self.cfg.seed, round_idx])
        eligible = np.asarray(
            [
                p
                for p in range(self.cfg.num_peers)
                if self._suspect_until.get(p, -1) < round_idx
                and p not in self.detector.suspected
            ]
        )
        if len(eligible) < self.cfg.trainers_per_round:
            if self.cfg.aggregator in ("fedavg", "secure_fedavg") and len(eligible) > 0:
                # Shrink participation: run the round with the survivors; the
                # compiled round accepts -1 vacancy padding and normalizes by
                # the live count, so no recompile.
                chosen = np.sort(eligible)
                pad = np.full(self.cfg.trainers_per_round - len(chosen), -1, chosen.dtype)
                return np.concatenate([chosen, pad])
            # Robust reducers need their full [T] update matrix: degrade to
            # the full peer set rather than shrinking the trainer quorum.
            eligible = np.arange(self.cfg.num_peers)
        t = self.cfg.trainers_per_round
        if (
            self.cfg.selection == "power_of_choice"
            and self._peer_losses is not None
        ):
            # Power-of-Choice (Cho et al. 2020): d uniform candidates, keep
            # the T with the highest last-known local loss. The candidate
            # draw stays keyed on (seed, round) like the uniform sampler.
            d = self.cfg.poc_candidates or min(2 * t, len(eligible))
            d = max(t, min(d, len(eligible)))
            candidates = rng.choice(eligible, d, replace=False)
            by_loss = candidates[
                np.argsort(-np.asarray(self._peer_losses)[candidates])
            ]
            return np.sort(by_loss[:t])
        return np.sort(rng.choice(eligible, t, replace=False))

    def _run_trust_plane(
        self, r: int, live: np.ndarray, delta, padded: Optional[np.ndarray] = None
    ) -> tuple:
        """Digest each live trainer's on-device delta, BRB-broadcast the
        commitments, account control traffic, and feed the failure detector
        (both receiver failures and excluded senders enter cooldown).
        Returns ``(delivered, failed, excluded, verified, msgs, nbytes)``.

        Single-transfer digesting: the per-trainer, per-leaf ``np.asarray``
        gathers of earlier builds cost one device->host transfer per (leaf,
        trainer) — O(T * leaves) blocking round trips. Here a jitted pack
        step (``parallel.build_digest_pack_fn``) flattens every trainer's
        delta into one contiguous ``[T, total_bytes]`` device buffer, ONE
        ``jax.device_get`` moves it — started asynchronously at dispatch
        and resolved lazily through :class:`_LazyDigests` so the copy
        overlaps the trust plane's quorum prep — and the per-row SHA-256
        (bit-identical to ``crypto.digest_update``) runs on a small host
        thread pool — sha256 releases the GIL on large buffers, so rows
        hash in parallel.

        ``padded`` is the round's full trainer vector including -1 vacancy
        slots (the pack function needs a static shape; vacant rows are
        packed-then-skipped); default ``live`` when there is no padding.
        """
        if padded is None:
            padded = live
        if self._digest_pack is None:
            # Wire-format routing: under delta_compression the pack emits
            # the COMPRESSED [T, compressed_bytes] buffer and hash_row
            # digests those wire bytes — BRB signs what ships, the
            # aggregate phase consumes the codec roundtrip of the same
            # rows, and everything downstream (agg_admit lineage, cli
            # audit, tower causal digests) carries the compressed digests
            # with zero protocol changes. Same (pack_fn, hash_row) shape,
            # same sentinel registration, same one-D2H-per-round.
            if self.cfg.delta_compression != "none":
                self._digest_pack = build_compressed_pack_fn(
                    delta,
                    self.cfg.delta_compression,
                    self.cfg.compress_ratio,
                )
            else:
                self._digest_pack = build_digest_pack_fn(delta)
            self.sentinel.register(
                getattr(self._digest_pack[0], "program_name", "digest_pack"),
                self._digest_pack[0],
            )
        pack_fn, hash_row = self._digest_pack
        # p2plint: disable=hostsync-transfer -- host-side trainer-id list, no device buffer involved
        padded_host = np.asarray(padded)
        padded_dev = jnp.asarray(padded_host, jnp.int32)
        if self.cost_model is not None:
            self.cost_model.capture("digest_pack", pack_fn, (delta, padded_dev))
        with self.sentinel.guard("digest_pack", r):
            packed = pack_fn(delta, padded_dev)
        # Async readback: kick the D2H copy off NOW and resolve it only
        # when the trust plane first touches a digest (building the SEND
        # payloads, after its live-set/quorum reconfigure prep), so the
        # transfer rides under the committee work instead of stalling the
        # round loop right here. copy_to_host_async is best-effort — on
        # backends without it the lazy resolution simply blocks exactly
        # where the synchronous path used to.
        try:
            packed.copy_to_host_async()
        except AttributeError:
            pass

        def _resolve() -> dict[int, bytes]:
            # p2plint: disable=hostsync-transfer -- THE audited single device->host transfer per round (driver.d2h_transfers); the copy was started async at dispatch
            buf = np.asarray(jax.device_get(packed))  # the round's one D2H
            telemetry.counter("driver.d2h_transfers").inc()
            flight.record("d2h", round=r, nbytes=int(buf.nbytes))
            pool = _digest_pool()
            futures = {
                int(t): pool.submit(hash_row, buf[i])
                for i, t in enumerate(padded_host)
                if t >= 0
            }
            return {t: f.result() for t, f in futures.items()}

        digests = _LazyDigests(_resolve)
        m0, b0 = self.trust.hub.messages_sent, self.trust.hub.bytes_sent
        delivered, failed, verified = self.trust.run_round(
            r, live.tolist(), digests, dark=frozenset(self.detector.suspected)
        )
        # The one-transfer-per-round accounting invariant holds even when
        # no payload ever touched the table (an empty trainer round).
        digests.materialize()
        excluded = sorted(set(live.tolist()) - set(verified))
        msgs = self.trust.hub.messages_sent - m0
        nbytes = self.trust.hub.bytes_sent - b0
        telemetry.gauge("driver.live_peers").set(delivered)
        health = self.trust.last_round_health
        if health is not None and health["quorum_margin_min"] is not None:
            telemetry.gauge("driver.quorum_margin_min").set(
                health["quorum_margin_min"]
            )
        # Per-peer failure counters: a peer that keeps missing deliveries
        # across rounds shows up as a hot series, not a scalar average.
        for pid in failed:
            # p2plint: disable=telemetry-cardinality -- deliberate per-peer failure series, O(num_peers) and folded past the registry cap
            telemetry.counter("driver.brb_delivery_failures", peer=pid).inc()
        for tid in excluded:
            # p2plint: disable=telemetry-cardinality -- deliberate per-trainer exclusion series, O(num_peers) and folded past the registry cap
            telemetry.counter("driver.brb_excluded_trainers", trainer=tid).inc()
        if self.failure_cooldown_rounds > 0:
            for pid in failed + excluded:
                self._suspect_until[pid] = r + self.failure_cooldown_rounds
        return delivered, failed, excluded, verified, msgs, nbytes

    def _dp_epsilon(self, rounds_done: int) -> Optional[float]:
        """Cumulative (eps, cfg.dp_delta)-DP spent after ``rounds_done``
        noisy releases; None when DP is off."""
        if self.cfg.dp_noise_multiplier <= 0.0:
            return None
        from p2pdl_tpu.utils.dp import rdp_epsilon

        eps, _ = rdp_epsilon(
            self.cfg.dp_noise_multiplier, rounds_done, self.cfg.dp_delta
        )
        return round(eps, 4)

    def _recover_dropped_masks(self, r: int, dropped: list[int]) -> list[int]:
        """Shamir dropout recovery for trainers gated out after masking.

        For each dropped trainer, the live holders (not dropped, not
        suspected, not crashed) reconstruct its private scalar from their
        shares and re-derive its pairwise-seed row; the row is verified by
        patching it into a wiped copy of the live seed matrix
        (``secure_agg.patch_seed_rows``) and checking it reproduces the
        entries actually baked into the compiled round. Returns the peers
        whose seeds recovered bit-exact; under-threshold or mismatching
        recoveries count ``chaos.mask_recovery{outcome=...}`` and are left
        out — the caller can see a failed recovery in the record.
        """
        from p2pdl_tpu.ops.secure_agg import patch_seed_rows

        crashed = self.faults.crashed if self.faults is not None else frozenset()
        holders = [
            p
            for p in range(self.cfg.num_peers)
            if p not in dropped
            and p not in self.detector.suspected
            and p not in crashed
        ]
        recovered: list[int] = []
        for tid in dropped:
            try:
                row = self.secure_keyring.reconstruct_seeds_for_dropped(
                    tid, holders
                )
            except ValueError:
                telemetry.counter("chaos.mask_recovery", outcome="failed").inc()
                flight.record(
                    "mask_recovery", round=r, peer=tid, outcome="failed"
                )
                continue
            wiped = self._seed_mat.copy()
            wiped[tid, :, :] = 0
            wiped[:, tid, :] = 0
            patched = patch_seed_rows(wiped, {tid: row})
            # Compare only pairs the baked matrix actually uses: the ring
            # derivation zeroes non-neighbor pairs, the recovery row has
            # every pair.
            used = (self._seed_mat[tid] != 0).any(axis=-1)
            if np.array_equal(patched[tid][used], self._seed_mat[tid][used]):
                recovered.append(tid)
                telemetry.counter("chaos.mask_recovery", outcome="recovered").inc()
                flight.record(
                    "mask_recovery", round=r, peer=tid, outcome="recovered"
                )
            else:
                telemetry.counter("chaos.mask_recovery", outcome="mismatch").inc()
                flight.record(
                    "mask_recovery", round=r, peer=tid, outcome="mismatch"
                )
        return recovered

    def run_round(self, trainers: Optional[np.ndarray] = None) -> RoundRecord:
        """Run one round, fully synchronously: any deferred readbacks from
        a pipelined loop are flushed first and this round's record is
        materialized before returning. ``trainers`` overrides role sampling
        (the Cluster facade passes the set its Nodes consented to, reference
        ``main.py:59-76``); default samples per ``sample_roles``."""
        return self._run_one_round(trainers, defer=False)

    def _run_one_round(
        self, trainers: Optional[np.ndarray] = None, defer: bool = False
    ) -> Optional[RoundRecord]:
        """Dispatch one round. With ``defer=True`` the host-blocking
        readbacks (per-peer losses, eval scalars) are parked in a slot of
        ``_pending_rounds`` and resolved once the in-flight window fills
        past ``pipeline_depth`` (or at an explicit flush) — by then the
        device has finished them, so the fetch is free, and rounds
        r+1..r+k's device work overlaps round r's host tail.
        Returns the round's record, or None when deferred."""
        # Bound the in-flight window BEFORE this round's chaos/sampling.
        # Uniform/random selection only needs the window to stay <= depth
        # (oldest rounds flush first, preserving record order); biased
        # selection needs round r-1's losses to sample round r, so
        # power_of_choice drains the whole window — the same reason it is
        # split-path in run_fused — and the stream stays bit-identical to
        # the synchronous loop at every configured depth.
        if self.cfg.selection == "power_of_choice":
            self._flush_all_pending()
        else:
            while len(self._pending_rounds) >= self.pipeline_depth:
                self._flush_pending_round()
        r = self._round_cursor
        # Anomaly watermark: everything the flight recorder counts between
        # here and this round's pending-record build belongs to round r
        # (timeouts of round r-1's instances surface during round r's prune
        # and are attributed here — one round late, like the readbacks).
        anoms0 = flight.recorder().anomaly_count
        telemetry.gauge("driver.round_index").set(r)
        fault_events = suspected_now = excluded_now = None
        if self.faults is not None:
            fault_events = self.faults.begin_round(r)
            if self.trust is not None:
                self.faults.apply_round(self.trust.hub)
            # Heartbeats land BEFORE sampling: membership is decided on
            # entry to the round, so a peer crashing at round r (with the
            # default suspicion_threshold=2) is still sampled this round —
            # its masked-then-dropped delta is what exercises the Shamir
            # recovery path below — and is excluded from the next round on.
            responded = {
                p
                for p in range(self.cfg.num_peers)
                if self.faults.heartbeat_ok(r, p)
            }
            newly, recovered = self.detector.observe(r, responded)
            for p in newly:
                # p2plint: disable=telemetry-cardinality -- deliberate per-peer suspicion series, O(num_peers) and folded past the registry cap
                telemetry.counter("chaos.suspected", peer=p).inc()
                fault_events.append({"event": "suspected", "peer": p})
            for p in recovered:
                # p2plint: disable=telemetry-cardinality -- deliberate per-peer suspicion series, O(num_peers) and folded past the registry cap
                telemetry.counter("chaos.unsuspected", peer=p).inc()
                fault_events.append({"event": "unsuspected", "peer": p})
            suspected_now = sorted(self.detector.suspected)
            excluded_now = sorted(
                set(self.detector.suspected)
                | {p for p, until in self._suspect_until.items() if until >= r}
            )
        if trainers is None:
            trainers = self.sample_roles(r)
        else:
            trainers = np.sort(np.asarray(trainers, dtype=np.int64))
            if len(trainers) != self.cfg.trainers_per_round:
                raise ValueError(
                    f"explicit trainer list has {len(trainers)} entries, "
                    f"config expects trainers_per_round={self.cfg.trainers_per_round}"
                )
            if (trainers < 0).any() and self.cfg.aggregator not in (
                "fedavg", "secure_fedavg", "gossip"
            ):
                # The gathered/blockwise robust reducers index their full
                # [T] update matrix; a traced -1 would WRAP to peer P-1 and
                # feed a phantom update into the reducer (sample_roles
                # never pads -1 for them — guard explicit lists too).
                raise ValueError(
                    "vacant (-1) trainer slots require a mean-family "
                    "aggregator; robust reducers need their full update matrix"
                )
        # -1 entries are vacancy padding for a shrunken round (see
        # sample_roles); the device program consumes the padded vector, the
        # host plane (trust, metrics, records) only the live peers.
        live = trainers[trainers >= 0]
        telemetry.gauge("driver.suspected_peers").set(len(self.detector.suspected))
        flight.record(
            "round_begin",
            round=r,
            trainers=[int(t) for t in live],
            suspected=sorted(self.detector.suspected),
        )
        mask_key = jax.random.fold_in(jax.random.PRNGKey(self.cfg.seed), r)
        t0 = time.perf_counter()
        brb_delivered = brb_failed = brb_excluded = msgs = nbytes = None
        mask_recoveries = None
        loss_scope = "live"  # mean over live trainers vs every peer
        set_peer_losses = True  # gossip-gated never fed biased selection
        if self._gated:
            if (
                self.secure_keyring is not None
                and self.cfg.secure_agg_rekey == "round"
            ):
                # Full Bonawitz per-execution freshness: fresh ECDH keypair
                # + Shamir shares for THIS round, so a reconstructed scalar
                # can ever disclose exactly one round's masks. Generation =
                # absolute round index + 1, so a checkpoint resume
                # re-derives the SAME key schedule as the uninterrupted run
                # (bit-exact resume, and no scalar ever serves two rounds).
                # Fresh matrix object per round — the previous round's
                # device array is never touched.
                if self.cfg.secure_agg_neighbors:
                    # Bell k-ring: only the round's ring pairs ever mask,
                    # so rotate the round's (pre-gate) trainers and derive
                    # O(T*k) pair seeds — per-round freshness at 1024+
                    # peers. Unsampled peers keep their last-generation
                    # scalar; no pair of theirs is used this round, and a
                    # later rotation jumps straight to that round's
                    # generation (explicit index, not a counter bump).
                    for pid in sorted({int(t) for t in trainers if t >= 0}):
                        self.secure_keyring.rotate(pid, generation=r + 1)
                    self._seed_mat = self.secure_keyring.seed_matrix_ring(
                        trainers, self.cfg.secure_agg_neighbors
                    )
                else:
                    for pid in range(self.cfg.num_peers):
                        self.secure_keyring.rotate(pid, generation=r + 1)
                    self._seed_mat = self.secure_keyring.seed_matrix()
                self._pair_seeds_dev = jnp.asarray(self._seed_mat)
            # BRB-gated pipeline: train -> digest+BRB -> gated aggregate.
            if self.cost_model is not None:
                self.cost_model.capture(
                    "train", self.train_fn,
                    (self.state, self.x, self.y, self.byz_gate, mask_key),
                )
            with self.profiler.phase("round", round=r, trainers=len(live)):
                with self.profiler.phase("round.dispatch", round=r), \
                        self.sentinel.guard("train", r):
                    delta, new_opt, losses_dev = self.train_fn(
                        self.state, self.x, self.y, self.byz_gate, mask_key
                    )
            with self.profiler.phase(
                "brb", round=r, trainers=len(live),
                committee=len(self.trust.committee),
            ):
                brb_delivered, brb_failed, brb_excluded, verified, msgs, nbytes = (
                    self._run_trust_plane(r, live, delta, padded=trainers)
                )
                if self.cfg.aggregator in ("fedavg", "secure_fedavg"):
                    # Gate: a trainer whose commitment did not deliver+verify
                    # contributes nothing to THIS round's aggregate (the -1
                    # vacancy mechanism; no recompile). This is the
                    # reference's aggregate-only-delivered-verified semantic
                    # (reference ``node/node.py:130-145``,
                    # ``aggregator/aggregation.py:8-28``).
                    gated = np.where(np.isin(trainers, verified), trainers, -1)
                else:
                    # Gathered robust reducers need their full [T] update
                    # matrix and are content-robust in-band (tolerate f
                    # Byzantine updates by construction); delivery failures
                    # remain observational -> next-round sampling exclusion.
                    gated = trainers
            gated_dev = jnp.asarray(gated, jnp.int32)
            masked_dev = jnp.asarray(trainers, jnp.int32)
            if self.cost_model is not None:
                self.cost_model.capture(
                    "agg", self.agg_fn,
                    (self.state, delta, new_opt, gated_dev, mask_key),
                    {"masked_idx": masked_dev, "seeds": self._pair_seeds_dev},
                )
            with self.profiler.phase("agg", round=r):
                # masked_idx = the PRE-gate trainer vector: under
                # secure_fedavg every sampled trainer masked its delta
                # before the BRB verdict landed, so the aggregate must
                # cancel the orphaned masks gated-out trainers left behind
                # (residual_mask_sum; Shamir recovery in a deployment).
                with self.sentinel.guard("agg", r):
                    self.state = self.agg_fn(
                        self.state, delta, new_opt, gated_dev,
                        mask_key, masked_idx=masked_dev,
                        seeds=self._pair_seeds_dev,
                    )
            if (
                self.secure_keyring is not None
                and self.secure_keyring.shares_distributed
                and brb_excluded
            ):
                # Exercise the Bonawitz dropout-recovery flow end-to-end for
                # every gated-out trainer: survivors' Shamir shares
                # reconstruct the dropped scalar and re-derive its seed row
                # — proof (recorded per round) that the aggregate the gate
                # just admitted can still be unmasked without the dropped
                # peer. The SPMD engine already cancels the orphaned masks
                # from the baked matrix (residual_mask_sum), so this costs
                # one O(P) ECDH re-derivation per dropped trainer.
                mask_recoveries = self._recover_dropped_masks(r, brb_excluded)
            if (
                self.secure_keyring is not None
                and brb_excluded
                and self.cfg.secure_agg_rekey != "round"
            ):
                # (Under rekey="round" this is dead weight: next round's
                # full rekey supersedes any targeted rotation, and bumping
                # counters here would make the key schedule depend on
                # exclusion history.)
                # Disclosure hygiene: a gated-out trainer's scalar became
                # reconstructible (the recovery flow's premise), so rotate
                # its key before it can mask again — old shares say nothing
                # about the new scalar, restoring forward secrecy
                # (protocol/secure_keys.py disclosure-scope note). Runtime
                # seeds: no recompile. Rotate into a COPY: on the CPU
                # backend jnp.asarray zero-copies aligned numpy buffers, so
                # mutating the live matrix would corrupt the still-in-flight
                # async aggregate that is reading it.
                new_mat = self._seed_mat.copy()
                for pid in brb_excluded:
                    self.secure_keyring.rotate(pid, mat=new_mat)
                self._seed_mat = new_mat
                self._pair_seeds_dev = jnp.asarray(new_mat)
        elif self._gated_gossip:
            # BRB-gated gossip: train -> digest+BRB -> verdict-masked mix.
            # Every peer commits to its own PRE-mix delta; an unverified
            # peer's weight is zeroed in every neighbor's mixing row, so its
            # (possibly corrupted) params never enter any honest peer's
            # round-r mix — exclusion is in-round, not one round late.
            loss_scope = "all"
            set_peer_losses = False
            if self.cost_model is not None:
                self.cost_model.capture(
                    "train", self.train_fn,
                    (self.state, self.x, self.y, self.byz_gate, mask_key),
                )
            with self.profiler.phase("round", round=r, trainers=self.cfg.num_peers):
                with self.profiler.phase("round.dispatch", round=r), \
                        self.sentinel.guard("train", r):
                    attacked, new_opt, losses_dev, delta = self.train_fn(
                        self.state, self.x, self.y, self.byz_gate, mask_key
                    )
            with self.profiler.phase(
                "brb", round=r, trainers=self.cfg.num_peers,
                committee=len(self.trust.committee),
            ):
                # Gossip has no roles: EVERY peer mixes, so every peer must
                # commit its delta — the verdict covers the full peer set
                # (a peer outside the committee would otherwise be
                # unverifiable yet zero-weighted out of the mix).
                gossip_live = np.arange(self.cfg.num_peers)
                brb_delivered, brb_failed, brb_excluded, verified, msgs, nbytes = (
                    self._run_trust_plane(r, gossip_live, delta)
                )
                verdict = np.isin(
                    gossip_live, np.asarray(verified)
                ).astype(np.float32)
            verdict_dev = jnp.asarray(verdict)
            if self.cost_model is not None:
                self.cost_model.capture(
                    "mix", self.mix_fn, (self.state, attacked, new_opt, verdict_dev)
                )
            with self.profiler.phase("agg", round=r):
                with self.sentinel.guard("mix", r):
                    self.state = self.mix_fn(
                        self.state, attacked, new_opt, verdict_dev
                    )
        else:
            trainers_dev = jnp.asarray(trainers, jnp.int32)
            if self.cost_model is not None:
                self.cost_model.capture(
                    "round", self.round_fn,
                    (self.state, self.x, self.y, trainers_dev,
                     self.byz_gate, mask_key),
                )
            with self.profiler.phase("round", round=r, trainers=len(live)):
                with self.profiler.phase("round.dispatch", round=r), \
                        self.sentinel.guard("round", r):
                    self.state, m = self.round_fn(
                        self.state,
                        self.x,
                        self.y,
                        trainers_dev,
                        self.byz_gate,
                        mask_key,
                    )
                # Mean over this round's trainers only — non-trainers' local
                # losses exist on-device but the reference's progress metric
                # is trainer loss (``main.py:90-94`` collects from trainer
                # runs). Gossip has no roles: every peer trains, so every
                # loss counts.
                losses_dev = m["train_loss"]  # [P] device array
                if self.cfg.aggregator == "gossip":
                    loss_scope = "all"

        if self.cost_model is not None:
            self.cost_model.capture(
                "eval", self.eval_fn,
                (self.state, self.data.eval_x, self.data.eval_y),
            )
        with self.profiler.phase("eval", round=r):
            # Async dispatch: ev holds device scalars; forcing them here
            # would stall the host on the whole round's device chain, so the
            # float() readbacks happen at flush time, one round late.
            with self.sentinel.guard("eval", r):
                ev = self.eval_fn(self.state, self.data.eval_x, self.data.eval_y)
        # Recompile sentinel: runs INSIDE the round's anomaly watermark, so
        # an unexpected compile lands in this round's protocol_health
        # anomaly delta as well as the flight ring + recompiles counter.
        self.sentinel.check(r)
        # Live conformance audit: runs INSIDE the anomaly watermark like the
        # sentinel, so a violated invariant lands in this round's
        # protocol_health anomaly delta as well as the flight ring.
        if self.auditor is not None:
            self._audit_round(r)
        # Per-round protocol health: deterministic quorum facts plus the
        # flight recorder's anomaly delta (unconditional counting, so the
        # record is identical with the recorder on or off), plus wall-clock
        # latency quantiles in their own stripped-for-replay block.
        protocol_health = None
        if brb_delivered is not None and self.trust is not None:
            h = self.trust.last_round_health or {}
            protocol_health = {
                "live_committee": h.get("live_committee"),
                "deliver_quorum": h.get("deliver_quorum"),
                "quorum_margin_min": h.get("quorum_margin_min"),
                "deliveries": h.get("deliveries"),
                "anomalies": flight.recorder().anomaly_count - anoms0,
                "brb_latency_s": _latency_block(h.get("latencies") or []),
            }
        # duration_s is measured at the dispatch/defer point (and is the one
        # field excluded from the bit-identity contract, see RoundRecord).
        self._pending_rounds.append({
            "r": r,
            "live": live,
            "losses_dev": losses_dev,
            "loss_scope": loss_scope,
            "set_peer_losses": set_peer_losses,
            "ev": ev,
            "duration_s": time.perf_counter() - t0,
            # Overlap accounting: device work still in flight after this
            # point runs under the NEXT round's host time; the flush
            # measures how much of that tail stayed hidden vs. exposed.
            "dispatch_done_ts": self.profiler.clock(),
            "brb_delivered": brb_delivered,
            "brb_failed": brb_failed,
            "brb_excluded": brb_excluded,
            "msgs": msgs,
            "nbytes": nbytes,
            "dp_epsilon": self._dp_epsilon(r + 1),
            "fault_events": fault_events,
            "suspected_now": suspected_now,
            "excluded_now": excluded_now,
            "faults_injected": (
                dict(self.faults.round_injected) if self.faults is not None else None
            ),
            "mask_recoveries": mask_recoveries,
            "health": protocol_health,
        })
        self._round_cursor = r + 1
        # Dispatch-time window gauges: pipeline_depth is the CONFIGURED
        # bound (0 when the loop runs synchronously), inflight_rounds the
        # actual occupancy right after this dispatch — at steady state it
        # saturates at the depth; shallower readings mean something keeps
        # draining the window (checkpoints, biased selection, sync calls).
        telemetry.gauge("driver.pipeline_depth").set(
            self.pipeline_depth if (defer and self.pipeline) else 0
        )
        telemetry.gauge("driver.inflight_rounds").set(len(self._pending_rounds))
        boundary = (
            self.checkpointer is not None and (r + 1) % self.checkpoint_every == 0
        )
        record = None
        if not defer or boundary:
            # Checkpoint boundaries flush first so the saved state never
            # runs ahead of the recorded stream (sync-mode ordering).
            record = self._flush_all_pending()
        if boundary:
            self.checkpointer.save(self.state, self.cfg, extra=self._ckpt_extra)
        return record

    def _audit_round(self, r: int) -> None:
        """Feed the flight events recorded since the last audit into the
        conformance auditor; new violations surface as ``audit_violation``
        flight anomalies and a per-invariant counter. The cursor tails the
        ring (``events_page``), so each event is audited exactly once."""
        page = flight.recorder().events_page(since=self._audit_cursor)
        new = []
        for ev in page["events"]:
            new.extend(self.auditor.feed(ev))
        self._audit_cursor = page["next_cursor"]
        new.extend(self.auditor.check())
        for v in new:
            flight.anomaly(
                "audit_violation",
                invariant=v.invariant,
                detail=v.detail,
                round=r,
            )
            telemetry.counter("audit.violations", invariant=v.invariant).inc()

    def _flush_all_pending(self) -> Optional[RoundRecord]:
        """Drain the whole in-flight window, oldest round first; returns
        the LAST record materialized (None when nothing was pending)."""
        record = None
        while self._pending_rounds:
            record = self._flush_pending_round()
        return record

    def _flush_pending_round(self) -> Optional[RoundRecord]:
        """Resolve the deferred readbacks of the OLDEST in-flight round
        into its RoundRecord; no-op (None) when nothing is pending."""
        if not self._pending_rounds:
            return None
        p = self._pending_rounds.popleft()
        telemetry.gauge("driver.inflight_rounds").set(len(self._pending_rounds))
        flush_t0 = self.profiler.clock()
        with self.profiler.phase("round.device", round=p["r"]):
            # THE sanctioned device-completion site: the flush must consume
            # these buffers anyway; blocking explicitly here (instead of
            # letting np.asarray block implicitly below) isolates the
            # residual device wait from the D2H copy time — the split the
            # overlap metric is made of.
            jax.block_until_ready((p["losses_dev"], p["ev"]))  # p2plint: disable=hostsync-transfer -- sanctioned device-completion sub-phase: the deferred flush blocks here by design
        with self.profiler.phase("round.d2h", round=p["r"]):
            # p2plint: disable=hostsync-transfer -- sanctioned deferred readback: flushes the previous round after the next one is in flight
            losses = np.asarray(p["losses_dev"])  # [P]
            ev = p["ev"]
            eval_loss = float(ev["eval_loss"])  # p2plint: disable=hostsync-transfer -- ev is host data in the deferred flush
            eval_acc = float(ev["eval_acc"])  # p2plint: disable=hostsync-transfer -- ev is host data in the deferred flush
        # hidden = device tail that ran under the next round's host work;
        # exposed = what this flush actually waited (device residual + D2H).
        # Host-side wall clock only — feeds gauges/summary, never records.
        exposed_s = self.profiler.clock() - flush_t0
        hidden_s = max(0.0, flush_t0 - p["dispatch_done_ts"])
        self.profiler.add_overlap(hidden_s, exposed_s)
        eff = self.profiler.overlap.efficiency()
        if eff is not None:
            telemetry.gauge("driver.overlap_efficiency").set(eff)
        if p["set_peer_losses"]:
            self._peer_losses = losses  # feeds biased selection
        row = losses if p["loss_scope"] == "all" else losses[p["live"]]
        record = RoundRecord(
            round=p["r"],
            trainers=p["live"].tolist(),
            train_loss=float(np.mean(row)),
            eval_loss=eval_loss,
            eval_acc=eval_acc,
            duration_s=p["duration_s"],
            brb_delivered=p["brb_delivered"],
            brb_failed_peers=p["brb_failed"],
            brb_excluded_trainers=p["brb_excluded"],
            control_messages=p["msgs"],
            control_bytes=p["nbytes"],
            dp_epsilon=p["dp_epsilon"],
            fault_events=p["fault_events"],
            suspected_peers=p["suspected_now"],
            excluded_peers=p["excluded_now"],
            faults_injected=p["faults_injected"],
            mask_recoveries=p["mask_recoveries"],
            protocol_health=p["health"],
        )
        flight.record("pipeline_flush", round=p["r"])
        # Compile/steady split: this PROCESS's first round pays jit tracing
        # + XLA compilation (whatever round index a resumed run starts at);
        # every later round is steady-state. Splitting the series keeps the
        # compile spike out of the throughput percentiles.
        if not getattr(self, "_first_round_done", False):
            self._first_round_done = True
            telemetry.gauge("driver.first_round_s").set(record.duration_s)
        else:
            telemetry.histogram("driver.steady_round_s").observe(record.duration_s)
        if record.duration_s > 0:
            telemetry.gauge("driver.rounds_per_sec").set(1.0 / record.duration_s)
            if self.cost_model is not None:
                self.cost_model.observe_round_rate(1.0 / record.duration_s)
        self.records.append(record)
        self.metrics.log(record.to_dict())
        return record

    def per_peer_accuracy(self) -> np.ndarray:
        """Accuracy of the current model per peer on that peer's OWN shard —
        the reference's per-tester progress metric (its testers evaluate on
        their own partitions, reference ``evaluation/evaluation.py:10``,
        surfaced per round over HTTP at ``main.py:86-109``). Built lazily:
        only the HTTP facade (and whoever asks) pays for it."""
        r = int(self.state.round_idx)
        cached = getattr(self, "_per_peer_cache", None)
        if cached is not None and cached[0] == r:
            return cached[1]
        if not hasattr(self, "_per_peer_eval"):
            from p2pdl_tpu.parallel import build_per_peer_eval_fn

            self._per_peer_eval = build_per_peer_eval_fn(self.cfg, self.mesh)
        accs = np.asarray(self._per_peer_eval(self.state, self.x, self.y))
        # Cached per round: the reference flow queries each tester in turn
        # (``main.py:87``) — that must not relaunch the mesh-wide eval N times.
        self._per_peer_cache = (r, accs)
        return accs

    def save_checkpoint(self) -> None:
        """Checkpoint the current state (no-op without a dir; idempotent —
        skips if the current round is already the latest saved step)."""
        if self.checkpointer is not None and self.checkpointer.latest_step() != int(
            self.state.round_idx
        ):
            self.checkpointer.save(self.state, self.cfg, extra=self._ckpt_extra)

    def _fused_block_schedule(self, r0: int, block: int) -> dict[str, list]:
        """Precompute one fused block's per-round host decisions as
        schedule rows: the trainer matrix plus the chaos bookkeeping that
        the split-path loop interleaves with device work.

        Omission-only fault plans make this legal: with no hub installed,
        ``FaultInjector.begin_round`` + ``heartbeat_ok`` are pure functions
        of ``(plan, round)`` (see ``FaultPlan.is_omission_only``), so the
        crash/suspicion/membership sequence for rounds r0..r0+block can be
        replayed on the host up front — same calls, same order, same PRF
        draws as :meth:`_run_one_round` — and the resulting exclusions land
        in ``sample_roles`` exactly as the sequential loop would see them.
        The device then consumes the rows as ``lax.scan`` schedule arrays.
        """
        rows: list[np.ndarray] = []
        fault_events: list[Optional[list]] = []
        suspected: list[Optional[list]] = []
        excluded: list[Optional[list]] = []
        injected: list[Optional[dict]] = []
        for i in range(block):
            r = r0 + i
            events = suspected_now = excluded_now = injected_now = None
            if self.faults is not None:
                events = self.faults.begin_round(r)
                responded = {
                    p
                    for p in range(self.cfg.num_peers)
                    if self.faults.heartbeat_ok(r, p)
                }
                newly, recovered = self.detector.observe(r, responded)
                for p in newly:
                    # p2plint: disable=telemetry-cardinality -- deliberate per-peer suspicion series, O(num_peers) and folded past the registry cap
                    telemetry.counter("chaos.suspected", peer=p).inc()
                    events.append({"event": "suspected", "peer": p})
                for p in recovered:
                    # p2plint: disable=telemetry-cardinality -- deliberate per-peer suspicion series, O(num_peers) and folded past the registry cap
                    telemetry.counter("chaos.unsuspected", peer=p).inc()
                    events.append({"event": "unsuspected", "peer": p})
                suspected_now = sorted(self.detector.suspected)
                excluded_now = sorted(
                    set(self.detector.suspected)
                    | {p for p, until in self._suspect_until.items() if until >= r}
                )
                injected_now = dict(self.faults.round_injected)
            rows.append(self.sample_roles(r))
            fault_events.append(events)
            suspected.append(suspected_now)
            excluded.append(excluded_now)
            injected.append(injected_now)
        return {
            "trainer_mat": np.stack(rows),
            "fault_events": fault_events,
            "suspected": suspected,
            "excluded": excluded,
            "injected": injected,
        }

    def run_fused(
        self,
        rounds_per_call: int = 8,
        on_record: Optional[Any] = None,
    ) -> list[RoundRecord]:
        """High-throughput mode: scan ``rounds_per_call`` rounds per device
        dispatch (``parallel.build_multi_round_fn``) — zero host round-trips
        at round boundaries, so small-per-round configs stop being
        dispatch-bound. Requires the trust plane off (it must interpose
        between training and aggregation). Role sampling, losses, metrics,
        and checkpoint cadence are per round exactly as in :meth:`run`;
        held-out eval runs once per BLOCK (recorded on the block's last
        round, ``None`` -> JSON null on interior rounds — evaluating interior
        rounds would re-serialize the device loop this mode exists to
        remove). ``on_record`` is called with each RoundRecord as blocks
        complete (per-block streaming for CLI/monitoring).

        Schedule-driven composition: uniform/random selection and
        OMISSION-ONLY fault plans (crashes, drops, partitions, heartbeat
        loss) run fused — their per-round host decisions are precomputed
        into schedule arrays by :meth:`_fused_block_schedule` and consumed
        on device one row per scanned round, bit-identical to the split
        path at the same seed. BRB (the trust plane must interpose between
        phases) and power_of_choice (needs round r-1's losses before
        sampling round r) remain legitimately split-path, as do plans with
        content/ordering faults (they act on in-flight control messages,
        which a fused block has none of)."""
        if self.trust is not None:
            raise ValueError("run_fused requires brb_enabled=False")
        if self.faults is not None and not self.faults.plan.is_omission_only():
            raise ValueError(
                "run_fused can only host an omission-only fault plan "
                "(crashes/drops/partitions/heartbeat loss): content and "
                "ordering faults (corrupt/delay/duplicate/reorder) mutate "
                "in-flight control messages, which a fused device block "
                "has none of — use run()"
            )
        if self.cfg.selection == "power_of_choice":
            raise ValueError(
                "run_fused with selection='power_of_choice' is not "
                "supported: the whole block's trainer rows are sampled "
                "before any of its rounds run, so the per-round loss "
                "feedback the biased sampler needs does not exist inside "
                "a fused block — use run() for biased selection"
            )
        from p2pdl_tpu.parallel import build_multi_round_fn
        from p2pdl_tpu.parallel.round import fused_block_sizes

        if not hasattr(self, "_multi_round_fn"):
            self._multi_round_fn = build_multi_round_fn(
                self.cfg, self.mesh, attack=self.attack
            )
            # Each distinct scan-block length (tail blocks are shorter) is
            # one legitimate compile; anything past that is an anomaly.
            self.sentinel.register(
                getattr(self._multi_round_fn, "program_name", "multi_round"),
                self._multi_round_fn,
                expected=max(
                    1,
                    len(
                        fused_block_sizes(
                            self.cfg.rounds, rounds_per_call,
                            start=int(self.state.round_idx),
                        )
                    ),
                ),
            )
        self._flush_all_pending()  # a prior pipelined loop may have a tail
        rpc = int(rounds_per_call)
        tuner = None
        if self.autotune:
            from p2pdl_tpu.parallel.autotune import OverlapAutotuner

            if (
                self._autotuner is None
                or self._autotuner.knob != "rounds_per_call"
            ):
                self._autotuner = OverlapAutotuner("rounds_per_call", rpc)
            tuner = self._autotuner
        # Every distinct scan-block length ever dispatched stays ONE
        # legitimate compile: retuning rounds_per_call changes the upcoming
        # schedule, so the sentinel's expected budget is recomputed each
        # iteration from the sizes already seen plus the remaining
        # schedule — a retune must never read as a recompile anomaly
        # (test-pinned in tests/test_autotune.py).
        if not hasattr(self, "_fused_sizes_seen"):
            self._fused_sizes_seen = set()
        base_key = jax.random.PRNGKey(self.cfg.seed)
        while int(self.state.round_idx) < self.cfg.rounds:
            r0 = int(self.state.round_idx)
            block = min(rpc, self.cfg.rounds - r0)
            self._fused_sizes_seen.add(block)
            self.sentinel.expect(
                "multi_round",
                max(
                    1,
                    len(
                        self._fused_sizes_seen
                        | set(
                            fused_block_sizes(self.cfg.rounds, rpc, start=r0)
                        )
                    ),
                ),
            )
            sched = self._fused_block_schedule(r0, block)
            trainer_mat = sched["trainer_mat"]
            trainer_dev = jnp.asarray(trainer_mat, jnp.int32)
            if self.cost_model is not None:
                self.cost_model.capture(
                    "multi_round", self._multi_round_fn,
                    (self.state, self.x, self.y, trainer_dev,
                     self.byz_gate, base_key),
                )
            t0 = time.perf_counter()
            with self.profiler.phase("round", round=r0, rounds=block):
                with self.profiler.phase("round.dispatch", round=r0), \
                        self.sentinel.guard("multi_round", r0):
                    self.state, m = self._multi_round_fn(
                        self.state,
                        self.x,
                        self.y,
                        trainer_dev,
                        self.byz_gate,
                        base_key,
                    )
                with self.profiler.phase("round.d2h", round=r0):
                    losses = np.asarray(m["train_loss"])  # [R, P]
                self._peer_losses = losses[-1]  # feeds biased selection
            self.sentinel.check(r0 + block - 1)
            dt = (time.perf_counter() - t0) / block
            if not getattr(self, "_first_round_done", False):
                self._first_round_done = True
                telemetry.gauge("driver.first_round_s").set(dt * block)
            else:
                telemetry.histogram("driver.steady_round_s").observe(dt)
            if self.cost_model is not None and dt > 0:
                self.cost_model.observe_round_rate(1.0 / dt)
            with self.profiler.phase("eval", round=r0 + block - 1):
                with self.sentinel.guard("eval", r0 + block - 1):
                    ev = self.eval_fn(
                        self.state, self.data.eval_x, self.data.eval_y
                    )
            for i in range(block):
                live = trainer_mat[i][trainer_mat[i] >= 0]
                row = losses[i] if self.cfg.aggregator == "gossip" else losses[i][live]
                last = i == block - 1
                record = RoundRecord(
                    round=r0 + i,
                    trainers=live.tolist(),
                    train_loss=float(np.mean(row)),
                    eval_loss=float(ev["eval_loss"]) if last else None,
                    eval_acc=float(ev["eval_acc"]) if last else None,
                    duration_s=dt,
                    dp_epsilon=self._dp_epsilon(r0 + i + 1),
                    fault_events=sched["fault_events"][i],
                    suspected_peers=sched["suspected"][i],
                    excluded_peers=sched["excluded"][i],
                    faults_injected=sched["injected"][i],
                )
                self.records.append(record)
                self.metrics.log(record.to_dict())
                if on_record is not None:
                    on_record(record)
            if tuner is not None:
                if getattr(self, "_autotune_skipped_first", False):
                    # One observation per ROUND (dt is the block's
                    # per-round average), so larger blocks fill the tuning
                    # window proportionally faster.
                    for _ in range(block):
                        tuner.observe(
                            dt,
                            overlap_efficiency=telemetry.gauge(
                                "driver.overlap_efficiency"
                            ).to_value(),
                            inflight=telemetry.gauge(
                                "driver.inflight_rounds"
                            ).to_value(),
                            mfu=telemetry.gauge("driver.mfu").to_value(),
                        )
                else:
                    # First block carries the jit/XLA compile spike.
                    self._autotune_skipped_first = True
                if tuner.ready():
                    rpc = max(1, int(tuner.propose()))
                    telemetry.gauge("driver.autotune_rounds_per_call").set(rpc)
            # Same cadence as run(): save iff a checkpoint_every boundary
            # was crossed inside this block (at most one save per block).
            if self.checkpointer is not None and (
                (r0 + block) // self.checkpoint_every > r0 // self.checkpoint_every
            ):
                self.checkpointer.save(self.state, self.cfg, extra=self._ckpt_extra)
        self._round_cursor = int(self.state.round_idx)
        self.save_checkpoint()
        return self.records

    def survival_summary(self) -> dict[str, Any]:
        """Chaos verdict for the run so far: did every configured round
        complete within ``round_timeout_s`` despite the fault plan, and
        what did surviving cost? (The ``cli.py chaos`` report and the bench
        ``faults`` block both print this.)"""
        durations = [rec.duration_s for rec in self.records]
        completed = len(self.records)
        return {
            "fault_plan": self.faults.plan.name if self.faults is not None else None,
            "rounds_configured": self.cfg.rounds,
            "rounds_completed": completed,
            "survived": completed >= self.cfg.rounds
            and (not durations or max(durations) <= self.cfg.round_timeout_s),
            "max_round_s": round(max(durations), 4) if durations else None,
            "round_timeout_s": self.cfg.round_timeout_s,
            "faults_injected": dict(self.faults.injected)
            if self.faults is not None
            else {},
            "crashed": sorted(self.faults.crashed) if self.faults is not None else [],
            "suspected": sorted(self.detector.suspected),
            "rounds_with_exclusions": sum(
                1 for rec in self.records if rec.excluded_peers
            ),
            "mask_recoveries": sum(
                len(rec.mask_recoveries or ()) for rec in self.records
            ),
            "final_eval_acc": self.records[-1].eval_acc if self.records else None,
        }

    def perf_summary(self) -> dict[str, Any]:
        """RoundRecord-ADJACENT performance attribution: phase timing,
        pipelined-readback overlap, recompile accounting, and (with
        ``perf=True``) the XLA cost model. Deliberately not part of any
        RoundRecord — every field here is wall-clock- or build-derived, and
        the record stream's bit-identity contract must hold with the perf
        plane on or off."""
        out: dict[str, Any] = {
            "phases": self.profiler.summary(),
            "overlap": self.profiler.overlap.to_dict(),
            "recompile": self.sentinel.summary(),
        }
        if self.cost_model is not None:
            out["cost_model"] = self.cost_model.to_dict()
        if self._autotuner is not None:
            out["autotune"] = self._autotuner.summary()
        return out

    def _autotune_feed(self, fed: int) -> int:
        """Feed newly materialized RoundRecords into the overlap autotuner
        and apply a retuned ``pipeline_depth`` at the next round boundary.
        Returns the new feed cursor into ``self.records``.

        Observations are the records' measured durations plus gauge reads
        (attribution only — see ``OverlapAutotuner``); a knob change first
        drains the in-flight window (a window-size change applies cleanly
        only to an empty window), which also preserves record order, so
        the record stream stays bit-identical (minus duration_s) to the
        untuned run — same contract as pipelining itself."""
        tuner = self._autotuner
        if tuner is None or tuner.knob != "pipeline_depth":
            return len(self.records)
        while fed < len(self.records):
            rec = self.records[fed]
            fed += 1
            if not getattr(self, "_autotune_skipped_first", False):
                # The process's first record carries the jit/XLA compile
                # spike; scoring it would poison the baseline window.
                self._autotune_skipped_first = True
                continue
            tuner.observe(
                rec.duration_s,
                overlap_efficiency=telemetry.gauge(
                    "driver.overlap_efficiency"
                ).to_value(),
                inflight=telemetry.gauge("driver.inflight_rounds").to_value(),
                mfu=telemetry.gauge("driver.mfu").to_value(),
            )
        if tuner.ready():
            new = int(tuner.propose())
            if new != self.pipeline_depth:
                self._flush_all_pending()
                self.pipeline_depth = new
            telemetry.gauge("driver.autotune_pipeline_depth").set(
                self.pipeline_depth
            )
        return fed

    def run_rounds(self, on_record: Optional[Any] = None) -> list[RoundRecord]:
        """The round loop alone (no profiler trace, no final checkpoint —
        callers that wrap their own trace context, like the CLI, use this).

        With ``self.pipeline`` (the default) rounds are dispatched up to
        ``pipeline_depth`` ahead: round r's loss/eval readbacks resolve
        while rounds r+1..r+k's device work runs, and the tail window is
        flushed explicitly before returning — the record stream is
        bit-identical (minus duration_s) to the synchronous loop at every
        depth. ``on_record`` is called with each record as it materializes
        (up to ``pipeline_depth`` rounds late under pipelining)."""
        emitted = len(self.records)

        def emit() -> int:
            n = emitted
            while n < len(self.records):
                if on_record is not None:
                    on_record(self.records[n])
                n += 1
            return n

        if self.autotune and self.pipeline and self._autotuner is None:
            from p2pdl_tpu.parallel.autotune import OverlapAutotuner

            self._autotuner = OverlapAutotuner(
                "pipeline_depth", self.pipeline_depth
            )
        fed = len(self.records)
        while self._round_cursor < self.cfg.rounds:
            self._run_one_round(defer=self.pipeline)
            emitted = emit()
            fed = self._autotune_feed(fed)
        self._flush_all_pending()
        emit()
        self._autotune_feed(fed)
        return self.records

    def run(self, on_record: Optional[Any] = None) -> list[RoundRecord]:
        """Run the remaining rounds (resume-aware: a restored experiment
        continues from its checkpointed round, reference has no equivalent).

        Always checkpoints the final state, whatever ``checkpoint_every`` —
        otherwise tail rounds would be lost and a re-launch would re-execute
        them, duplicating their JSONL metrics records. Device traces go to
        ``profile_dir`` when configured (the ``jax.profiler`` trace wraps the
        whole run here, not only in the CLI)."""
        with self.profiler.trace():
            self.run_rounds(on_record)
        self.save_checkpoint()
        return self.records


def run_experiment(cfg: Config, **kwargs: Any) -> list[RoundRecord]:
    return Experiment(cfg, **kwargs).run()
