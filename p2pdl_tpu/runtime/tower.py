"""Cluster control tower: live multi-peer flight tailing + streaming audit.

The per-process observability pieces (flight recorder with cursor paging,
Prometheus exposition, Lamport-tagged causal merge, conformance auditor)
only became a *cluster* plane once something consumes N of them at once.
This module is that consumer, and it is deliberately jax-free — a tower
runs on an operator laptop against training hosts, never inside one.

- :class:`ControlTower` tails N ``/flight?since=`` cursor endpoints
  (bounded deterministic backoff, per-stream watermarks, ring-eviction gap
  accounting via the page's ``oldest_retained``), scrapes ``/metrics`` and
  ``/healthz``, merges the streams *incrementally* through
  :class:`p2pdl_tpu.protocol.audit.StreamingMerger` (so the rolling
  ``causal_digest`` is bit-identical to the offline ``cli audit`` merge
  over the same events), feeds every merged event into a live
  :class:`ProtocolAuditor`, and maintains a deterministic cluster-health
  model (committee size, min quorum margin, suspicion set, anomaly counts,
  round-progress SLO) with threshold alert rules.
- :func:`diverge` + :func:`blame_chain` are the forensics half: align two
  recorded streams by the canonical ``(round, lamport, stream, n)`` key,
  report the first divergent event with a field-level diff, then walk the
  ``cause`` edges (``"peer:lamport"`` trace tags) backwards to the
  earliest upstream event that already differs.

Determinism: everything derived from event *content* is pure bookkeeping
(sorted traversals, no entropy). The poll loop itself lives on
``time.perf_counter`` — the sanctioned monotonic clock — for pacing,
backoff, and SLO stall measurement; the only wall-clock reads are
operator-facing stamps on the dashboard and the archive trailer, each
carrying an inline lint suppression with its reason.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Any, Callable, Iterable, Optional

from p2pdl_tpu.protocol.audit import (
    ProtocolAuditor,
    StreamingMerger,
    merge_key,
)
from p2pdl_tpu.utils import telemetry

__all__ = [
    "TowerSLO",
    "StreamTail",
    "ControlTower",
    "load_jsonl",
    "stream_kind",
    "field_diff",
    "diverge",
    "blame_chain",
]

# Poll-loop bounds: a failing endpoint backs off exponentially (factor 2,
# deterministic — no jitter, the fleet is N<=dozens of laptops' towers, not
# a thundering herd) up to BACKOFF_CAP_S; a healthy stream is drained at
# most MAX_PAGES_PER_POLL pages per sweep so one chatty peer cannot starve
# the others.
BACKOFF_CAP_S = 30.0
MAX_PAGES_PER_POLL = 64
DOWN_AFTER_ERRORS = 3


class TowerSLO:
    """Threshold alert rules over the cluster-health model.

    Every rule is a pure predicate over deterministic state, so the alert
    set for a given event prefix is identical on every run. ``None``
    disables a rule.
    """

    def __init__(
        self,
        round_stall_s: Optional[float] = 60.0,
        min_quorum_margin: Optional[int] = 1,
        max_anomalies_per_round: Optional[float] = 1.0,
    ) -> None:
        self.round_stall_s = round_stall_s
        self.min_quorum_margin = min_quorum_margin
        self.max_anomalies_per_round = max_anomalies_per_round


class StreamTail:
    """Mutable tail state for one endpoint: cursor, gaps, backoff, health."""

    def __init__(self, url: str) -> None:
        self.url = url.rstrip("/")
        if "://" not in self.url:
            self.url = "http://" + self.url
        self.cursor = 0
        self.events_ingested = 0
        self.gap_events = 0  # history lost to ring eviction, exactly
        self.errors = 0
        self.consecutive_errors = 0
        self.next_attempt = 0.0  # perf_counter deadline for backoff
        self.drained = False  # last sweep saw an empty page
        self.closed = False
        self.last_health: dict[str, Any] = {}
        self.last_metrics: dict[str, float] = {}

    @property
    def down(self) -> bool:
        return self.consecutive_errors >= DOWN_AFTER_ERRORS

    def state(self) -> str:
        if self.closed:
            return "closed"
        if self.down:
            return "down"
        if self.drained:
            return "drained"
        return "tailing"


class ControlTower:
    """Tail N observability endpoints into one audited causal stream.

    ``endpoints`` are ``host:port`` or full ``http://`` base URLs exposing
    the ``serve_metrics`` surface. ``kinds`` optionally narrows the tail to
    a server-side ``?kind=`` filter (note: a filtered tail is cheaper but
    its causal digest covers only the filtered events). ``registered`` is
    the auditor's voter universe, as in ``cli audit``.
    """

    def __init__(
        self,
        endpoints: list[str],
        poll_interval: float = 0.5,
        kinds: Optional[Iterable[str]] = None,
        registered: Optional[Iterable[int]] = None,
        slo: Optional[TowerSLO] = None,
        hold_rounds: int = 2,
        http_timeout: float = 3.0,
        page_limit: int = 512,
        archive_path: Optional[str] = None,
        fetch_json: Optional[Callable[[str, float], Any]] = None,
    ) -> None:
        if not endpoints:
            raise ValueError("ControlTower needs at least one endpoint")
        self.tails = [StreamTail(u) for u in endpoints]
        self.poll_interval = max(0.01, float(poll_interval))
        self.kinds = tuple(kinds) if kinds else None
        self.slo = slo if slo is not None else TowerSLO()
        self.http_timeout = float(http_timeout)
        self.page_limit = int(page_limit)
        self.merger = StreamingMerger(len(self.tails), hold_rounds=hold_rounds)
        self.auditor = ProtocolAuditor(registered=registered)
        self._fetch_json = fetch_json if fetch_json is not None else _http_json
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.polls = 0
        self.finalized = False
        # Cluster-health model (all derived from merged event content).
        self.max_round = -1
        self._round_advanced_at = time.perf_counter()
        self.committee: Optional[int] = None
        self.live: Optional[int] = None
        self.suspected: list[int] = []
        self.min_quorum_margin: Optional[int] = None
        self.anomalies_by_kind: dict[str, int] = {}
        self._archive = open(archive_path, "w") if archive_path else None
        self.archive_path = archive_path

    # ---- transport -----------------------------------------------------------

    def _flight_url(self, tail: StreamTail) -> str:
        url = f"{tail.url}/flight?since={tail.cursor}&limit={self.page_limit}"
        if self.kinds:
            url += "&kind=" + ",".join(self.kinds)
        return url

    def _sweep_stream(self, index: int, tail: StreamTail) -> None:
        """One poll sweep over a single endpoint: drain flight pages into
        the merger, then refresh its health/metrics snapshots."""
        first_page = True
        for _ in range(MAX_PAGES_PER_POLL):
            page = self._fetch_json(self._flight_url(tail), self.http_timeout)
            events = page.get("events", [])
            oldest = page.get("oldest_retained")
            if first_page and oldest is not None and oldest > tail.cursor:
                # The ring evicted history past our cursor: account the
                # loss exactly (the recorder's monotone `n` makes the gap
                # arithmetic precise even under a ?kind= filter).
                if tail.cursor > 0 or tail.events_ingested > 0:
                    tail.gap_events += oldest - tail.cursor
                tail.cursor = oldest
            first_page = False
            next_cursor = page.get("next_cursor", tail.cursor)
            if events:
                self.merger.push(index, events)
                tail.events_ingested += len(events)
                telemetry.counter("tower.events_ingested").inc(len(events))
            if next_cursor <= tail.cursor:
                tail.drained = True
                break
            tail.cursor = next_cursor
            if not events and next_cursor >= page.get("events_recorded", 0):
                tail.drained = True
                break
        else:
            tail.drained = False
        health = self._fetch_json(f"{tail.url}/healthz", self.http_timeout)
        if isinstance(health, dict):
            tail.last_health = health
        metrics = self._fetch_json(f"{tail.url}/metrics", self.http_timeout)
        if isinstance(metrics, str):
            tail.last_metrics = telemetry.parse_prometheus_text(metrics)

    # ---- polling -------------------------------------------------------------

    def poll_once(self) -> dict[str, Any]:
        """One synchronous sweep over every stream; returns ``snapshot()``."""
        with self._lock:
            now = time.perf_counter()
            self.polls += 1
            telemetry.counter("tower.polls").inc()
            for i, tail in enumerate(self.tails):
                if tail.closed or tail.next_attempt > now:
                    continue
                try:
                    self._sweep_stream(i, tail)
                except Exception:
                    tail.errors += 1
                    tail.consecutive_errors += 1
                    tail.drained = False
                    telemetry.counter("tower.poll_errors").inc()
                    # Deterministic bounded exponential backoff (no jitter).
                    delay = min(
                        BACKOFF_CAP_S,
                        self.poll_interval
                        * (2 ** min(tail.consecutive_errors, 6)),
                    )
                    tail.next_attempt = time.perf_counter() + delay
                else:
                    tail.consecutive_errors = 0
                    tail.next_attempt = 0.0
            for ev in self.merger.poll():
                self._observe(ev)
            self.auditor.check()
            self._update_gauges()
            return self.snapshot()

    def close_stream(self, index: int) -> None:
        """Stop tailing one endpoint and release its merge watermark."""
        with self._lock:
            self.tails[index].closed = True
            self.merger.close(index)

    def finalize(self) -> dict[str, Any]:
        """Close every stream, drain the merger, run the final audit pass,
        and seal the archive; returns the final ``snapshot()``."""
        with self._lock:
            if not self.finalized:
                self.finalized = True
                for tail in self.tails:
                    tail.closed = True
                for ev in self.merger.finalize():
                    self._observe(ev)
                self.auditor.check()
                self._update_gauges()
                if self._archive is not None:
                    trailer = {
                        "tower_archive": {
                            "causal_digest": self.merger.digest(),
                            "emitted": self.merger.emitted,
                            "late_events": self.merger.late_events,
                        },
                        # Operator-facing stamp, never replayed state.
                        "ts": time.time(),  # p2plint: disable=determinism-wallclock -- archive trailer wall-clock stamp for the human reader; stripped (like every `ts`) from all comparisons
                    }
                    self._archive.write(
                        json.dumps(trailer, sort_keys=True) + "\n"
                    )
                    self._archive.close()
                    self._archive = None
            return self.snapshot()

    def run(self, max_polls: Optional[int] = None) -> None:
        """Blocking poll loop until ``stop()`` (or ``max_polls`` sweeps)."""
        done = 0
        while not self._stop.is_set():
            self.poll_once()
            done += 1
            if max_polls is not None and done >= max_polls:
                break
            self._stop.wait(self.poll_interval)

    def start(self) -> threading.Thread:
        """Run the poll loop on a daemon thread; returns the thread."""
        if self._thread is not None:
            raise RuntimeError("tower already started")
        self._thread = threading.Thread(
            target=self.run, name="p2pdl-tower", daemon=True
        )
        self._thread.start()
        return self._thread

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def run_to_exhaustion(
        self, max_polls: int = 64, settle_polls: int = 2
    ) -> dict[str, Any]:
        """Poll until every live stream reports a drained tail for
        ``settle_polls`` consecutive sweeps (the ``--once`` replay mode),
        then finalize. Bounded by ``max_polls`` so a flapping endpoint
        cannot wedge the caller."""
        settled = 0
        for _ in range(max_polls):
            self.poll_once()
            if all(t.closed or t.down or t.drained for t in self.tails):
                settled += 1
                if settled >= settle_polls:
                    break
            else:
                settled = 0
        return self.finalize()

    # ---- health model --------------------------------------------------------

    def _observe(self, ev: dict[str, Any]) -> None:
        """Fold one merged event into the health model (and the archive)."""
        kind = ev.get("kind")
        r = merge_key(ev, 0)[0]
        if r > self.max_round:
            self.max_round = r
            self._round_advanced_at = time.perf_counter()
        if kind == "quorum_reconfig" or kind == "quorum_collapse":
            if ev.get("live") is not None:
                self.live = int(ev["live"])
            if ev.get("committee") is not None:
                self.committee = int(ev["committee"])
            if ev.get("suspected") is not None:
                self.suspected = sorted(int(p) for p in ev["suspected"])
        elif kind == "suspect":
            p = ev.get("peer")
            if p is not None and int(p) not in self.suspected:
                self.suspected = sorted(self.suspected + [int(p)])
        elif kind == "unsuspect":
            p = ev.get("peer")
            if p is not None and int(p) in self.suspected:
                self.suspected = [q for q in self.suspected if q != int(p)]
        elif kind == "brb_deliver":
            margin = ev.get("margin")
            if margin is not None and (
                self.min_quorum_margin is None
                or int(margin) < self.min_quorum_margin
            ):
                self.min_quorum_margin = int(margin)
        if ev.get("anomaly") and kind is not None:
            self.anomalies_by_kind[kind] = (
                self.anomalies_by_kind.get(kind, 0) + 1
            )
        self.auditor.feed(ev)
        if self._archive is not None:
            stripped = {k: v for k, v in ev.items() if k != "ts"}
            self._archive.write(json.dumps(stripped, sort_keys=True) + "\n")

    def round_stall_s(self) -> float:
        """Seconds since the merged round coordinate last advanced."""
        return time.perf_counter() - self._round_advanced_at

    def rounds_per_sec(self) -> Optional[float]:
        """Slowest live peer's reported round rate (None before any report)."""
        rates = [
            float(t.last_health["rounds_per_sec"])
            for t in self.tails
            if "rounds_per_sec" in t.last_health
        ]
        return min(rates) if rates else None

    def alerts(self) -> list[dict[str, str]]:
        """Evaluate the threshold alert rules; deterministic given the
        merged event prefix (the stall rule alone reads the pacing clock)."""
        out: list[dict[str, str]] = []
        down = [t.url for t in self.tails if t.down and not t.closed]
        if down:
            out.append(
                {"rule": "stream_down", "detail": ", ".join(sorted(down))}
            )
        slo = self.slo
        if (
            slo.round_stall_s is not None
            and self.max_round >= 0
            and not self.finalized
            and self.round_stall_s() > slo.round_stall_s
        ):
            out.append(
                {
                    "rule": "round_stall",
                    "detail": f"round {self.max_round} for "
                    f"{self.round_stall_s():.0f}s (SLO {slo.round_stall_s:.0f}s)",
                }
            )
        if (
            slo.min_quorum_margin is not None
            and self.min_quorum_margin is not None
            and self.min_quorum_margin < slo.min_quorum_margin
        ):
            out.append(
                {
                    "rule": "quorum_margin_low",
                    "detail": f"min deliver margin {self.min_quorum_margin} "
                    f"< {slo.min_quorum_margin}",
                }
            )
        anomalies = sum(self.anomalies_by_kind.values())
        rounds = max(1, self.max_round + 1)
        if (
            slo.max_anomalies_per_round is not None
            and anomalies / rounds > slo.max_anomalies_per_round
        ):
            out.append(
                {
                    "rule": "anomaly_rate_high",
                    "detail": f"{anomalies} anomalies over {rounds} rounds",
                }
            )
        if self.auditor.violations:
            out.append(
                {
                    "rule": "audit_violation",
                    "detail": f"{len(self.auditor.violations)} conformance "
                    "violations (see audit section)",
                }
            )
        if self.merger.late_events:
            out.append(
                {
                    "rule": "merge_late_events",
                    "detail": f"{self.merger.late_events} events arrived "
                    "behind the emission frontier; rolling digest no longer "
                    "matches the offline merge",
                }
            )
        return out

    def _update_gauges(self) -> None:
        telemetry.gauge("tower.streams_live").set(
            sum(1 for t in self.tails if not t.down and not t.closed)
        )
        telemetry.gauge("tower.events_merged").set(self.merger.emitted)
        telemetry.gauge("tower.late_events").set(self.merger.late_events)
        telemetry.gauge("tower.gap_events").set(
            sum(t.gap_events for t in self.tails)
        )
        telemetry.gauge("tower.round_index").set(self.max_round)
        telemetry.gauge("tower.suspected_peers").set(len(self.suspected))
        if self.min_quorum_margin is not None:
            telemetry.gauge("tower.min_quorum_margin").set(
                self.min_quorum_margin
            )
        telemetry.gauge("tower.audit_violations").set(
            len(self.auditor.violations)
        )
        telemetry.gauge("tower.alerts_active").set(len(self.alerts()))

    # ---- reporting -----------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """JSON-ready tower state (the ``--json`` / archive shape)."""
        frontier = self.merger.frontier
        return {
            "polls": self.polls,
            "finalized": self.finalized,
            "streams": [
                {
                    "url": t.url,
                    "state": t.state(),
                    "cursor": t.cursor,
                    "events_ingested": t.events_ingested,
                    "gap_events": t.gap_events,
                    "errors": t.errors,
                    "health": t.last_health,
                }
                for t in self.tails
            ],
            "merge": {
                "emitted": self.merger.emitted,
                "late_events": self.merger.late_events,
                "frontier": frontier,
                "causal_digest": self.merger.digest(),
            },
            "audit": {
                **self.auditor.summary(),
                "details": [v.to_dict() for v in self.auditor.violations],
            },
            "health": {
                "round_index": self.max_round,
                "committee": self.committee,
                "live": self.live,
                "suspected": list(self.suspected),
                "min_quorum_margin": self.min_quorum_margin,
                "anomalies_by_kind": dict(
                    sorted(self.anomalies_by_kind.items())
                ),
                "rounds_per_sec": self.rounds_per_sec(),
            },
            "alerts": self.alerts(),
        }

    def render_dashboard(self) -> str:
        """Fixed-width text dashboard (the default ``cli tower`` surface)."""
        snap = self.snapshot()
        live = sum(1 for s in snap["streams"] if s["state"] == "tailing")
        lines = [
            f"p2pdl control tower — {len(self.tails)} streams "
            f"({live} tailing), poll #{snap['polls']}"
            + ("  [final]" if self.finalized else ""),
            f"  {'stream':<28} {'state':<8} {'cursor':>8} {'events':>8} "
            f"{'gap':>6} {'errs':>5}",
        ]
        for s in snap["streams"]:
            lines.append(
                f"  {s['url'][:28]:<28} {s['state']:<8} {s['cursor']:>8} "
                f"{s['events_ingested']:>8} {s['gap_events']:>6} "
                f"{s['errors']:>5}"
            )
        m = snap["merge"]
        lines.append(
            f"  merge   emitted={m['emitted']} late={m['late_events']} "
            f"frontier={m['frontier']} digest={m['causal_digest'][:16]}…"
        )
        h = snap["health"]
        rps = h["rounds_per_sec"]
        rps_str = f"{rps:.2f}" if rps is not None else "-"
        lines.append(
            f"  health  round={h['round_index']} committee={h['committee']} "
            f"live={h['live']} suspected={h['suspected']} "
            f"min_margin={h['min_quorum_margin']} rps={rps_str}"
        )
        a = snap["audit"]
        lines.append(
            f"  audit   violations={a['violations']} "
            f"by_invariant={a['by_invariant']}"
        )
        if snap["alerts"]:
            for alert in snap["alerts"]:
                lines.append(f"  ALERT   {alert['rule']}: {alert['detail']}")
        else:
            lines.append("  alerts  none")
        return "\n".join(lines)


def _http_json(url: str, timeout: float) -> Any:
    """GET ``url``; JSON-decode ``application/json`` bodies, return text
    otherwise (the ``/metrics`` exposition)."""
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        body = resp.read().decode()
        ctype = resp.headers.get("Content-Type", "")
    if "json" in ctype:
        return json.loads(body)
    return body


# ---- Divergence forensics ----------------------------------------------------


def load_jsonl(path: str) -> list[dict[str, Any]]:
    """Load one JSONL stream (flight dump, tower archive, or RoundRecord
    log); blank lines are skipped, malformed lines raise."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def stream_kind(events: list[dict[str, Any]]) -> str:
    """``"flight"`` when the stream carries flight events (``kind`` field),
    ``"records"`` for RoundRecord JSONL (``round`` + loss fields)."""
    for ev in events:
        if "kind" in ev:
            return "flight"
    return "records"


# RoundRecord fields that are timing, not replayed state — the same set
# tests strip before bit-identity comparisons.
_RECORD_TIME_FIELDS = ("duration_s",)
_RECORD_TIME_HEALTH = ("brb_latency_s",)


def _strip(ev: dict[str, Any], kind: str) -> dict[str, Any]:
    out = {k: v for k, v in ev.items() if k != "ts"}
    if kind == "records":
        for f in _RECORD_TIME_FIELDS:
            out.pop(f, None)
        health = out.get("protocol_health")
        if isinstance(health, dict):
            out["protocol_health"] = {
                k: v
                for k, v in health.items()
                if k not in _RECORD_TIME_HEALTH
            }
    return out


def field_diff(
    a: dict[str, Any], b: dict[str, Any], kind: str = "flight"
) -> dict[str, dict[str, Any]]:
    """Field-level diff of two aligned events: ``{field: {"a":…, "b":…}}``
    over the union of keys, time fields excluded."""
    sa, sb = _strip(a, kind), _strip(b, kind)
    out: dict[str, dict[str, Any]] = {}
    for key in sorted(set(sa) | set(sb)):
        va, vb = sa.get(key, "<absent>"), sb.get(key, "<absent>")
        if va != vb:
            out[key] = {"a": va, "b": vb}
    return out


def _aligned(events: list[dict[str, Any]], kind: str) -> list[dict[str, Any]]:
    if kind == "flight":
        return sorted(events, key=lambda ev: merge_key(ev, 0))
    return sorted(events, key=lambda ev: int(ev.get("round", -1)))


def _cause_index(
    events: list[dict[str, Any]],
) -> dict[str, dict[str, Any]]:
    """Map ``"peer:lamport"`` trace tags to the first event recorded by
    that peer at that Lamport time — the emission a ``cause`` field names."""
    index: dict[str, dict[str, Any]] = {}
    for ev in events:
        peer, lamport = ev.get("peer"), ev.get("lamport")
        if peer is None or lamport is None:
            continue
        index.setdefault(f"{peer}:{lamport}", ev)
    return index


def blame_chain(
    a_events: list[dict[str, Any]],
    b_events: list[dict[str, Any]],
    a_ev: dict[str, Any],
    b_ev: dict[str, Any],
) -> list[dict[str, Any]]:
    """Walk ``cause`` edges backwards from a divergent event pair to the
    earliest upstream emission that already differs.

    Returns the chain earliest-cause-first; the divergent pair itself is
    always the last entry, so the chain is never empty. The walk stops when
    an event has no ``cause``, the cause resolves identically in both
    streams (the divergence started at the current link), or a cycle/missing
    tag breaks the edge.
    """
    index_a, index_b = _cause_index(a_events), _cause_index(b_events)
    chain: list[dict[str, Any]] = []
    seen: set[str] = set()
    cur_a, cur_b = a_ev, b_ev
    while True:
        chain.append(
            {
                "a": _strip(cur_a, "flight"),
                "b": _strip(cur_b, "flight"),
                "diff": field_diff(cur_a, cur_b),
            }
        )
        ca, cb = cur_a.get("cause"), cur_b.get("cause")
        if ca is None or cb is None:
            break
        # Follow each stream's own edge (the tags may themselves disagree —
        # that disagreement is part of the divergence being explained).
        tag = f"{ca}|{cb}"
        if tag in seen:
            break
        seen.add(tag)
        nxt_a, nxt_b = index_a.get(str(ca)), index_b.get(str(cb))
        if nxt_a is None or nxt_b is None:
            break
        if _strip(nxt_a, "flight") == _strip(nxt_b, "flight"):
            break  # upstream agrees: the current link is the blame root
        cur_a, cur_b = nxt_a, nxt_b
    chain.reverse()
    return chain


def diverge(
    a_events: list[dict[str, Any]], b_events: list[dict[str, Any]]
) -> dict[str, Any]:
    """First-divergence report between two recorded streams.

    Aligns both by the canonical causal key (flight streams:
    ``(round, lamport, stream, n)``; RoundRecord logs: round index),
    compares time-stripped events pairwise, and reports the first
    divergent position with a field diff plus — for flight streams — the
    causal blame chain. ``{"identical": True, …}`` when nothing differs.
    """
    kind = stream_kind(a_events) if a_events else stream_kind(b_events)
    a_sorted, b_sorted = _aligned(a_events, kind), _aligned(b_events, kind)
    n = min(len(a_sorted), len(b_sorted))
    for i in range(n):
        ea, eb = a_sorted[i], b_sorted[i]
        if _strip(ea, kind) == _strip(eb, kind):
            continue
        report: dict[str, Any] = {
            "identical": False,
            "kind": kind,
            "index": i,
            "a_len": len(a_sorted),
            "b_len": len(b_sorted),
            "first_divergent": {
                "a": _strip(ea, kind),
                "b": _strip(eb, kind),
                "diff": field_diff(ea, eb, kind),
            },
        }
        if kind == "flight":
            report["blame_chain"] = blame_chain(a_sorted, b_sorted, ea, eb)
        return report
    if len(a_sorted) != len(b_sorted):
        longer, which = (a_sorted, "a") if len(a_sorted) > n else (b_sorted, "b")
        return {
            "identical": False,
            "kind": kind,
            "index": n,
            "a_len": len(a_sorted),
            "b_len": len(b_sorted),
            "first_divergent": {
                "only_in": which,
                which: _strip(longer[n], kind),
                "diff": {},
            },
            **({"blame_chain": []} if kind == "flight" else {}),
        }
    return {
        "identical": True,
        "kind": kind,
        "a_len": len(a_sorted),
        "b_len": len(b_sorted),
    }
