"""Node-level API parity over the SPMD engine.

A user of the reference drives ``Node`` objects: construct, ``start()``,
``connect()`` them into a mesh, ``set_start_learning()`` on trainers, wait
for delivery, ``testing()`` on testers (reference ``node/node.py:21-326``,
orchestrated by ``main.py:22-87``). This module offers the same surface:
``Cluster`` owns the compiled experiment (the peers all live on the device
mesh), and each ``Node`` is a per-peer handle exposing the reference's
methods with the same semantics — minus its races and silent failure modes.

Key behavioral mapping:
- ``set_start_learning(rounds, epochs)`` marks the node a trainer for the
  pending round (reference ``node/node.py:322-326`` trains + fans out
  updates); the round executes collectively once every sampled trainer has
  called it (the reference's thread-join barrier, ``main.py:79-80``).
- ``wait_for_delivered()`` blocks until this peer's BRB instances for the
  round delivered (reference ``node/node.py:71-74``) — but with the
  config's round timeout, not forever.
- ``testing()`` aggregates + evaluates (reference ``node/node.py:315-317``)
  and returns ``{"accuracy", "addr", "port"}`` like reference
  ``evaluation/evaluation.py:20-24`` — except accuracy is held-out, and
  aggregation already happened deterministically on-device.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from p2pdl_tpu.config import Config
from p2pdl_tpu.runtime.driver import Experiment, RoundRecord
from p2pdl_tpu.utils import flight


class Node:
    def __init__(self, cluster: "Cluster", node_id: int, addr: str, port: int) -> None:
        self.cluster = cluster
        self.node_id = node_id
        self.addr = addr
        self.port = port
        self.neighbors: list["Node"] = []
        self._delivered = threading.Event()

    @property
    def running(self) -> bool:
        """Single source of truth: the cluster's stopped set (a second
        boolean here would have to be kept in sync manually)."""
        return self.node_id not in self.cluster._stopped

    # -- lifecycle (reference node/node.py:76-95) --
    def start(self) -> None:
        """(Re-)join the cluster: eligible for sampling and consent again
        (reference ``start()`` binds the listener socket)."""
        self.cluster._set_stopped(self.node_id, stopped=False)
        flight.record("membership", peer=self.node_id, change="start")

    def stop(self) -> None:
        """Go dark, like the reference's socket teardown (``node/node.py:
        93-95``): a stopped node cannot consent to training, a round that
        sampled it runs with its slot vacated (-1, shrunken participation),
        and its delivery flag never sets. ``start()`` re-admits."""
        self.cluster._set_stopped(self.node_id, stopped=True)
        flight.record("membership", peer=self.node_id, change="stop")

    def connect(self, other: "Node") -> None:
        """Record a neighbor (reference ``node/node.py:251-263``; its TCP
        handshake is silently dropped remotely — SURVEY §2 #9 — so the local
        append is all the reference effectively does too)."""
        if other is not self and other not in self.neighbors:
            self.neighbors.append(other)

    # -- BRB delivery flags (reference node/node.py:55-74) --
    def reset_delivered_flag(self) -> None:
        self._delivered.clear()

    def wait_for_delivered(self, timeout: Optional[float] = None) -> bool:
        """Block until the round's broadcasts were delivered to this peer.
        Unlike the reference (no timeout: one silent peer stalls forever,
        ``node/node.py:73``), defaults to the config round timeout."""
        if timeout is None:
            timeout = self.cluster.cfg.round_timeout_s
        return self._delivered.wait(timeout)

    # -- training / testing (reference node/node.py:315-326) --
    def set_start_learning(self, rounds: int = 1, epochs: int = 5) -> None:
        """Consent to train this round. On a stopped node this raises —
        the reference's equivalent would enqueue onto a dead command loop
        and hang its caller forever (``node/node.py:322-326`` after
        ``stop()``); failing loudly is the honest version."""
        if not self.running:
            raise RuntimeError(f"node {self.node_id} is stopped")
        self.cluster._mark_trainer(self.node_id)

    def testing(self) -> dict[str, Any]:
        """This node's accuracy on ITS OWN shard — exactly the reference's
        tester metric (``evaluation/evaluation.py:20-24`` evaluates the
        node's partition and returns ``{accuracy, addr, port}``)."""
        if self.cluster.last_record is None:
            raise RuntimeError("no round has run yet")
        acc = self.cluster.experiment.per_peer_accuracy()[self.node_id]
        return {"accuracy": float(acc), "addr": self.addr, "port": self.port}


class Cluster:
    """All peers of one experiment plus their Node handles."""

    def __init__(self, cfg: Config, base_port: int = 7001, **experiment_kwargs: Any) -> None:
        self.cfg = cfg
        self.experiment = Experiment(cfg, **experiment_kwargs)
        self._stopped: set[int] = set()
        self.nodes = [Node(self, i, "127.0.0.1", base_port + i) for i in range(cfg.num_peers)]
        self._pending_trainers: set[int] = set()
        self._expected_trainers: Optional[list[int]] = None
        self.last_record: Optional[RoundRecord] = None
        self._lock = threading.Lock()

    def sample_roles(self) -> tuple[list[Node], list[Node]]:
        """Trainer/tester split for the next round (reference ``main.py:52-54``).
        Resets any stale consent from an abandoned round: set_start_learning
        calls only count toward the round they were sampled for."""
        trainers = self.experiment.sample_roles().tolist()
        with self._lock:
            # One critical section: a consent arriving mid-reset must see
            # either the old round's full state or the new round's, never a
            # cleared pending-set with a stale expected list.
            self._pending_trainers.clear()
            self._expected_trainers = trainers
        testers = [i for i in range(self.cfg.num_peers) if i not in trainers]
        return [self.nodes[i] for i in trainers], [self.nodes[i] for i in testers]

    def _set_stopped(self, node_id: int, stopped: bool) -> None:
        """Membership mutation, serialized against the quorum check: a
        concurrent stop() must not interleave with _mark_trainer's
        live-trainer computation (it reads `_stopped` under this lock)."""
        with self._lock:
            if stopped:
                self._stopped.add(node_id)
            else:
                self._stopped.discard(node_id)

    def _mark_trainer(self, node_id: int) -> None:
        run_now = False
        with self._lock:
            self._pending_trainers.add(node_id)
            # Stopped trainers can never consent — the round proceeds once
            # every LIVE sampled trainer has (their slots get vacated).
            if self._expected_trainers is not None and self._pending_trainers >= (
                set(self._expected_trainers) - self._stopped
            ):
                run_now = True
        if run_now:
            self._run_pending_round()

    def _run_pending_round(self) -> None:
        with self._lock:
            trainers = self._expected_trainers
            self._pending_trainers.clear()
            self._expected_trainers = None
        if trainers is None:
            return
        # The cluster's consented roles, not the experiment's own sampling.
        # A stopped node's slot runs vacant (-1): shrunken participation,
        # exactly as if the peer failed before training (the reference's
        # stop() tears the node down mid-experiment, ``node/node.py:93-95``).
        trainers = [t if t not in self._stopped else -1 for t in trainers]
        if all(t < 0 for t in trainers):
            raise RuntimeError("every sampled trainer is stopped")
        record = self.experiment.run_round(trainers=trainers)
        self.last_record = record
        failed = set(record.brb_failed_peers or [])
        for node in self.nodes:
            if node.node_id not in failed and node.node_id not in self._stopped:
                node._delivered.set()

    def membership(self) -> dict[str, list[int]]:
        """The failure detector's live-membership view plus the cluster's
        own administratively-stopped set — the node-level surface over the
        chaos plane's suspicion table (a Node's ``stop()`` and a fault
        plan's crash look identical to a peer asking "who can I reach?")."""
        det = self.experiment.detector
        return {
            "live": [p for p in det.live() if p not in self._stopped],
            "suspected": sorted(det.suspected),
            "stopped": sorted(self._stopped),
        }

    def per_node_results(self, node_ids: Optional[list[int]] = None) -> list[dict[str, Any]]:
        """Per-node ``{accuracy, addr, port}`` on each node's own shard
        (the reference's per-tester entries in the HTTP learning progress,
        ``main.py:86-94``); defaults to every node."""
        accs = self.experiment.per_peer_accuracy()
        nodes = self.nodes if node_ids is None else [self.nodes[i] for i in node_ids]
        return [
            {"accuracy": float(accs[n.node_id]), "addr": n.addr, "port": n.port}
            for n in nodes
        ]

    def run_round(self, trainers: Optional[list[int]] = None) -> RoundRecord:
        """Drive one full round directly (the orchestration in
        reference ``main.py:50-87`` collapsed into one call)."""
        if trainers is None:
            trainers = self.experiment.sample_roles().tolist()
        if all(t in self._stopped for t in trainers):
            raise RuntimeError("every sampled trainer is stopped")
        with self._lock:
            self._expected_trainers = trainers
        before = len(self.experiment.records)
        for node in self.nodes:
            node.reset_delivered_flag()
        for t in trainers:
            # Stopped trainers cannot consent; their slots run vacant.
            if t not in self._stopped:
                self.nodes[t].set_start_learning(rounds=1, epochs=self.cfg.local_epochs)
        if len(self.experiment.records) == before:
            raise RuntimeError("round did not execute (trainer set mismatch)")
        return self.experiment.records[-1]
