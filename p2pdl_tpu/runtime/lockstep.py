"""Lockstep deterministic chaos runner: one seeded scenario, two deployments.

The acceptance story for the async transport plane is *bit-identity*: the
same seeded chaos scenario (BRB digest broadcasts under crashes, drops,
partitions, delays, duplicates) must produce the same per-host flight
streams whether the N logical hosts share one process and an in-memory
mesh, or run as N real OS processes exchanging frames over loopback TCP
(``protocol.aio_transport.AsyncTCPTransport``). Everything nondeterministic
about a real network — arrival interleaving, dial timing, kernel buffering
— is fenced off by a *tick barrier*:

- frames emitted while processing tick T are delivered at tick
  ``T + 1 + delay_ticks`` (the fault plan's delay fate becomes a concrete
  delivery epoch instead of a wall-clock sleep);
- a host may process tick T only after every host's ``tick_done(T-1)``
  marker arrived (frames ride the same pooled FIFO connection as the
  marker, so marker receipt implies frame receipt);
- each tick's inbox is processed in the canonical order
  ``(src, dst, route_seq, copy)`` — the only order-dependent state (Lamport
  clocks, vote arrival, delivery) sees identical sequences everywhere;
- a round ends at the first tick where no host emitted and no host holds
  buffered future frames (the distributed form of the in-memory hub's
  quiescence promotion).

Fault injection happens at the frame boundary through
``FaultInjector.frame_fate`` — keyed ``(seed, round, src, dst, route_seq)``,
never by traffic order — so the same ``FaultPlan`` drops/duplicates/delays/
corrupts the same frames in both deployments. Flight events are recorded
per host (``flight.using_recorder`` swaps streams in the single-process
baseline; each worker process owns its recorder in the TCP deployment), so
per-stream determinism digests and the causally-merged ``causal_digest``
both compare bit-for-bit.

jax-free on purpose: the module exercises the protocol/transport planes
only, so chaos acceptance runs anywhere the control plane does.
"""

from __future__ import annotations

import base64
import collections
import dataclasses
import hashlib
import json
import threading
import time
from typing import Any, Callable, Optional

from p2pdl_tpu.protocol.brb import BRBConfig, Broadcaster
from p2pdl_tpu.protocol.faults import (
    FailureDetector,
    FaultInjector,
    FaultPlan,
    resolve_plan,
)
from p2pdl_tpu.utils import flight

__all__ = [
    "ChaosSpec",
    "LockstepHost",
    "TickChannel",
    "run_in_memory",
    "run_tcp_host",
]


@dataclasses.dataclass(frozen=True)
class ChaosSpec:
    """One chaos scenario, fully determined by its fields (the whole spec
    crosses process boundaries as JSON so every worker runs the same run)."""

    num_peers: int = 6
    num_hosts: int = 3
    rounds: int = 3
    f: int = 1
    trainers_per_round: int = 2
    plan: Any = "crash_drop_partition"
    seed: int = 0
    # Flight ring capacity — identical in every deployment, or ring
    # eviction alone would split the determinism digests.
    capacity: int = 65536
    # What the BRB broadcast carries: "digest" is the tiny JSON marker the
    # original scenarios ship; "compressed" runs a deterministic pseudo-delta
    # keyed (seed, round, trainer) through the topk+int8 wire codec
    # (``ops.delta_codec``) and broadcasts the digest of the COMPRESSED
    # bytes — the lockstep pin that the compressed wire format is
    # deployment-independent.
    payload_mode: str = "digest"

    def __post_init__(self) -> None:
        if self.num_peers % self.num_hosts != 0:
            raise ValueError(
                f"num_peers ({self.num_peers}) must divide evenly over "
                f"num_hosts ({self.num_hosts})"
            )
        if self.payload_mode not in ("digest", "compressed"):
            raise ValueError(
                f"payload_mode must be 'digest' or 'compressed', "
                f"got {self.payload_mode!r}"
            )

    @property
    def peers_per_host(self) -> int:
        return self.num_peers // self.num_hosts

    def resolved_plan(self) -> FaultPlan:
        return resolve_plan(
            self.plan, self.num_peers, self.rounds, f=self.f, seed=self.seed
        )

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["plan"] = self.resolved_plan().to_dict()
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ChaosSpec":
        d = dict(d)
        if isinstance(d.get("plan"), dict):
            d["plan"] = FaultPlan.from_dict(d["plan"])
        return cls(**d)


def _delta_codec():
    """Load ``ops.delta_codec`` without executing the ``ops`` package
    __init__ (which drags in jax via the reducers) — the codec module is
    numpy-first by contract, so compressed-payload chaos runs stay as
    jax-free as the digest ones."""
    import importlib.util
    import os
    import sys

    name = "p2pdl_tpu.ops.delta_codec"
    mod = sys.modules.get(name)
    if mod is not None:
        return mod
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "ops",
        "delta_codec.py",
    )
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _frame_key(fr: dict) -> tuple[int, int, int, int]:
    """Canonical within-tick processing order. Total over a tick's inbox:
    ``route_seq`` is per (src, dst) route and ``copy`` splits duplicates."""
    return (fr["src"], fr["dst"], fr["q"], fr["c"])


class LockstepHost:
    """One logical host: its peers' BRB broadcasters, its own seeded fault
    injector and failure detector, and the frame-boundary fate logic.

    Deployment-agnostic: the in-memory runner and the TCP worker both drive
    the same three calls per round (``begin_round`` / ``handle_frames`` per
    tick / ``end_round``), so every protocol decision lives here exactly
    once and cannot drift between deployments.
    """

    def __init__(self, host_id: int, spec: ChaosSpec, plan: FaultPlan) -> None:
        from p2pdl_tpu.protocol.crypto import (
            KeyServer,
            generate_key_pair,
            public_key_from_pem,
            public_key_pem,
        )

        self.host_id = host_id
        self.spec = spec
        self.injector = FaultInjector(plan, spec.num_peers)
        self.detector = FailureDetector(spec.num_peers, suspicion_threshold=2)
        ppn = spec.peers_per_host
        self.local_peers = list(range(host_id * ppn, (host_id + 1) * ppn))
        self.key_server = KeyServer()
        self._from_pem = public_key_from_pem
        self.pems: dict[int, str] = {}
        self.broadcasters: dict[int, Broadcaster] = {}
        brb_cfg = BRBConfig(spec.num_peers, spec.f)
        for pid in self.local_peers:
            priv, pub = generate_key_pair()
            self.key_server.register_key(pid, pub)
            self.pems[pid] = public_key_pem(pub).decode()
            self.broadcasters[pid] = Broadcaster(
                brb_cfg, pid, self.key_server, priv
            )
        # Per-route frame counters: monotone over the whole run, keying the
        # route-local fault schedule.
        self._route_seq: collections.Counter = collections.Counter()
        self.records: list[dict] = []
        self._round = -1

    def register_pems(self, pems: dict) -> None:
        """Fold other hosts' peer pubkeys into the directory (re-registering
        an identical key is a no-op, so repeated announcements are safe)."""
        for pid, pem in sorted(pems.items(), key=lambda kv: int(kv[0])):
            self.key_server.register_key(int(pid), self._from_pem(pem.encode()))

    def peer_host(self, peer: int) -> int:
        return peer // self.spec.peers_per_host

    # -- deterministic scenario inputs ----------------------------------
    def trainers_for(self, r: int) -> list[int]:
        """PRF-ranked trainer sample for round ``r`` — a pure function of
        (seed, round), identical on every host and deployment."""
        ranked = sorted(
            range(self.spec.num_peers),
            key=lambda p: hashlib.sha256(
                f"chaos-trainer|{self.spec.seed}|{r}|{p}".encode()
            ).hexdigest(),
        )
        return sorted(ranked[: self.spec.trainers_per_round])

    def _payload(self, r: int, trainer: int) -> bytes:
        body = {"round": r, "trainer": trainer, "seed": self.spec.seed}
        if self.spec.payload_mode == "compressed":
            import numpy as np

            dc = _delta_codec()
            # Deterministic pseudo-delta: a SHA-256 counter stream keyed
            # (seed, round, trainer), mapped into [-1, 1) f32 — pure data,
            # identical on every host and deployment.
            n = 4096
            raw = b"".join(
                hashlib.sha256(
                    f"chaos-delta|{self.spec.seed}|{r}|{trainer}|{i}".encode()
                ).digest()
                for i in range((n * 4 + 31) // 32)
            )
            x = np.frombuffer(raw[: n * 4], dtype="<u4").astype(np.float32)
            x = x * np.float32(2.0 / 2**32) - np.float32(1.0)
            k = dc.topk_count(n, 0.01)
            buf = dc.encode_np(x[None, :], "topk", k)
            body["codec"] = "topk+int8"
            body["nbytes"] = int(buf.shape[1])
            body["digest"] = hashlib.sha256(buf.tobytes()).hexdigest()
        return json.dumps(
            body, sort_keys=True, separators=(",", ":")
        ).encode()

    # -- frame-boundary fate fan-out ------------------------------------
    def _fan_out(self, msgs) -> list[dict]:
        """Route every protocol message to every peer, applying the active
        partition and the route-keyed frame fates. Returns frame dicts
        ``{src, dst, q(route_seq), c(copy), d(delay_ticks), w(wire bytes)}``
        in canonical generation order."""
        from p2pdl_tpu.protocol.transport import brb_to_wire

        frames: list[dict] = []
        for msg in msgs:
            src = msg.from_id
            wire = brb_to_wire(msg)
            for dst in range(self.spec.num_peers):
                if self.injector.cut(src, dst):
                    self.injector._count("partition_cut")
                    continue
                q = self._route_seq[(src, dst)]
                self._route_seq[(src, dst)] += 1
                fate = self.injector.frame_fate(
                    self._round, src, dst, q, size=len(wire)
                )
                if fate["drop"]:
                    continue
                data = wire
                if fate["corrupt_pos"] is not None:
                    flipped = bytearray(data)
                    flipped[fate["corrupt_pos"] % len(data)] ^= 0xFF
                    data = bytes(flipped)
                for c in range(fate["copies"]):
                    frames.append(
                        {
                            "src": src,
                            "dst": dst,
                            "q": q,
                            "c": c,
                            "d": fate["delay_ticks"],
                            "w": data,
                        }
                    )
        return frames

    # -- the three per-round entry points -------------------------------
    def begin_round(self, r: int) -> list[dict]:
        """Advance fault state, record the round marker, and originate this
        round's broadcasts for the trainers this host owns."""
        self._round = r
        self.injector.begin_round(r)
        trainers = self.trainers_for(r)
        flight.record("round_begin", round=r, trainers=trainers)
        msgs = []
        for t in trainers:
            if t in self.broadcasters and t not in self.injector.crashed:
                msgs.extend(self.broadcasters[t].broadcast(r, self._payload(r, t)))
        return self._fan_out(msgs)

    def handle_frames(self, frames: list[dict]) -> list[dict]:
        """Process one tick's inbox (caller passes it canonically sorted);
        returns the outbound frames the handling produced."""
        from p2pdl_tpu.protocol.transport import brb_from_wire

        out_msgs = []
        for fr in frames:
            dst = fr["dst"]
            bc = self.broadcasters.get(dst)
            if bc is None or dst in self.injector.crashed:
                continue
            try:
                msg = brb_from_wire(fr["w"])
            except Exception:
                msg = None  # corrupted frame: unparseable, dropped
            if msg is None:
                continue
            out_msgs.extend(bc.handle(msg))
        return self._fan_out(out_msgs)

    def end_round(self, r: int) -> dict:
        """Heartbeat/detector fold, per-trainer delivery verdicts for the
        peers this host owns, and the round record row."""
        responded = {
            p
            for p in range(self.spec.num_peers)
            if self.injector.heartbeat_ok(r, p)
        }
        self.detector.observe(r, responded)
        trainers = self.trainers_for(r)
        delivered = {
            str(t): sum(
                1
                for p in self.local_peers
                if self.broadcasters[p].delivered(t, r) is not None
            )
            for t in trainers
        }
        rec = {
            "round": r,
            "host": self.host_id,
            "trainers": trainers,
            "delivered": delivered,
            "responded": sorted(responded),
            "suspected": sorted(self.detector.suspected),
            "faults": dict(sorted(self.injector.round_injected.items())),
        }
        self.records.append(rec)
        for pid in sorted(self.broadcasters):
            self.broadcasters[pid].prune(r + 1)
        return rec


# ---------------------------------------------------------------- in-memory

def run_in_memory(spec: ChaosSpec) -> dict:
    """The single-process baseline: N logical hosts over an in-memory mesh,
    driven host-by-host in lockstep ticks with per-host flight recorders
    (``flight.using_recorder``). Returns per-host streams, determinism
    digests, and round records — the reference the TCP deployment must
    match bit-for-bit."""
    plan = spec.resolved_plan()
    hosts = [LockstepHost(h, spec, plan) for h in range(spec.num_hosts)]
    recorders = [
        flight.FlightRecorder(capacity=spec.capacity, enabled=True)
        for _ in range(spec.num_hosts)
    ]
    # Key exchange is trivial in-process: one shared directory pass.
    all_pems: dict[int, str] = {}
    for host in hosts:
        all_pems.update(host.pems)
    for host in hosts:
        host.register_pems(all_pems)

    buffers: list[dict[int, list[dict]]] = [
        collections.defaultdict(list) for _ in range(spec.num_hosts)
    ]

    def route(frames: list[dict], tick: int) -> None:
        for fr in frames:
            dst_host = hosts[0].peer_host(fr["dst"])
            buffers[dst_host][tick + 1 + fr["d"]].append(fr)

    tick = 0
    for r in range(spec.rounds):
        emitted = []
        for hid, host in enumerate(hosts):
            with flight.using_recorder(recorders[hid]):
                frames = host.begin_round(r)
            route(frames, tick)
            emitted.append(bool(frames))
        while True:
            pending = [
                any(k > tick for k in buffers[h])
                for h in range(spec.num_hosts)
            ]
            if not (any(emitted) or any(pending)):
                break
            tick += 1
            emitted = []
            for hid, host in enumerate(hosts):
                todo = sorted(buffers[hid].pop(tick, []), key=_frame_key)
                with flight.using_recorder(recorders[hid]):
                    frames = host.handle_frames(todo)
                route(frames, tick)
                emitted.append(bool(frames))
        for hid, host in enumerate(hosts):
            with flight.using_recorder(recorders[hid]):
                host.end_round(r)
        tick += 1
    return {
        "streams": [rec.events(strip_time=True) for rec in recorders],
        "digests": [rec.determinism_digest() for rec in recorders],
        "records": [host.records for host in hosts],
    }


# ---------------------------------------------------------------- real TCP

class TickChannel:
    """The lockstep mesh between real host processes, riding the pooled
    async transport. Three frame kinds, all JSON over the length-prefixed
    codec: ``keys`` / ``keys_ack`` (directory bootstrap), ``f`` (a protocol
    frame with its absolute delivery tick), ``tick_done`` (the barrier
    marker with the emitted/pending flags the stop rule needs).

    Barrier safety leans on the transport's per-peer FIFO: a tick's frames
    are enqueued before its marker on the same pooled connection, so
    holding every host's ``tick_done(T)`` implies every tick-T frame is
    buffered. Markers are retried until the transport accepts them —
    control must survive the backpressure that protocol frames are allowed
    to lose."""

    def __init__(
        self,
        host_id: int,
        num_hosts: int,
        ports: list[int],
        high_water: int = 512,
        send_timeout_s: float = 30.0,
    ) -> None:
        from p2pdl_tpu.protocol.aio_transport import AsyncTCPTransport

        self.host_id = host_id
        self.num_hosts = num_hosts
        self.send_timeout_s = send_timeout_s
        self._cv = threading.Condition()
        self._buffers: dict[int, list[dict]] = collections.defaultdict(list)
        self._done: dict[int, dict[int, tuple[bool, bool]]] = (
            collections.defaultdict(dict)
        )
        self._peer_pems: dict[int, dict] = {}
        self._acks: set[int] = set()
        self.lost_sends = 0
        self.transport = AsyncTCPTransport(
            host_id, "127.0.0.1", ports[host_id], self._on_frame,
            high_water=high_water,
        )
        self.transport.start()
        for h in range(num_hosts):
            if h != host_id:
                self.transport.add_peer(h, "127.0.0.1", ports[h])

    # -- receive path (transport event loop: enqueue + notify only) -----
    def _on_frame(self, src: int, data: bytes) -> None:
        try:
            obj = json.loads(data)
        except ValueError:
            return
        kind = obj.get("t")
        with self._cv:
            if kind == "f":
                fr = obj["fr"]
                fr["w"] = base64.b64decode(fr["w"])
                self._buffers[int(obj["k"])].append(fr)
            elif kind == "tick_done":
                self._done[int(obj["tick"])][src] = (
                    bool(obj["e"]), bool(obj["p"])
                )
            elif kind == "keys":
                self._peer_pems[src] = obj["pems"]
            elif kind == "keys_ack":
                self._acks.add(src)
            self._cv.notify_all()

    def _send_reliable(self, dst: int, payload: bytes) -> None:
        """Retry a control frame past transient backpressure; the barrier
        protocol deadlocks if markers are silently lost."""
        deadline = time.monotonic() + self.send_timeout_s
        while not self.transport.send(dst, payload):
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    f"host {self.host_id}: control frame to {dst} refused "
                    f"for {self.send_timeout_s}s"
                )
            time.sleep(0.001)

    # -- key exchange ----------------------------------------------------
    def exchange_keys(
        self,
        pems: dict[int, str],
        register: Callable[[dict], None],
        timeout_s: float = 30.0,
    ) -> None:
        """Full pubkey directory on every host before any protocol frame:
        an unverifiable BRB message is silently dropped, which would be a
        nondeterministic divergence, not a fault. Announce-until-complete,
        then an ack barrier so *peers'* directories are known-full too."""
        msg = json.dumps({"t": "keys", "pems": pems}).encode()
        deadline = time.monotonic() + timeout_s
        others = [h for h in range(self.num_hosts) if h != self.host_id]

        def directory_full() -> bool:
            return all(h in self._peer_pems for h in others)

        while time.monotonic() < deadline:
            for h in others:
                self._send_reliable(h, msg)
            with self._cv:
                self._cv.wait_for(directory_full, timeout=0.2)
                if directory_full():
                    break
        if not directory_full():
            raise TimeoutError(
                f"host {self.host_id}: key exchange incomplete after "
                f"{timeout_s}s"
            )
        for h in others:
            register(self._peer_pems[h])
        ack = json.dumps({"t": "keys_ack"}).encode()

        def acked() -> bool:
            return all(h in self._acks for h in others)

        while time.monotonic() < deadline:
            for h in others:
                self._send_reliable(h, msg)
                self._send_reliable(h, ack)
            with self._cv:
                self._cv.wait_for(acked, timeout=0.2)
                if acked():
                    return
        raise TimeoutError(
            f"host {self.host_id}: key-exchange ack barrier incomplete"
        )

    # -- tick plane ------------------------------------------------------
    def send_frames(self, frames: list[dict], tick: int) -> None:
        """Ship one tick's frames: local destinations buffer directly (the
        in-memory runner's path, bit-identical); remote ones ride the
        transport and MAY be refused by backpressure — counted, not
        retried (protocol loss is the protocol's problem, by design)."""
        for fr in frames:
            delivery = tick + 1 + fr["d"]
            dst_host = self._dst_host(fr["dst"])
            if dst_host == self.host_id:
                with self._cv:
                    self._buffers[delivery].append(dict(fr))
                continue
            payload = json.dumps(
                {
                    "t": "f",
                    "k": delivery,
                    "fr": {
                        "src": fr["src"],
                        "dst": fr["dst"],
                        "q": fr["q"],
                        "c": fr["c"],
                        "d": fr["d"],
                        "w": base64.b64encode(fr["w"]).decode(),
                    },
                }
            ).encode()
            if not self.transport.send(dst_host, payload):
                self.lost_sends += 1

    def _dst_host(self, peer: int) -> int:
        return peer // self._peers_per_host

    # set by run_tcp_host once the spec is known
    _peers_per_host: int = 1

    def barrier(self, tick: int, emitted: bool, pending: bool) -> bool:
        """Announce this host's tick verdict, wait for everyone's, and
        return True when the round went globally idle (nobody emitted,
        nobody holds future frames)."""
        marker = json.dumps(
            {"t": "tick_done", "tick": tick, "e": emitted, "p": pending}
        ).encode()
        for h in range(self.num_hosts):
            if h != self.host_id:
                self._send_reliable(h, marker)
        deadline = time.monotonic() + self.send_timeout_s
        with self._cv:
            self._done[tick][self.host_id] = (emitted, pending)

            def have_all() -> bool:
                return len(self._done[tick]) == self.num_hosts

            while not have_all():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RuntimeError(
                        f"host {self.host_id}: tick {tick} barrier timed "
                        f"out with {len(self._done[tick])}/{self.num_hosts}"
                    )
                self._cv.wait(timeout=min(remaining, 0.2))
            verdicts = [self._done[tick][h] for h in range(self.num_hosts)]
            del self._done[tick]
            return not any(e or p for e, p in verdicts)

    def take(self, tick: int) -> list[dict]:
        with self._cv:
            return sorted(self._buffers.pop(tick, []), key=_frame_key)

    def has_pending(self, tick: int) -> bool:
        with self._cv:
            return any(k > tick for k in self._buffers)

    def stop(self) -> None:
        self.transport.stop()


def run_tcp_host(
    spec: ChaosSpec,
    host_id: int,
    ports: list[int],
    high_water: int = 512,
    key_timeout_s: float = 60.0,
    on_channel: Optional[Callable[["TickChannel"], None]] = None,
) -> dict:
    """One host process's whole run: key exchange, ``spec.rounds`` lockstep
    rounds over real loopback TCP, then the verdict dict (round records +
    transport stats; the flight stream lives in the process recorder for
    ``/flight`` to serve). The caller owns recorder setup — typically
    ``flight.set_recorder(FlightRecorder(capacity=spec.capacity,
    enabled=True))`` before calling, matching ``run_in_memory``."""
    plan = spec.resolved_plan()
    host = LockstepHost(host_id, spec, plan)
    ch = TickChannel(
        host_id, spec.num_hosts, ports, high_water=high_water
    )
    ch._peers_per_host = spec.peers_per_host
    if on_channel is not None:
        on_channel(ch)
    try:
        ch.exchange_keys(host.pems, host.register_pems, timeout_s=key_timeout_s)
        tick = 0
        for r in range(spec.rounds):
            frames = host.begin_round(r)
            ch.send_frames(frames, tick)
            emitted = bool(frames)
            while True:
                if ch.barrier(tick, emitted, ch.has_pending(tick)):
                    break
                tick += 1
                frames = host.handle_frames(ch.take(tick))
                ch.send_frames(frames, tick)
                emitted = bool(frames)
            host.end_round(r)
            tick += 1
        stats = ch.transport.transport_stats()
    finally:
        ch.stop()
    return {
        "host": host_id,
        "records": host.records,
        "transport": stats,
        "lost_sends": ch.lost_sends,
    }
