"""Multi-host execution: DCN-spanning device mesh + host control plane.

The reference "scales" by adding threads in one process (reference
``main.py:24-36``); every node lives on one machine and the TCP mesh is
loopback. The TPU-native multi-host shape is different in kind and this
module is its entry point:

- **Data plane**: one SPMD program over all hosts' devices. Each host runs
  the same Python program; ``jax.distributed.initialize`` wires the hosts
  into one runtime, ``global_mesh()`` builds a peer mesh over every device
  in the job, and the compiled round from ``parallel.round`` runs unchanged
  — XLA routes collectives over ICI within a slice and DCN across slices.
  Each host feeds only its addressable shard of the peer-stacked data
  (``host_local_batch``), exactly the device-put contract
  ``jax.make_array_from_process_local_data`` expects.
- **Control plane**: the BRB trust plane runs host-side over the framed-TCP
  control plane between hosts (the pooled asyncio transport
  ``protocol.aio_transport.AsyncTCPTransport`` by default, the legacy
  ``protocol.transport.TCPTransport`` on request — same wire bytes either
  way) — signatures and quorum votes never touch the device program
  (SURVEY §5: control/data plane split the reference lacks).

Single-host (or simulation) callers never need this module; the driver uses
the in-memory hub. ``initialize()`` is a no-op outside a multi-process
launch, so the same experiment script works in all three deployments
(simulation / single host / multi-host).
"""

from __future__ import annotations

import base64
import collections
import dataclasses
import json
import os
import threading
import time
from typing import Optional

import jax
import numpy as np

from p2pdl_tpu.config import Config
from p2pdl_tpu.parallel.mesh import PEER_AXIS

# Environment contract (mirrors the standard JAX multi-process launch vars).
COORDINATOR_ENV = "P2PDL_COORDINATOR"  # host:port of process 0
PROCESS_ID_ENV = "P2PDL_PROCESS_ID"
NUM_PROCESSES_ENV = "P2PDL_NUM_PROCESSES"


@dataclasses.dataclass(frozen=True)
class HostTopology:
    """This process's place in the job."""

    process_id: int
    num_processes: int
    local_devices: int
    global_devices: int

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def initialize(
    coordinator: Optional[str] = None,
    process_id: Optional[int] = None,
    num_processes: Optional[int] = None,
) -> HostTopology:
    """Join (or stand alone as) a multi-host job.

    Args fall back to the ``P2PDL_*`` env vars; with neither present this is
    a no-op single-process topology, so experiment scripts are deployment
    agnostic. Must run before the first device query, like every
    ``jax.distributed`` initialization.
    """
    coordinator = coordinator or os.environ.get(COORDINATOR_ENV)
    if process_id is None:
        process_id = int(os.environ.get(PROCESS_ID_ENV, "0"))
    if num_processes is None:
        num_processes = int(os.environ.get(NUM_PROCESSES_ENV, "1"))
    if bool(coordinator) != (num_processes > 1):
        # Half-configured multi-host would silently degrade to N independent
        # single-host jobs (every host believing it is process 0).
        raise ValueError(
            f"inconsistent multi-host config: coordinator={coordinator!r} but "
            f"num_processes={num_processes}; set both {COORDINATOR_ENV} and "
            f"{NUM_PROCESSES_ENV} (>1), or neither"
        )
    if coordinator:
        if num_processes > 1:
            # Cross-process collectives on the CPU backend need gloo (the
            # default CPU collective impl cannot span processes). No-op on
            # TPU, where ICI/DCN collectives are native.
            try:
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            except Exception:  # pragma: no cover - older jax without the knob
                pass
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    return HostTopology(
        process_id=jax.process_index(),
        num_processes=jax.process_count(),
        local_devices=jax.local_device_count(),
        global_devices=jax.device_count(),
    )


def global_mesh() -> jax.sharding.Mesh:
    """A 1-D peer mesh over every device of every host in the job.

    Host h must own the contiguous peer range ``[h*L*ppd, (h+1)*L*ppd)`` for
    L local devices, or each host's data shard would not be locally
    addressable. ``jax.devices()`` usually lists devices in process order
    already, but that is a convention, not a contract — sort by
    ``(process_index, id)`` so the mesh order is guaranteed contiguous
    per host rather than assumed.
    """
    devices = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    return jax.sharding.Mesh(np.asarray(devices), (PEER_AXIS,))


def peers_per_host(cfg: Config, topo: HostTopology, mesh: jax.sharding.Mesh) -> int:
    """The one shared shard-size derivation (assumes the homogeneous
    per-host device counts of a TPU pod slice — validated, not presumed)."""
    if mesh.devices.size % topo.num_processes != 0 or (
        topo.local_devices * topo.num_processes != mesh.devices.size
    ):
        raise ValueError(
            f"heterogeneous hosts are unsupported: {topo.num_processes} "
            f"processes x {topo.local_devices} local devices != "
            f"{mesh.devices.size} global devices"
        )
    if cfg.num_peers % mesh.devices.size != 0:
        raise ValueError(
            f"num_peers ({cfg.num_peers}) must divide the global device count "
            f"({mesh.devices.size})"
        )
    return cfg.num_peers // topo.num_processes


def host_peer_slice(cfg: Config, topo: HostTopology, mesh: jax.sharding.Mesh) -> slice:
    """The global peer-id range this host materializes data for."""
    per_host = peers_per_host(cfg, topo, mesh)
    start = topo.process_id * per_host
    return slice(start, start + per_host)


def host_local_batch(global_array: np.ndarray, cfg: Config, topo: HostTopology, mesh):
    """Build the globally-sharded peer-stacked array from this host's shard.

    ``global_array`` may be the full ``[P, ...]`` array (each host slices its
    own range — convenient when data is generated deterministically from the
    config seed, as the synthetic datasets are) or already the local
    ``[P/num_hosts, ...]`` shard.
    """
    from p2pdl_tpu.parallel.mesh import peer_sharding

    sh = peer_sharding(mesh)
    per_host = peers_per_host(cfg, topo, mesh)
    if global_array.shape[0] == cfg.num_peers:
        local = (
            global_array[host_peer_slice(cfg, topo, mesh)]
            if topo.num_processes > 1
            else global_array
        )
    elif global_array.shape[0] == per_host:
        local = global_array
    else:
        raise ValueError(
            f"array leading dim {global_array.shape[0]} is neither num_peers "
            f"({cfg.num_peers}) nor the per-host shard ({per_host})"
        )
    if topo.num_processes == 1:
        return jax.device_put(local, sh)
    return jax.make_array_from_process_local_data(sh, np.asarray(local))


def control_plane_transport(
    my_peer_id: int,
    bind_host: str,
    bind_port: int,
    handler,
    kind: str = "aio",
):
    """Control-plane endpoint for the BRB trust plane between hosts (the
    DCN path; simulation uses ``InMemoryHub`` instead). ``kind`` picks the
    plane: ``"aio"`` is the pooled single-event-loop asyncio transport
    (``protocol.aio_transport.AsyncTCPTransport`` — lazy dial, re-dial
    backoff, bounded per-peer send queues); ``"tcp"`` is the legacy
    thread-per-connection ``protocol.transport.TCPTransport``. Both speak
    the identical length-prefixed frame codec (no pickle), so they
    interoperate on the wire and callers never see the difference.
    ``MultiHostTrustPlane`` builds on this."""
    if kind == "aio":
        from p2pdl_tpu.protocol.aio_transport import AsyncTCPTransport

        t = AsyncTCPTransport(my_peer_id, bind_host, bind_port, handler)
    elif kind == "tcp":
        from p2pdl_tpu.protocol.transport import TCPTransport

        t = TCPTransport(my_peer_id, bind_host, bind_port, handler)
    else:
        raise ValueError(f"unknown control-plane transport kind: {kind!r}")
    t.start()
    return t


def shard_peer_state(state, cfg: Config, topo: HostTopology, mesh):
    """Multi-host placement of a ``PeerState``: peer-stacked leaves become
    globally-sharded arrays from each host's local slice
    (``jax.make_array_from_process_local_data``); replicated leaves are
    materialized identically on every host. The single-host analogue is
    ``parallel.peer_state.shard_state``."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from p2pdl_tpu.parallel.mesh import peer_sharding
    from p2pdl_tpu.parallel.peer_state import PeerState, params_layout

    ps = peer_sharding(mesh)
    rs = NamedSharding(mesh, P())
    sl = host_peer_slice(cfg, topo, mesh)

    def put_peer(leaf):
        local = np.asarray(leaf)
        if local.shape[0] == cfg.num_peers and topo.num_processes > 1:
            local = local[sl]
        if topo.num_processes == 1:
            return jax.device_put(local, ps)
        return jax.make_array_from_process_local_data(ps, local)

    def put_rep(leaf):
        arr = np.asarray(leaf)
        if topo.num_processes == 1:
            return jax.device_put(arr, rs)
        return jax.make_array_from_process_local_data(rs, arr)

    layout = params_layout(cfg)
    return PeerState(
        params=jax.tree.map(put_peer if layout == "peer" else put_rep, state.params),
        opt_state=jax.tree.map(
            lambda l: put_peer(l) if getattr(l, "ndim", 0) >= 1 else put_rep(l),
            state.opt_state,
        ),
        rng=put_peer(state.rng),
        round_idx=put_rep(state.round_idx),
        # Momentum buffer mirrors the (sync-layout) params placement.
        server_m=None
        if state.server_m is None
        else jax.tree.map(put_rep, state.server_m),
        server_v=None
        if state.server_v is None
        else jax.tree.map(put_rep, state.server_v),
        scaffold_c=None
        if state.scaffold_c is None
        else jax.tree.map(put_rep, state.scaffold_c),
        scaffold_ci=None
        if state.scaffold_ci is None
        else jax.tree.map(put_peer, state.scaffold_ci),
        compress_err=None
        if state.compress_err is None
        else jax.tree.map(put_peer, state.compress_err),
    )


def addressable_row(arr, row: int) -> np.ndarray:
    """Extract global row ``row`` of a peer-sharded array from this host's
    addressable shards (device->host of one row, no cross-host transfer)."""
    for sh in arr.addressable_shards:
        idx = sh.index[0]
        start = idx.start or 0
        stop = idx.stop if idx.stop is not None else arr.shape[0]
        if start <= row < stop:
            return np.asarray(sh.data)[row - start]
    raise ValueError(f"row {row} is not addressable from process {jax.process_index()}")


class MultiHostTrustPlane:
    """The BRB trust plane across hosts, riding framed TCP (the DCN path).

    Each host runs Bracha ``Broadcaster`` instances for its OWN peers only
    and fans protocol messages out over ``TCPTransport`` (reference parity:
    the echo/ready mesh of ``utils/broadcast.py:8-141``, minus its
    one-process assumption). Per round:

    1. hosts owning this round's trainers BRB-broadcast the trainers'
       update digests (``crypto.digest_update`` of the addressable delta
       rows — content commitments only cross hosts as 32-byte digests, the
       updates themselves never leave the data plane);
    2. every host reports its local peers' delivery verdicts (plus digest
       attestations for the trainers it owns) to the coordinator;
    3. the coordinator computes the global verdict — failed peers
       (receiver faults), verified trainers (delivered everywhere live with
       the attested digest) — and broadcasts the decision, which every host
       applies identically to gate the aggregate.

    Content verification is attestation-based across hosts: a trainer's
    digest is checked against the on-device delta by its OWNING host (in
    the SPMD data plane individual updates are never shipped peer-to-peer,
    so only the owner can digest them; a host Byzantine toward its own
    peers is outside this trust model — it controls those peers outright).

    Message handling is single-threaded: the transport's receive path only
    enqueues (under a condition variable it notifies); ``_pump`` drains on
    the caller's thread, so broadcaster state needs no locks (SURVEY §5
    race-safety stance). Receipt is event-driven — the pump sleeps on the
    condition and is woken the instant a frame lands, instead of the old
    0.05 s ``queue.Queue`` poll tax per frame.

    The control plane defaults to the pooled asyncio transport
    (``transport="aio"``): one dialed connection per peer host carries
    every frame, with bounded per-peer send queues and re-dial backoff.
    ``transport="tcp"`` keeps the legacy connection-per-frame plane; the
    wire bytes are identical either way.

    Every frame a host ACTS ON is authenticated: BRB messages carry their
    per-peer ECDSA signatures inside the Bracha state machine, and the
    host-level ``report``/``decision`` frames are signed with per-host
    identity keys (exchanged alongside the peer PEMs) — unsigned or
    mis-signed frames are dropped, and the decision additionally binds to
    the coordinator's key, so no host can forge the global verdict
    (the reference signs every acted-on payload too,
    ``utils/broadcast.py:19-30``; round 3 shipped these frames plain).
    """

    def __init__(
        self,
        cfg: Config,
        topo: HostTopology,
        mesh,
        host_addrs: list[tuple[str, int]],
        bind_host: str = "127.0.0.1",
        transport: str = "aio",
    ) -> None:
        from p2pdl_tpu.protocol.brb import BRBConfig, Broadcaster
        from p2pdl_tpu.protocol.crypto import (
            KeyServer,
            generate_key_pair,
            public_key_from_pem,
            public_key_pem,
            sign_data,
        )

        self._sign_data = sign_data

        self.cfg = cfg
        self.topo = topo
        sl = host_peer_slice(cfg, topo, mesh)
        self.local_peers = list(range(sl.start, sl.stop))
        self.key_server = KeyServer()
        self._from_pem = public_key_from_pem
        # Event-driven inbox: the transport's receive path appends and
        # notifies; _pump sleeps on the condition instead of polling.
        self._rx: collections.deque = collections.deque()
        self._rx_cv = threading.Condition()
        self.host_addrs = host_addrs
        self.transport = control_plane_transport(
            topo.process_id,
            bind_host,
            host_addrs[topo.process_id][1],
            lambda src, data: self._on_frame(data),
            kind=transport,
        )
        for h, (hh, pp) in enumerate(host_addrs):
            self.transport.add_peer(h, hh, pp)

        brb_cfg = BRBConfig(cfg.num_peers, cfg.byzantine_f)
        self._pems: dict[int, str] = {}
        self.broadcasters = {}
        for pid in self.local_peers:
            priv, pub = generate_key_pair()
            self.key_server.register_key(pid, pub)
            self._pems[pid] = public_key_pem(pub).decode()
            self.broadcasters[pid] = Broadcaster(brb_cfg, pid, self.key_server, priv)
        # Host identity key: signs the host-level protocol frames (report,
        # decision). Round 3 shipped these as PLAIN JSON — any process that
        # could reach a host's control port could forge the coordinator's
        # decision and admit an arbitrary trainer set (the reference, for
        # all its flaws, signs every payload it acts on,
        # ``utils/broadcast.py:19-30``). Host pubkeys ride the same
        # key-exchange phase as peer keys; the directory reuses KeyServer's
        # substitution guard.
        self._host_priv, host_pub = generate_key_pair()
        self._host_pem = public_key_pem(host_pub).decode()
        self.host_keys = KeyServer()
        self.host_keys.register_key(topo.process_id, host_pub)
        self._reports: dict[int, dict] = {}
        self._decision: Optional[dict] = None
        self._acks: set[int] = set()
        # Replay guard: signed frames are only accepted for the round the
        # plane is currently running — a recorded, validly-signed frame
        # from an earlier round must not clobber current state (stale
        # report displacing a fresh one, stale decision blocking the slot).
        self._active_round: Optional[int] = None
        # Failure-detector heartbeats ride the same plane: one probe/ack
        # round-trip per host per round, collected by host_heartbeat().
        self._hb_round: Optional[int] = None
        self._hb_acks: set[int] = set()

    # -- wire helpers ------------------------------------------------------
    @staticmethod
    def _canonical(obj: dict) -> bytes:
        """The signed byte view of a host frame: sorted-key JSON of
        everything but the signature itself. Canonical (dict order cannot
        perturb it), and unlike the reference's pickle-of-object signing
        (``utils/broadcast.py:19-21``) it never deserializes untrusted
        bytes into live objects."""
        return json.dumps(
            {k: v for k, v in obj.items() if k != "sig"},
            sort_keys=True, separators=(",", ":"),
        ).encode()

    def _sign_frame(self, obj: dict) -> dict:
        sig = self._sign_data(self._host_priv, self._canonical(obj))
        return {**obj, "sig": base64.b64encode(sig).decode()}

    def _verify_frame(self, obj: dict) -> bool:
        """True iff the frame's ``sig`` verifies under the claimed host's
        registered identity key. Missing key, missing sig, or bad sig all
        fail CLOSED — the frame is dropped, never acted on."""
        sig_b64 = obj.get("sig")
        if sig_b64 is None or "host" not in obj:
            return False
        try:
            sig = base64.b64decode(sig_b64)
        except (ValueError, TypeError):
            return False
        return self.host_keys.verify(int(obj["host"]), sig, self._canonical(obj))

    def _on_frame(self, data: bytes) -> None:
        """Transport receive hook: enqueue and wake the pump. Called from
        the transport's event loop (aio) or serve threads (tcp) — it must
        never block or touch broadcaster state."""
        with self._rx_cv:
            self._rx.append(data)
            self._rx_cv.notify()

    def _send_host(self, h: int, obj: dict) -> None:
        data = json.dumps(obj).encode()
        if h == self.topo.process_id:
            self._on_frame(data)
        else:
            self.transport.send(h, data)

    def _broadcast_hosts(self, obj: dict) -> None:
        for h in range(self.topo.num_processes):
            self._send_host(h, obj)

    def _fan_out_brb(self, msg) -> None:
        from p2pdl_tpu.protocol.transport import brb_to_wire

        wire = base64.b64encode(brb_to_wire(msg)).decode()
        self._broadcast_hosts({"t": "brb", "host": self.topo.process_id, "w": wire})

    def _handle(self, data: bytes) -> None:
        from p2pdl_tpu.protocol.transport import brb_from_wire

        try:
            obj = json.loads(data)
        except ValueError:
            return
        kind = obj.get("t")
        # Any protocol message past the key phase implies its host passed
        # the ack barrier — a lost final ack must not starve a slow host.
        if kind in ("brb", "report", "decision") and "host" in obj:
            self._acks.add(int(obj["host"]))
        if kind == "keys":
            for pid_s, pem in obj.get("keys", {}).items():
                self.key_server.register_key(int(pid_s), self._from_pem(pem.encode()))
            # Host identity key rides the same announcement (trust-on-first-
            # use into a substitution-guarded directory, like peer keys —
            # the PKI bootstrap assumption is shared, reference
            # ``utils/crypto.py:7-40``).
            if "host_key" in obj and "host" in obj:
                self.host_keys.register_key(
                    int(obj["host"]), self._from_pem(obj["host_key"].encode())
                )
        elif kind == "brb":
            msg = brb_from_wire(base64.b64decode(obj["w"]))
            if msg is None:
                return
            for bc in self.broadcasters.values():
                for out in bc.handle(msg):
                    self._fan_out_brb(out)
        elif kind == "keys_ack":
            self._acks.add(int(obj["host"]))
        elif kind == "hb":
            # Liveness probe: answer on the pump thread. Unsigned by design
            # — heartbeats only feed the failure detector's suspicion table
            # (liveness), never a trust verdict, and the detector tolerates
            # spurious "alive" exactly as it tolerates a slow network.
            h = int(obj.get("host", -1))
            if 0 <= h < self.topo.num_processes:
                self._send_host(
                    h,
                    {
                        "t": "hb_ack",
                        "host": self.topo.process_id,
                        "round": obj.get("round"),
                    },
                )
        elif kind == "hb_ack":
            if obj.get("round") == self._hb_round and "host" in obj:
                self._hb_acks.add(int(obj["host"]))
        elif kind == "report":
            # Unsigned/forged reports are dropped: a spoofed report could
            # fabricate delivery verdicts or digest attestations for peers
            # it does not own. Stale rounds are dropped too (replay guard).
            if (
                obj.get("round") == self._active_round
                and self._verify_frame(obj)
            ):
                self._reports[int(obj["host"])] = obj
        elif kind == "decision":
            # The decision gates the aggregate on every host — accept it
            # only under the COORDINATOR's key (host 0), and only for the
            # active round (a replayed signed decision from an earlier
            # round would otherwise occupy the slot and stall the round).
            if (
                obj.get("round") == self._active_round
                and int(obj.get("host", -1)) == 0
                and self._verify_frame(obj)
            ):
                self._decision = obj

    def _pump(self, deadline: float, done) -> bool:
        """Drain the inbox on the caller's thread until ``done()`` or the
        deadline. Event-driven: sleeps on the receive condition and is
        notified per frame, so frames are handled the moment they land
        (the old ``queue.Queue(timeout=0.05)`` pump paid up to 50 ms of
        latency per frame and burned wakeups while idle)."""
        while True:
            if done():
                return True
            batch: list[bytes] = []
            with self._rx_cv:
                if not self._rx:
                    now = time.monotonic()
                    if now >= deadline:
                        return done()
                    self._rx_cv.wait(timeout=deadline - now)
                while self._rx:
                    batch.append(self._rx.popleft())
            for data in batch:
                self._handle(data)

    # -- protocol rounds ---------------------------------------------------
    def exchange_keys(self, timeout_s: float = 30.0) -> None:
        """Full pubkey directory on every host before any BRB signature
        verification (the reference shares one in-process KeyServer,
        ``main.py:18`` — here keys cross hosts as PEM, never private).

        The announcement is re-sent every second until the directory fills:
        hosts start listeners at their own pace and ``TCPTransport.send`` is
        fire-and-forget, so a single early send can land before the remote
        listener is bound and vanish (re-registration of an identical key is
        a no-op, so resends are safe)."""
        msg = {
            "t": "keys",
            "host": self.topo.process_id,
            "keys": {str(p): pem for p, pem in self._pems.items()},
            "host_key": self._host_pem,
        }
        deadline = time.monotonic() + timeout_s
        done = lambda: (  # noqa: E731
            len(self.key_server) == self.cfg.num_peers
            and len(self.host_keys) == self.topo.num_processes
        )
        full = False
        while time.monotonic() < deadline:
            self._broadcast_hosts(msg)
            if self._pump(min(time.monotonic() + 1.0, deadline), done):
                full = True
                break
        if not full:
            raise TimeoutError(
                f"key exchange incomplete: {len(self.key_server)}/{self.cfg.num_peers}"
            )
        # Ack barrier: a host's own full directory does not imply its PEERS
        # have this host's keys yet — BRB messages signed by unknown keys
        # would be silently dropped. Proceed only once every host acked.
        # Keep announcing keys here too: a slow-starting host may have missed
        # every pre-barrier announcement (its listener binds after jit
        # compile), and without re-announcement the fast host would ack
        # forever while the slow one starves at a partial directory.
        acked = lambda: len(self._acks) == self.topo.num_processes  # noqa: E731
        while time.monotonic() < deadline:
            self._broadcast_hosts(msg)
            self._broadcast_hosts({"t": "keys_ack", "host": self.topo.process_id})
            if self._pump(min(time.monotonic() + 1.0, deadline), acked):
                return
        raise TimeoutError(
            f"key-exchange ack barrier incomplete: {len(self._acks)}/{self.topo.num_processes}"
        )

    def _payload(self, round_idx: int, tid: int, digest: bytes) -> bytes:
        return json.dumps(
            {"round": round_idx, "trainer": tid, "digest": digest.hex()}
        ).encode()

    def run_round(
        self,
        round_idx: int,
        trainer_ids: list[int],
        local_digests: dict[int, bytes],
        equivocate: tuple[int, ...] = (),
    ) -> tuple[list[int], list[int]]:
        """One trust round; returns ``(failed_peers, verified_trainers)`` —
        identical on every host (coordinator decision). ``local_digests``
        covers the trainers this host owns. ``equivocate`` is fault
        injection: those owned trainers send conflicting digests to the two
        halves of the host set."""
        self._active_round = round_idx
        my_trainers = [t for t in trainer_ids if t in self.broadcasters]
        for tid in my_trainers:
            payload = self._payload(round_idx, tid, local_digests[tid])
            if tid in equivocate:
                forged = self._payload(round_idx, tid, b"\xff" * 32)
                a, b = self.broadcasters[tid].broadcast_equivocating(
                    round_idx, payload, forged
                )
                half = self.topo.num_processes // 2 or 1
                from p2pdl_tpu.protocol.transport import brb_to_wire

                for h in range(self.topo.num_processes):
                    wire = base64.b64encode(brb_to_wire(a if h < half else b)).decode()
                    self._send_host(h, {"t": "brb", "w": wire})
            else:
                for msg in self.broadcasters[tid].broadcast(round_idx, payload):
                    self._fan_out_brb(msg)

        # Phase deadlines are independent: a sender whose broadcast can never
        # deliver (dead / equivocating) exhausts the delivery window, and the
        # report/decision phase still needs its own full window after that.
        self._pump(
            time.monotonic() + self.cfg.round_timeout_s,
            lambda: all(
                self.broadcasters[p].delivered(t, round_idx) is not None
                for p in self.local_peers
                for t in trainer_ids
            ),
        )

        # Local verdict report: per trainer, which of my peers delivered,
        # and one delivered payload sample (BRB guarantees agreement).
        delivered: dict[str, list[int]] = {}
        payloads: dict[str, Optional[str]] = {}
        for t in trainer_ids:
            got = [
                p
                for p in self.local_peers
                if self.broadcasters[p].delivered(t, round_idx) is not None
            ]
            delivered[str(t)] = got
            sample = (
                self.broadcasters[got[0]].delivered(t, round_idx) if got else None
            )
            payloads[str(t)] = (
                base64.b64encode(sample).decode() if sample is not None else None
            )
        report = self._sign_frame({
            "t": "report",
            "host": self.topo.process_id,
            "round": round_idx,
            "delivered": delivered,
            "payloads": payloads,
            "attest": {str(t): local_digests[t].hex() for t in my_trainers},
        })
        decision_deadline = time.monotonic() + self.cfg.round_timeout_s
        if self.topo.is_coordinator:
            self._send_host(0, report)
            self._pump(
                decision_deadline,
                lambda: len(
                    [r for r in self._reports.values() if r.get("round") == round_idx]
                )
                == self.topo.num_processes,
            )
            decision = self._decide(round_idx, trainer_ids)
            self._broadcast_hosts(
                self._sign_frame(
                    {"t": "decision", "host": self.topo.process_id,
                     "round": round_idx, **decision}
                )
            )
            # Apply the freshly-computed decision directly: report collection
            # may have exhausted decision_deadline, and the coordinator must
            # not time out waiting for its own loop-back frame while the
            # other hosts apply the decision and proceed.
            self._decision = {"round": round_idx, **decision}

        def have_decision() -> bool:
            return (
                self._decision is not None
                and self._decision.get("round") == round_idx
            )

        # Non-coordinators re-send their report until the decision lands —
        # a single lost report frame must not zero out a host's verdicts.
        while time.monotonic() < decision_deadline and not have_decision():
            if not self.topo.is_coordinator:
                self._send_host(0, report)
            self._pump(min(time.monotonic() + 1.0, decision_deadline), have_decision)
        if not have_decision():
            raise TimeoutError("no trust-plane decision before timeout")
        decision = self._decision
        self._decision = None
        self._reports = {}
        for bc in self.broadcasters.values():
            bc.prune(round_idx)
        return list(decision["failed"]), list(decision["verified"])

    def _decide(self, round_idx: int, trainer_ids: list[int]) -> dict:
        """Coordinator: combine host reports into the global verdict (same
        sender-vs-receiver failure logic as the single-process trust plane,
        ``runtime.driver._TrustPlane.run_round``)."""
        delivered_at: dict[int, set[int]] = {t: set() for t in trainer_ids}
        attested: dict[int, str] = {}
        payload_by_trainer: dict[int, set[str]] = {t: set() for t in trainer_ids}
        for rep in self._reports.values():
            if rep.get("round") != round_idx:
                continue
            for t_s, peers in rep.get("delivered", {}).items():
                delivered_at[int(t_s)].update(peers)
            for t_s, digest_hex in rep.get("attest", {}).items():
                attested[int(t_s)] = digest_hex
            for t_s, b64_payload in rep.get("payloads", {}).items():
                if b64_payload is not None:
                    payload_by_trainer[int(t_s)].add(b64_payload)
        sender_failed = {t for t in trainer_ids if not delivered_at[t]}
        failed = [
            p
            for p in range(self.cfg.num_peers)
            if any(
                p not in delivered_at[t]
                for t in trainer_ids
                if t not in sender_failed
            )
        ]
        live = [p for p in range(self.cfg.num_peers) if p not in failed]
        verified = []
        for t in trainer_ids:
            if t in sender_failed or t not in attested:
                continue
            if not live or not all(p in delivered_at[t] for p in live):
                continue
            wires = payload_by_trainer[t]
            expected = self._payload(round_idx, t, bytes.fromhex(attested[t]))
            if len(wires) == 1 and base64.b64decode(next(iter(wires))) == expected:
                verified.append(t)
        return {"failed": failed, "verified": verified}

    def host_heartbeat(
        self,
        round_idx: int,
        timeout_s: float = 2.0,
        faults=None,
    ) -> set[int]:
        """One failure-detector heartbeat round over the control plane.

        Probes every host (``hb``) and collects acks (``hb_ack``) until all
        hosts answered or the window closes; returns the responded set, the
        exact shape :class:`protocol.faults.FailureDetector.observe` folds
        into its suspicion table. Probes are re-sent once per pump slice —
        the transport is fire-and-forget and a single lost probe must not
        read as a dead host.

        ``faults`` (a :class:`protocol.faults.FaultInjector` or anything
        with its ``heartbeat_ok(round, peer)`` face) injects deterministic
        heartbeat loss on the OBSERVER side, so the same seeded FaultPlan
        drives membership identically whether the plane is in-memory or N
        real processes over TCP.
        """
        self._hb_round = round_idx
        self._hb_acks = set()
        probe = {"t": "hb", "host": self.topo.process_id, "round": round_idx}
        deadline = time.monotonic() + timeout_s
        all_acked = lambda: len(self._hb_acks) == self.topo.num_processes  # noqa: E731
        while time.monotonic() < deadline and not all_acked():
            self._broadcast_hosts(probe)
            self._pump(min(time.monotonic() + 0.25, deadline), all_acked)
        responded = {
            h
            for h in sorted(self._hb_acks)
            if faults is None or faults.heartbeat_ok(round_idx, h)
        }
        self._hb_round = None
        return responded

    def transport_stats(self) -> dict:
        """The control plane's transport counters (pooled connections,
        dialed/accepted, backpressure drops, queue depths) for /healthz;
        the legacy plane reports only its kind."""
        fn = getattr(self.transport, "transport_stats", None)
        return fn() if fn is not None else {"transport": "tcp"}

    def stop(self) -> None:
        self.transport.stop()
