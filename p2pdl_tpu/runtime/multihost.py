"""Multi-host execution: DCN-spanning device mesh + host control plane.

The reference "scales" by adding threads in one process (reference
``main.py:24-36``); every node lives on one machine and the TCP mesh is
loopback. The TPU-native multi-host shape is different in kind and this
module is its entry point:

- **Data plane**: one SPMD program over all hosts' devices. Each host runs
  the same Python program; ``jax.distributed.initialize`` wires the hosts
  into one runtime, ``global_mesh()`` builds a peer mesh over every device
  in the job, and the compiled round from ``parallel.round`` runs unchanged
  — XLA routes collectives over ICI within a slice and DCN across slices.
  Each host feeds only its addressable shard of the peer-stacked data
  (``host_local_batch``), exactly the device-put contract
  ``jax.make_array_from_process_local_data`` expects.
- **Control plane**: the BRB trust plane runs host-side over the framed-TCP
  transport (``protocol.transport.TCPTransport``) between hosts — signatures
  and quorum votes never touch the device program (SURVEY §5: control/data
  plane split the reference lacks).

Single-host (or simulation) callers never need this module; the driver uses
the in-memory hub. ``initialize()`` is a no-op outside a multi-process
launch, so the same experiment script works in all three deployments
(simulation / single host / multi-host).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax
import numpy as np

from p2pdl_tpu.config import Config
from p2pdl_tpu.parallel.mesh import PEER_AXIS

# Environment contract (mirrors the standard JAX multi-process launch vars).
COORDINATOR_ENV = "P2PDL_COORDINATOR"  # host:port of process 0
PROCESS_ID_ENV = "P2PDL_PROCESS_ID"
NUM_PROCESSES_ENV = "P2PDL_NUM_PROCESSES"


@dataclasses.dataclass(frozen=True)
class HostTopology:
    """This process's place in the job."""

    process_id: int
    num_processes: int
    local_devices: int
    global_devices: int

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def initialize(
    coordinator: Optional[str] = None,
    process_id: Optional[int] = None,
    num_processes: Optional[int] = None,
) -> HostTopology:
    """Join (or stand alone as) a multi-host job.

    Args fall back to the ``P2PDL_*`` env vars; with neither present this is
    a no-op single-process topology, so experiment scripts are deployment
    agnostic. Must run before the first device query, like every
    ``jax.distributed`` initialization.
    """
    coordinator = coordinator or os.environ.get(COORDINATOR_ENV)
    if process_id is None:
        process_id = int(os.environ.get(PROCESS_ID_ENV, "0"))
    if num_processes is None:
        num_processes = int(os.environ.get(NUM_PROCESSES_ENV, "1"))
    if bool(coordinator) != (num_processes > 1):
        # Half-configured multi-host would silently degrade to N independent
        # single-host jobs (every host believing it is process 0).
        raise ValueError(
            f"inconsistent multi-host config: coordinator={coordinator!r} but "
            f"num_processes={num_processes}; set both {COORDINATOR_ENV} and "
            f"{NUM_PROCESSES_ENV} (>1), or neither"
        )
    if coordinator:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    return HostTopology(
        process_id=jax.process_index(),
        num_processes=jax.process_count(),
        local_devices=jax.local_device_count(),
        global_devices=jax.device_count(),
    )


def global_mesh() -> jax.sharding.Mesh:
    """A 1-D peer mesh over every device of every host in the job.

    Host h must own the contiguous peer range ``[h*L*ppd, (h+1)*L*ppd)`` for
    L local devices, or each host's data shard would not be locally
    addressable. ``jax.devices()`` usually lists devices in process order
    already, but that is a convention, not a contract — sort by
    ``(process_index, id)`` so the mesh order is guaranteed contiguous
    per host rather than assumed.
    """
    devices = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    return jax.sharding.Mesh(np.asarray(devices), (PEER_AXIS,))


def peers_per_host(cfg: Config, topo: HostTopology, mesh: jax.sharding.Mesh) -> int:
    """The one shared shard-size derivation (assumes the homogeneous
    per-host device counts of a TPU pod slice — validated, not presumed)."""
    if mesh.devices.size % topo.num_processes != 0 or (
        topo.local_devices * topo.num_processes != mesh.devices.size
    ):
        raise ValueError(
            f"heterogeneous hosts are unsupported: {topo.num_processes} "
            f"processes x {topo.local_devices} local devices != "
            f"{mesh.devices.size} global devices"
        )
    if cfg.num_peers % mesh.devices.size != 0:
        raise ValueError(
            f"num_peers ({cfg.num_peers}) must divide the global device count "
            f"({mesh.devices.size})"
        )
    return cfg.num_peers // topo.num_processes


def host_peer_slice(cfg: Config, topo: HostTopology, mesh: jax.sharding.Mesh) -> slice:
    """The global peer-id range this host materializes data for."""
    per_host = peers_per_host(cfg, topo, mesh)
    start = topo.process_id * per_host
    return slice(start, start + per_host)


def host_local_batch(global_array: np.ndarray, cfg: Config, topo: HostTopology, mesh):
    """Build the globally-sharded peer-stacked array from this host's shard.

    ``global_array`` may be the full ``[P, ...]`` array (each host slices its
    own range — convenient when data is generated deterministically from the
    config seed, as the synthetic datasets are) or already the local
    ``[P/num_hosts, ...]`` shard.
    """
    from p2pdl_tpu.parallel.mesh import peer_sharding

    sh = peer_sharding(mesh)
    per_host = peers_per_host(cfg, topo, mesh)
    if global_array.shape[0] == cfg.num_peers:
        local = (
            global_array[host_peer_slice(cfg, topo, mesh)]
            if topo.num_processes > 1
            else global_array
        )
    elif global_array.shape[0] == per_host:
        local = global_array
    else:
        raise ValueError(
            f"array leading dim {global_array.shape[0]} is neither num_peers "
            f"({cfg.num_peers}) nor the per-host shard ({per_host})"
        )
    if topo.num_processes == 1:
        return jax.device_put(local, sh)
    return jax.make_array_from_process_local_data(sh, np.asarray(local))


def control_plane_transport(
    my_peer_id: int,
    bind_host: str,
    bind_port: int,
    handler,
):
    """Framed-TCP control-plane endpoint for the BRB trust plane between
    hosts (the DCN path; simulation uses ``InMemoryHub`` instead). Thin
    convenience over ``protocol.transport.TCPTransport``: same wire codec as
    every other control message (length-prefixed JSON, no pickle)."""
    from p2pdl_tpu.protocol.transport import TCPTransport

    t = TCPTransport(my_peer_id, bind_host, bind_port, handler)
    t.start()
    return t
