"""Lock-discipline rule: shared mutable state crosses the lock boundary.

The hub/transport/cluster/telemetry classes all follow one convention: a
``self._lock = threading.Lock()`` in ``__init__`` and every post-init
write to shared attributes under ``with self._lock:``. This rule does a
per-class lexical dataflow over that convention and flags any attribute
written *both* inside and outside the lock — the mixed case is the bug
(an attribute consistently written without the lock is usually
single-threaded by design and produces no finding; requiring both sides
keeps the rule's false-positive rate near zero).

Since PR 10 the rule is interprocedural: a lexically-unlocked write is
exonerated when its enclosing method provably runs with the lock held on
*every* resolved call path (``with self._lock: self._flush()`` calling a
helper that writes without its own ``with``). Attribution comes from the
shared :mod:`lockflow` lock model over the conservative call graph —
entry points and dynamically-dispatched calls are never exonerated.

Tracked writes: ``self.x = ...``, ``self.x += ...``, ``self.x[...] = ...``
and in-place mutator calls (``self.x.append(...)``, ``.pop()``,
``.update()`` ...). ``__init__`` is exempt (the object is not yet shared).
The same analysis runs at module level for ``LOCK = threading.Lock()``
globals guarding ``global X`` writes (the driver's digest-pool
double-checked locking pattern).
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from p2pdl_tpu.analysis.engine import (
    Finding,
    ModuleInfo,
    Program,
    ProgramRule,
    register,
)

_LOCK_FACTORIES = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
    # The hybrid model: asyncio locks get the same program-unique
    # identities, so lock-order cycles span the thread<->loop boundary
    # and the asyncflow rules can tell the two worlds apart by factory.
    "asyncio.Lock",
    "asyncio.Condition",
    "asyncio.Semaphore",
    "asyncio.BoundedSemaphore",
}
_MUTATORS = {
    "append",
    "appendleft",
    "add",
    "discard",
    "remove",
    "clear",
    "extend",
    "insert",
    "pop",
    "popitem",
    "popleft",
    "update",
    "setdefault",
}


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.x`` -> ``"x"``, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _WriteLog:
    """Per-attribute write sites, split by lock-held state."""

    def __init__(self) -> None:
        self.inside: dict[str, list[ast.AST]] = {}
        self.outside: dict[str, list[ast.AST]] = {}

    def record(self, attr: str, node: ast.AST, locked: bool) -> None:
        pool = self.inside if locked else self.outside
        pool.setdefault(attr, []).append(node)


def _writes_in_stmt(stmt: ast.stmt, attr_of, log: _WriteLog, locked: bool) -> None:
    """Record every tracked write inside one simple statement (or the
    header expressions of a compound one). ``attr_of`` maps an expression
    to the tracked attribute name, or None."""
    for node in ast.walk(stmt):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                attr = attr_of(base)
                if attr is not None:
                    log.record(attr, t, locked)
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in _MUTATORS:
                attr = attr_of(node.func.value)
                if attr is not None:
                    log.record(attr, node, locked)


def _is_lock_expr(expr: ast.AST, lock_attrs: set[str], lock_globals: set[str]) -> bool:
    attr = _self_attr(expr)
    if attr is not None and attr in lock_attrs:
        return True
    if isinstance(expr, ast.Name) and expr.id in lock_globals:
        return True
    return False


def _scan_stmts(
    stmts: list[ast.stmt],
    attr_of,
    log: _WriteLog,
    locked: bool,
    lock_attrs: set[str],
    lock_globals: set[str],
) -> None:
    for st in stmts:
        if isinstance(st, (ast.With, ast.AsyncWith)):
            takes_lock = any(
                _is_lock_expr(item.context_expr, lock_attrs, lock_globals)
                for item in st.items
            )
            # Header expressions (the context managers) run unlocked.
            for item in st.items:
                _writes_in_stmt(
                    ast.Expr(value=item.context_expr), attr_of, log, locked
                )
            _scan_stmts(
                st.body, attr_of, log, locked or takes_lock, lock_attrs, lock_globals
            )
        elif isinstance(st, (ast.If, ast.While)):
            _writes_in_stmt(ast.Expr(value=st.test), attr_of, log, locked)
            _scan_stmts(st.body, attr_of, log, locked, lock_attrs, lock_globals)
            _scan_stmts(st.orelse, attr_of, log, locked, lock_attrs, lock_globals)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            _writes_in_stmt(ast.Expr(value=st.iter), attr_of, log, locked)
            _scan_stmts(st.body, attr_of, log, locked, lock_attrs, lock_globals)
            _scan_stmts(st.orelse, attr_of, log, locked, lock_attrs, lock_globals)
        elif isinstance(st, ast.Try):
            _scan_stmts(st.body, attr_of, log, locked, lock_attrs, lock_globals)
            for h in st.handlers:
                _scan_stmts(h.body, attr_of, log, locked, lock_attrs, lock_globals)
            _scan_stmts(st.orelse, attr_of, log, locked, lock_attrs, lock_globals)
            _scan_stmts(st.finalbody, attr_of, log, locked, lock_attrs, lock_globals)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A closure defined here may run later on any thread: treat its
            # body as unlocked regardless of the enclosing with-block.
            _scan_stmts(st.body, attr_of, log, False, lock_attrs, lock_globals)
        else:
            _writes_in_stmt(st, attr_of, log, locked)


class LockDisciplineRule(ProgramRule):
    name = "lock-discipline"
    description = "shared attribute written both with and without its lock"
    scope = None  # everywhere

    def check_program(self, program: Program) -> Iterable[Finding]:
        from p2pdl_tpu.analysis.lockflow import lock_model_for

        model = lock_model_for(program)
        for mod in program.mods:
            yield from self._check_classes(mod, model)
            yield from self._check_module_globals(mod, model)

    @staticmethod
    def _site_exonerated(mod: ModuleInfo, model, node: ast.AST, lids) -> bool:
        """A lexically-unlocked write is fine when its enclosing function
        only ever runs with the lock held (call-graph attribution)."""
        fn_key = f"{mod.relpath}::{mod.context_of(node)}"
        return model.entered_locked(fn_key, lids)

    # -- classes with self._lock ------------------------------------------

    def _check_classes(self, mod: ModuleInfo, model) -> Iterable[Finding]:
        for cls in mod.walk():
            if not isinstance(cls, ast.ClassDef):
                continue
            lock_attrs: set[str] = set()
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                    if mod.dotted(node.value.func) in _LOCK_FACTORIES:
                        for t in node.targets:
                            attr = _self_attr(t)
                            if attr is not None:
                                lock_attrs.add(attr)
            if not lock_attrs:
                continue
            # context_of on a class node is its own qualname already.
            lids = model.class_lock_ids(mod.relpath, mod.context_of(cls))

            def attr_of(expr: ast.AST) -> Optional[str]:
                attr = _self_attr(expr)
                if attr is None or attr in lock_attrs:
                    return None
                return attr

            log = _WriteLog()
            for item in cls.body:
                if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if item.name == "__init__":
                    continue  # not yet shared across threads
                _scan_stmts(item.body, attr_of, log, False, lock_attrs, set())
            lock_name = sorted(lock_attrs)[0]
            for attr in sorted(set(log.inside) & set(log.outside)):
                remaining = [
                    n
                    for n in log.outside[attr]
                    if not self._site_exonerated(mod, model, n, lids)
                ]
                if not remaining:
                    continue
                first = min(remaining, key=lambda n: getattr(n, "lineno", 0))
                yield mod.finding(
                    self.name,
                    first,
                    f"attribute `self.{attr}` of `{cls.name}` is written both "
                    f"with and without `self.{lock_name}` held",
                )

    # -- module-level LOCK = threading.Lock() globals ----------------------

    def _check_module_globals(self, mod: ModuleInfo, model) -> Iterable[Finding]:
        lock_globals: set[str] = set()
        for st in mod.tree.body:
            if isinstance(st, ast.Assign) and isinstance(st.value, ast.Call):
                if mod.dotted(st.value.func) in _LOCK_FACTORIES:
                    for t in st.targets:
                        if isinstance(t, ast.Name):
                            lock_globals.add(t.id)
        if not lock_globals:
            return
        lids = [("G", mod.relpath, name) for name in sorted(lock_globals)]

        log = _WriteLog()
        for fn in mod.walk():
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            declared: set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    declared.update(node.names)
            declared -= lock_globals
            if not declared:
                continue

            def attr_of(expr: ast.AST) -> Optional[str]:
                if isinstance(expr, ast.Name) and expr.id in declared:
                    return expr.id
                return None

            _scan_stmts(fn.body, attr_of, log, False, set(), lock_globals)
        lock_name = sorted(lock_globals)[0]
        for name in sorted(set(log.inside) & set(log.outside)):
            remaining = [
                n
                for n in log.outside[name]
                if not self._site_exonerated(mod, model, n, lids)
            ]
            if not remaining:
                continue
            first = min(remaining, key=lambda n: getattr(n, "lineno", 0))
            yield mod.finding(
                self.name,
                first,
                f"global `{name}` is written both with and without "
                f"`{lock_name}` held",
            )


register(LockDisciplineRule())
