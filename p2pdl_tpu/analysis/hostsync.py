"""Host-sync rule: protect the one-device->host-transfer-per-round path.

PR 4 collapsed the per-round readback to a single ``jax.device_get`` of a
packed digest buffer (``driver.d2h_transfers`` counts it). Any new
``.item()`` / ``np.asarray`` / ``float()``-on-array sneaking into
``runtime/driver.py`` or ``parallel/round.py`` silently reintroduces a
blocking sync per call site. This rule flags:

- explicit transfers: ``jax.device_get(...)``, ``numpy.asarray(...)``,
  ``numpy.array(...)`` (H2D-side ``jax.numpy.asarray`` is fine and not
  flagged);
- ``.item()`` calls with no arguments (the classic scalar sync);
- ``float()`` / ``int()`` / ``bool()`` casts whose argument mentions a
  device-suggesting expression: a name ending in ``_dev``, the eval-result
  dict ``ev``, or the on-device ``self.state`` tree;
- ``jax.block_until_ready(...)`` / ``x.block_until_ready()``: a blocking
  device-completion wait. The perf plane's phase decomposition sanctions
  exactly one such site (the deferred flush's ``round.device`` sub-phase,
  where blocking IS the measurement) — anywhere else it serializes the
  pipelined loop.

Sanctioned sites (the audited single transfer, deferred block-boundary
readbacks) carry inline ``# p2plint: disable=hostsync-transfer`` comments
with reasons, or live in the committed baseline.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from p2pdl_tpu.analysis.engine import Finding, ModuleInfo, Rule, register

_TRANSFER_FNS = {"jax.device_get", "numpy.asarray", "numpy.array"}
_CAST_FNS = {"float", "int", "bool"}


def _device_marker(mod: ModuleInfo, node: ast.AST) -> Optional[str]:
    """A human-readable marker if ``node``'s subtree mentions a
    device-suggesting expression, else None."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            if sub.id.endswith("_dev") or sub.id == "ev":
                return sub.id
        elif isinstance(sub, ast.Attribute):
            if sub.attr.endswith("_dev"):
                return sub.attr
            dotted = mod.dotted(sub)
            if dotted is not None and dotted.startswith("self.state"):
                return "self.state"
    return None


class HostSyncRule(Rule):
    name = "hostsync-transfer"
    description = "implicit device->host transfer outside the audited path"
    scope = ("runtime/driver.py", "parallel/round.py")

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.dotted(node.func)
            if dotted == "jax.block_until_ready" or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"
            ):
                yield mod.finding(
                    self.name,
                    node,
                    "`block_until_ready` blocks the host on device "
                    "completion; only the deferred flush's round.device "
                    "sub-phase may wait — elsewhere it serializes the "
                    "pipelined round loop",
                )
            elif dotted in _TRANSFER_FNS:
                yield mod.finding(
                    self.name,
                    node,
                    f"device->host transfer `{dotted}(...)` outside the "
                    "audited single-transfer path; batch it into the packed "
                    "digest readback or justify it",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "item"
                and not node.args
                and not node.keywords
            ):
                yield mod.finding(
                    self.name,
                    node,
                    "`.item()` forces a blocking device->host scalar sync; "
                    "read scalars from the packed digest buffer instead",
                )
            elif dotted in _CAST_FNS and node.args:
                marker = _device_marker(mod, node.args[0])
                if marker is not None:
                    yield mod.finding(
                        self.name,
                        node,
                        f"host scalar cast `{dotted}(...)` over "
                        f"device-derived value `{marker}` forces a "
                        "device->host sync",
                    )


register(HostSyncRule())
