"""Whole-program lock rules: shared lock model, membership, lock order.

:class:`LockModel` computes, once per lint run and shared by every lock
rule (including the upgraded ``lock-discipline``):

- which classes own which lock attributes (``self._lock = Lock()``) and
  which modules own lock globals, with the factory recorded so reentrant
  ``RLock`` can be told apart from ``Lock``;
- for every function, the set of lock identities lexically held around
  every node, plus each acquisition event (``with self._lock:``);
- call-graph attribution (:func:`dataflow.always_locked`): whether a
  function is entered with a given lock held on *every* resolved path.

Lock identity is ``(class, attr)`` for instance locks (instances of the
same class collapse — acquiring two instances' locks in any order is
already an ordering hazard) and ``(module, name)`` for globals. An
attribute acquisition on a non-``self`` object (``state.lock``) resolves
only when exactly one class in the program owns a lock attribute of that
name; ambiguous names (every class calls it ``_lock``) resolve to
nothing rather than to a guess.

Rules:

- ``lock-membership`` — ROADMAP item 5's invariant: membership state
  (peers / members / trainers / stopped / suspected) of a lock-owning
  class may only mutate with that lock held, lexically or via call-graph
  attribution; and never from outside the owning class (the cross-object
  ``node.cluster._stopped.add(...)`` shape — route it through a
  lock-holding method instead).
- ``lock-order`` — builds the acquired-while-holding digraph across
  acquisition sites (including locks taken transitively through resolved
  calls) and flags cycles, plus self-re-acquisition of a non-reentrant
  ``Lock``.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from p2pdl_tpu.analysis import dataflow
from p2pdl_tpu.analysis.callgraph import FunctionNode
from p2pdl_tpu.analysis.engine import (
    Finding,
    ModuleInfo,
    Program,
    ProgramRule,
    register,
)
from p2pdl_tpu.analysis.locks import _LOCK_FACTORIES, _MUTATORS, _self_attr

#: Attribute names that hold membership state in a lock-owning class.
_MEMBERSHIP_RE = re.compile(
    r"(^|_)(peer|peers|member|members|membership|trainer|trainers|"
    r"stopped|suspected|role|roles)(_|$)"
)

_AMBIGUOUS = ("<ambiguous>",)

#: Factories whose locks deadlock on re-acquisition by the same holder.
#: ``threading.RLock`` and ``threading.Condition`` (which wraps an RLock)
#: are reentrant; ``asyncio.Lock``/``Condition`` are not.
_NON_REENTRANT = frozenset({"threading.Lock", "asyncio.Lock", "asyncio.Condition"})


def _class_qual(mod: ModuleInfo, cls: ast.ClassDef) -> str:
    # ``context_of`` on a class node is its own qualname already.
    return mod.context_of(cls)


def own_nodes(fn: FunctionNode) -> Iterable[ast.AST]:
    """Walk a function body without descending into nested defs (those
    are separate call-graph nodes and would double-report)."""

    def rec(node: ast.AST) -> Iterable[ast.AST]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            yield child
            yield from rec(child)

    for st in fn.node.body:
        yield st
        yield from rec(st)


class LockModel:
    def __init__(self, program: Program) -> None:
        self.program = program
        self.graph = program.callgraph
        #: (relpath, cls_qual) -> {lock_attr: factory_dotted}
        self.class_locks: dict[tuple[str, str], dict[str, str]] = {}
        #: relpath -> {global_name: factory_dotted}
        self.module_locks: dict[str, dict[str, str]] = {}
        #: lock attr name -> unique (relpath, cls_qual) owner or _AMBIGUOUS
        self._lock_attr_owner: dict[str, tuple] = {}
        #: fn key -> {id(node): frozenset(lock ids held)}
        self.node_held: dict[str, dict[int, frozenset]] = {}
        #: fn key -> [(lock_id, acquire_expr, held_before)]
        self.acquires: dict[str, list[tuple]] = {}
        self._safe_cache: dict[tuple, set[str]] = {}
        self._collect_owners()
        self._scan_functions()

    # -- ownership ---------------------------------------------------------

    def _collect_owners(self) -> None:
        for mod in self.program.mods:
            for node in mod.walk():
                if isinstance(node, ast.ClassDef):
                    attrs: dict[str, str] = {}
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Assign) and isinstance(
                            sub.value, ast.Call
                        ):
                            factory = mod.dotted(sub.value.func)
                            if factory in _LOCK_FACTORIES:
                                for t in sub.targets:
                                    attr = _self_attr(t)
                                    if attr is not None:
                                        attrs[attr] = factory
                    if attrs:
                        key = (mod.relpath, _class_qual(mod, node))
                        self.class_locks[key] = attrs
                        for attr in attrs:
                            if attr in self._lock_attr_owner:
                                self._lock_attr_owner[attr] = _AMBIGUOUS
                            else:
                                self._lock_attr_owner[attr] = key
            globs: dict[str, str] = {}
            for st in mod.tree.body:
                if isinstance(st, ast.Assign) and isinstance(st.value, ast.Call):
                    factory = mod.dotted(st.value.func)
                    if factory in _LOCK_FACTORIES:
                        for t in st.targets:
                            if isinstance(t, ast.Name):
                                globs[t.id] = factory
            if globs:
                self.module_locks[mod.relpath] = globs

    def lock_factory(self, lid: tuple) -> Optional[str]:
        if lid[0] == "C":
            return self.class_locks.get((lid[1], lid[2]), {}).get(lid[3])
        return self.module_locks.get(lid[1], {}).get(lid[2])

    def display(self, lid: tuple) -> str:
        if lid[0] == "C":
            return f"{lid[2]}.{lid[3]}"
        return lid[2]

    def class_lock_ids(self, relpath: str, cls_qual: str) -> list[tuple]:
        return [
            ("C", relpath, cls_qual, attr)
            for attr in sorted(self.class_locks.get((relpath, cls_qual), {}))
        ]

    # -- per-function lexical state ---------------------------------------

    def _lock_id_resolver(self, fn: FunctionNode):
        def lock_id(expr: ast.AST) -> Optional[tuple]:
            attr = _self_attr(expr)
            if attr is not None:
                if fn.cls is not None and attr in self.class_locks.get(
                    (fn.relpath, fn.cls), {}
                ):
                    return ("C", fn.relpath, fn.cls, attr)
                return None
            if isinstance(expr, ast.Name):
                if expr.id in self.module_locks.get(fn.relpath, {}):
                    return ("G", fn.relpath, expr.id)
                return None
            if isinstance(expr, ast.Attribute):
                owner = self._lock_attr_owner.get(expr.attr)
                if owner is not None and owner != _AMBIGUOUS:
                    return ("C", owner[0], owner[1], expr.attr)
            return None

        return lock_id

    def _scan_functions(self) -> None:
        for key, fn in self.graph.functions.items():
            held_map: dict[int, frozenset] = {}
            acq: list[tuple] = []
            for ev in dataflow.iter_lock_states(
                fn.node.body,
                self._lock_id_resolver(fn),
                descend_closures=False,
            ):
                if ev[0] == "node":
                    held_map[id(ev[1])] = ev[2]
                else:
                    acq.append((ev[1], ev[2], ev[3]))
            self.node_held[key] = held_map
            self.acquires[key] = acq

    def held_at(self, fn_key: str, node: ast.AST) -> frozenset:
        return self.node_held.get(fn_key, {}).get(id(node), frozenset())

    def lock_id(self, fn: FunctionNode, expr: ast.AST) -> Optional[tuple]:
        """Public resolver: the lock identity an expression denotes inside
        ``fn`` (``self._lock`` / module global / unique foreign attr)."""
        return self._lock_id_resolver(fn)(expr)

    # -- call-graph attribution -------------------------------------------

    def always_locked_for(self, lid: tuple) -> set[str]:
        if lid not in self._safe_cache:
            self._safe_cache[lid] = dataflow.always_locked(
                self.graph,
                lambda s: lid in self.held_at(s.caller, s.call),
            )
        return self._safe_cache[lid]

    def entered_locked(self, fn_key: str, lids: Iterable[tuple]) -> bool:
        return any(fn_key in self.always_locked_for(lid) for lid in lids)


def lock_model_for(program: Program) -> LockModel:
    model = getattr(program, "_lock_model", None)
    if model is None:
        model = LockModel(program)
        program._lock_model = model
    return model


# ---- write-site extraction ---------------------------------------------------


def _write_targets(node: ast.AST) -> list[ast.AST]:
    """Attribute-or-subscript write targets of one node (assignments and
    in-place mutator calls)."""
    out: list[ast.AST] = []
    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in targets:
            out.append(t.value if isinstance(t, ast.Subscript) else t)
    elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr in _MUTATORS:
            base = node.func.value
            out.append(base.value if isinstance(base, ast.Subscript) else base)
    return out


def _attr_and_base(target: ast.AST) -> tuple[Optional[str], Optional[ast.AST]]:
    """``<base>.<attr>`` -> (attr, base); None for non-attribute targets."""
    if isinstance(target, ast.Attribute):
        return target.attr, target.value
    return None, None


# ---- lock-membership ----------------------------------------------------------


class MembershipLockRule(ProgramRule):
    name = "lock-membership"
    description = (
        "membership state of a lock-owning class mutated without its lock "
        "(ROADMAP item 5: membership mutations only under the cluster lock)"
    )
    scope = None  # everywhere

    def check_program(self, program: Program) -> Iterable[Finding]:
        model = lock_model_for(program)
        # membership attr -> owning (relpath, cls_qual); ambiguous removed.
        owners: dict[str, tuple] = {}
        members_of: dict[tuple, set[str]] = {}
        for (relpath, cls_qual), _locks in model.class_locks.items():
            mod = program.module(relpath)
            if mod is None or not self.applies(mod):
                continue
            attrs = self._membership_attrs(model, relpath, cls_qual)
            if not attrs:
                continue
            members_of[(relpath, cls_qual)] = attrs
            for a in attrs:
                owners[a] = _AMBIGUOUS if a in owners else (relpath, cls_qual)
        owners = {a: o for a, o in owners.items() if o != _AMBIGUOUS}

        findings: list[Finding] = []
        findings.extend(self._check_intra(program, model, members_of))
        findings.extend(self._check_cross(program, model, owners))
        return findings

    def _membership_attrs(
        self, model: LockModel, relpath: str, cls_qual: str
    ) -> set[str]:
        attrs: set[str] = set()
        lock_attrs = set(model.class_locks.get((relpath, cls_qual), {}))
        for fn in model.graph.functions.values():
            if fn.relpath != relpath or not self._in_class(fn, cls_qual):
                continue
            for node in own_nodes(fn):
                for target in _write_targets(node):
                    attr = _self_attr(target)
                    if (
                        attr is not None
                        and attr not in lock_attrs
                        and _MEMBERSHIP_RE.search(attr)
                    ):
                        attrs.add(attr)
        return attrs

    @staticmethod
    def _in_class(fn: FunctionNode, cls_qual: str) -> bool:
        return fn.cls == cls_qual or fn.qualname.startswith(cls_qual + ".")

    def _check_intra(
        self,
        program: Program,
        model: LockModel,
        members_of: dict[tuple, set[str]],
    ) -> Iterable[Finding]:
        for (relpath, cls_qual), attrs in sorted(members_of.items()):
            mod = program.module(relpath)
            lids = model.class_lock_ids(relpath, cls_qual)
            lock_display = model.display(lids[0]) if lids else "its lock"
            for fn in model.graph.functions.values():
                if fn.relpath != relpath or not self._in_class(fn, cls_qual):
                    continue
                if fn.qualname == f"{cls_qual}.__init__":
                    continue  # not yet shared across threads
                entered = model.entered_locked(fn.key, lids)
                for node in own_nodes(fn):
                    for target in _write_targets(node):
                        attr = _self_attr(target)
                        if attr is None or attr not in attrs:
                            continue
                        held = model.held_at(fn.key, node)
                        if any(l in held for l in lids) or entered:
                            continue
                        yield mod.finding(
                            self.name,
                            node,
                            f"membership state `self.{attr}` of `{cls_qual}` "
                            f"is mutated without `{lock_display}` held on "
                            "every path into "
                            f"`{fn.qualname}`",
                        )

    def _check_cross(
        self,
        program: Program,
        model: LockModel,
        owners: dict[str, tuple],
    ) -> Iterable[Finding]:
        if not owners:
            return
        for mod in program.mods:
            if not self.applies(mod):
                continue
            for node in mod.walk():
                for target in _write_targets(node):
                    attr, base = _attr_and_base(target)
                    if attr is None or attr not in owners:
                        continue
                    if isinstance(base, ast.Name) and base.id == "self":
                        continue  # intra-class: _check_intra's job
                    relpath, cls_qual = owners[attr]
                    ctx = mod.context_of(node)
                    if mod.relpath == relpath and (
                        ctx == cls_qual or ctx.startswith(cls_qual + ".")
                    ):
                        continue  # still inside the owning class
                    yield mod.finding(
                        self.name,
                        node,
                        f"membership state `.{attr}` of `{cls_qual}` is "
                        "mutated from outside the owning class — route it "
                        f"through a `{cls_qual}` method that holds its lock",
                    )


# ---- lock-order ---------------------------------------------------------------


class LockOrderRule(ProgramRule):
    name = "lock-order"
    description = "inconsistent lock acquisition order across call paths"
    scope = None  # everywhere

    def check_program(self, program: Program) -> Iterable[Finding]:
        model = lock_model_for(program)
        graph = program.callgraph
        direct = {
            k: frozenset(lid for lid, _, _ in model.acquires.get(k, ()))
            for k in graph.functions
        }
        trans = dataflow.transitive_acquires(graph, direct)

        # edges[a][b] = representative site where b is acquired with a held
        edges: dict[tuple, dict[tuple, tuple[str, ast.AST]]] = {}

        def add_edge(a: tuple, b: tuple, relpath: str, site: ast.AST) -> None:
            cur = edges.setdefault(a, {})
            prev = cur.get(b)
            key = (relpath, getattr(site, "lineno", 0))
            if prev is None or (prev[0], getattr(prev[1], "lineno", 0)) > key:
                cur[b] = (relpath, site)

        for k, fn in graph.functions.items():
            for lid, expr, held_before in model.acquires.get(k, ()):
                for h in held_before:
                    add_edge(h, lid, fn.relpath, expr)
            for site in graph.callees_of(k):
                held = model.held_at(k, site.call)
                if not held:
                    continue
                for l2 in trans.get(site.callee, ()):
                    for h in held:
                        if h == l2 and model.lock_factory(h) not in _NON_REENTRANT:
                            continue  # reentrant: re-acquiring is fine
                        add_edge(h, l2, fn.relpath, site.call)

        yield from self._cycle_findings(program, model, edges)

    def _cycle_findings(
        self,
        program: Program,
        model: LockModel,
        edges: dict[tuple, dict[tuple, tuple[str, ast.AST]]],
    ) -> Iterable[Finding]:
        # Self-loops: re-acquiring a non-reentrant lock while held.
        for a, outs in sorted(edges.items()):
            if a in outs:
                relpath, site = outs[a]
                mod = program.module(relpath)
                if mod is not None:
                    yield mod.finding(
                        self.name,
                        site,
                        f"non-reentrant lock `{model.display(a)}` may be "
                        "re-acquired while already held (self-deadlock)",
                    )
        # Multi-lock cycles: strongly connected components of size > 1.
        for scc in _sccs({a: set(outs) for a, outs in edges.items()}):
            if len(scc) < 2:
                continue
            names = sorted(model.display(l) for l in scc)
            # Deterministic anchor: the earliest edge site inside the SCC.
            sites = [
                edges[a][b]
                for a in scc
                for b in edges.get(a, {})
                if b in scc and b != a
            ]
            if not sites:
                continue
            relpath, site = min(
                sites, key=lambda s: (s[0], getattr(s[1], "lineno", 0))
            )
            mod = program.module(relpath)
            if mod is None:
                continue
            yield mod.finding(
                self.name,
                site,
                f"lock-order cycle among {', '.join(f'`{n}`' for n in names)}: "
                "inconsistent acquisition order across call paths can deadlock",
            )


def _sccs(adj: dict) -> list[set]:
    """Tarjan strongly-connected components over a small digraph."""
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    out: list[set] = []
    counter = [0]
    nodes = set(adj) | {b for outs in adj.values() for b in outs}

    def strongconnect(v) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in adj.get(v, ()):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = set()
            while True:
                w = stack.pop()
                on_stack.discard(w)
                comp.add(w)
                if w == v:
                    break
            out.append(comp)

    for v in sorted(nodes):
        if v not in index:
            strongconnect(v)
    return out


register(MembershipLockRule())
register(LockOrderRule())
