"""p2plint: project-native static invariant checks (pure stdlib).

Public surface: the engine (:func:`run_lint`, :func:`lint_source`,
:func:`cli_lint`) plus the rule families registered on import —
determinism, host-sync, lock discipline, wire conformance, and the
interprocedural families (wire-taint, lock-membership, lock-order, and
the async family: async-blocking-call / async-lock-stall /
async-coroutine-drop / async-loop-state) built on the
call-graph/dataflow layer (``callgraph.py`` / ``dataflow.py`` /
``asyncflow.py``). See ``engine.py`` for the suppression and baseline
model.
"""

from p2pdl_tpu.analysis.engine import (  # noqa: F401
    DEFAULT_BASELINE_PATH,
    Finding,
    LintResult,
    ModuleInfo,
    Program,
    ProgramRule,
    Rule,
    all_rules,
    changed_files,
    cli_lint,
    lint_source,
    lint_tree,
    load_baseline,
    render_json,
    render_sarif,
    render_text,
    resolve_rules,
    run_lint,
    write_baseline_file,
)
