"""p2plint: project-native static invariant checks (pure stdlib).

Public surface: the engine (:func:`run_lint`, :func:`lint_source`,
:func:`cli_lint`) plus the four rule families registered on import —
determinism, host-sync, lock discipline, and wire conformance. See
``engine.py`` for the suppression and baseline model.
"""

from p2pdl_tpu.analysis.engine import (  # noqa: F401
    DEFAULT_BASELINE_PATH,
    Finding,
    LintResult,
    ModuleInfo,
    Rule,
    all_rules,
    cli_lint,
    lint_source,
    lint_tree,
    load_baseline,
    render_json,
    render_text,
    run_lint,
    write_baseline_file,
)
