"""Determinism rules: replay-critical modules must be wall-clock-,
entropy-, and set-order-free.

The chaos plane's acceptance story is bit-identical replay of a
``RoundRecord`` stream; any hidden nondeterminism in ``protocol/``,
``parallel/``, or the driver breaks it silently. Three rules:

- ``determinism-wallclock``: ``time.time()`` / ``time.time_ns()`` /
  ``datetime.now()``-family reads. ``time.perf_counter`` / ``monotonic``
  are allowed by design — they feed ``duration_s`` telemetry stamps,
  which are explicitly outside the replayed state.
- ``determinism-entropy``: ``os.urandom``, ``secrets.*``, ``uuid.uuid1/4``,
  module-level ``random.*`` / legacy ``numpy.random.*`` draws, and
  *unseeded* ``numpy.random.default_rng()`` / ``random.Random()``
  constructions. Seeded constructions are the sanctioned pattern.
- ``determinism-set-order``: iterating a ``set`` (``for``, comprehensions,
  ``list()``/``tuple()``/``enumerate()``/``iter()``/``.join()`` over a set
  display, set comprehension, or ``set()``/``frozenset()`` call). Python
  sets hash-order-randomize ``str``/``bytes`` keys across interpreter
  runs, so any set-ordered traversal is replay-hostile; ``sorted(set(...))``
  is the sanctioned spelling and is not flagged.
"""

from __future__ import annotations

import ast
from typing import Iterable

from p2pdl_tpu.analysis.engine import Finding, ModuleInfo, Rule, register

REPLAY_SCOPE = ("protocol/", "parallel/", "runtime/driver.py")

# The control tower is not replayed state, but its merged-stream digest and
# health model must be deterministic given the same event prefix — so the
# wallclock and entropy rules extend to it (operator-facing stamps carry
# inline suppressions with reasons). Set-order stays replay-scoped: the
# tower's sorted-traversal discipline is enforced by digest equality tests
# instead.
TOWER_SCOPE = REPLAY_SCOPE + ("runtime/tower.py",)

_WALLCLOCK = {"time.time", "time.time_ns"}
_DT_METHODS = {"now", "utcnow", "today"}
_ENTROPY_EXACT = {"os.urandom", "uuid.uuid4", "uuid.uuid1"}
# Module-level draw functions on `random` / `numpy.random` (shared global RNG).
_RANDOM_MODULE_FNS = {
    "random",
    "randint",
    "randrange",
    "uniform",
    "normalvariate",
    "gauss",
    "choice",
    "choices",
    "shuffle",
    "sample",
    "betavariate",
    "expovariate",
    "getrandbits",
    "random_sample",
    "rand",
    "randn",
    "permutation",
    "bytes",
    "standard_normal",
}


class WallclockRule(Rule):
    name = "determinism-wallclock"
    description = "wall-clock reads in replay-critical code"
    scope = TOWER_SCOPE

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.dotted(node.func)
            if dotted in _WALLCLOCK:
                yield mod.finding(
                    self.name,
                    node,
                    f"wall-clock read `{dotted}()` in replay-critical code; "
                    "stamp durations via time.perf_counter outside the "
                    "recorded state",
                )
            elif dotted is not None:
                parts = dotted.split(".")
                if parts[-1] in _DT_METHODS and any(
                    "datetime" in p or p == "date" for p in parts[:-1]
                ):
                    yield mod.finding(
                        self.name,
                        node,
                        f"wall-clock read `{dotted}()` in replay-critical "
                        "code; replayed state must not embed the current "
                        "date/time",
                    )


class EntropyRule(Rule):
    name = "determinism-entropy"
    description = "unseeded randomness in replay-critical code"
    scope = TOWER_SCOPE

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.dotted(node.func)
            if dotted is None:
                continue
            parts = dotted.split(".")
            if dotted in _ENTROPY_EXACT or parts[0] == "secrets":
                yield mod.finding(
                    self.name,
                    node,
                    f"OS entropy `{dotted}()` in replay-critical code; "
                    "derive randomness from the recorded seed instead",
                )
            elif (
                len(parts) == 2
                and parts[0] == "random"
                and parts[1] in _RANDOM_MODULE_FNS
            ):
                yield mod.finding(
                    self.name,
                    node,
                    f"global-RNG draw `{dotted}()` in replay-critical code; "
                    "use a seeded random.Random / numpy Generator",
                )
            elif (
                len(parts) == 3
                and parts[0] == "numpy"
                and parts[1] == "random"
                and parts[2] in _RANDOM_MODULE_FNS
            ):
                yield mod.finding(
                    self.name,
                    node,
                    f"legacy global-RNG draw `{dotted}()` in replay-critical "
                    "code; use numpy.random.default_rng(seed)",
                )
            elif dotted in ("numpy.random.default_rng", "random.Random"):
                if not node.args and not node.keywords:
                    yield mod.finding(
                        self.name,
                        node,
                        f"unseeded `{dotted}()` in replay-critical code; "
                        "pass an explicit seed",
                    )


def _is_setlike(mod: ModuleInfo, node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return mod.dotted(node.func) in ("set", "frozenset")
    return False


class SetOrderRule(Rule):
    name = "determinism-set-order"
    description = "order-dependent traversal of an unordered set"
    scope = REPLAY_SCOPE

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        advice = "; wrap in sorted(...) for a replay-stable order"
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                if _is_setlike(mod, node.iter):
                    yield mod.finding(
                        self.name,
                        node.iter,
                        "`for` loop iterates a set in hash order" + advice,
                    )
            elif isinstance(
                node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
            ):
                for gen in node.generators:
                    if _is_setlike(mod, gen.iter):
                        yield mod.finding(
                            self.name,
                            gen.iter,
                            "comprehension iterates a set in hash order" + advice,
                        )
            elif isinstance(node, ast.Call):
                dotted = mod.dotted(node.func)
                if (
                    dotted in ("list", "tuple", "enumerate", "iter")
                    and node.args
                    and _is_setlike(mod, node.args[0])
                ):
                    yield mod.finding(
                        self.name,
                        node,
                        f"`{dotted}()` materializes a set in hash order" + advice,
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"
                    and node.args
                    and _is_setlike(mod, node.args[0])
                ):
                    yield mod.finding(
                        self.name,
                        node,
                        "`.join()` consumes a set in hash order" + advice,
                    )


register(WallclockRule())
register(EntropyRule())
register(SetOrderRule())
