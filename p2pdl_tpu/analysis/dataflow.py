"""Worklist dataflow over the call graph: taint, lock-held, lock-set facts.

Three analyses share this module, all deliberately cheap and conservative:

- :class:`TaintEngine` — interprocedural taint. Each function gets a
  summary (which parameters are tainted, whether its return is tainted);
  a worklist re-analyzes a function when a caller feeds taint into a new
  parameter and re-analyzes callers when a callee's return flips tainted.
  Facts are monotone (taint only spreads) so the fixpoint terminates.
  What counts as a source / sanitizer / sink is a :class:`TaintPolicy`
  supplied by the rule (``wiretaint``) — the engine only moves facts.
- :func:`iter_lock_states` — a lexical scan yielding every expression
  node with the set of locks held around it, plus each acquisition event.
  Closures nested in a locked region are scanned as *unlocked* (they may
  run later on any thread), matching the per-file lock rule.
- :func:`always_locked` — greatest-fixpoint attribution: a function runs
  lock-held on every path iff it has at least one in-graph caller and
  every call site is either lexically under the lock or inside a function
  that itself always runs lock-held. Entry points (no in-graph callers)
  are never attributed — dynamic dispatch cannot smuggle in a lock.
- :func:`transitive_acquires` — which locks a call may take, directly or
  through callees (the lock-order cycle detector's edge source).
"""

from __future__ import annotations

import ast
import dataclasses
from collections import deque
from typing import Callable, Hashable, Iterable, Iterator, Optional

from p2pdl_tpu.analysis.callgraph import CallGraph, CallSite, FunctionNode
from p2pdl_tpu.analysis.engine import Finding, ModuleInfo

# ---- lexical lock states ----------------------------------------------------

LockId = Hashable
#: ("node", ast_node, held_lock_ids) or ("acquire", lock_id, with_node, held_before)
LockEvent = tuple


def iter_lock_states(
    stmts: list[ast.stmt],
    lock_id: Callable[[ast.AST], Optional[LockId]],
    held: frozenset = frozenset(),
    descend_closures: bool = True,
) -> Iterator[LockEvent]:
    """Walk statements in order, tracking the set of held lock identities.

    Yields ``("node", node, held)`` for every AST node of every simple
    statement (and compound-statement header expression), and
    ``("acquire", lock, with_item_expr, held_before)`` at each ``with``
    that takes a recognized lock. Closures are scanned with an empty held
    set (they may run later on any thread) — or skipped entirely with
    ``descend_closures=False`` when the caller analyzes nested functions
    as call-graph nodes of their own.
    """

    def rec(body: list[ast.stmt], inner: frozenset) -> Iterator[LockEvent]:
        return iter_lock_states(body, lock_id, inner, descend_closures)

    for st in stmts:
        if isinstance(st, (ast.With, ast.AsyncWith)):
            inner = held
            for item in st.items:
                for n in ast.walk(item.context_expr):
                    yield ("node", n, held)
                lid = lock_id(item.context_expr)
                if lid is not None:
                    yield ("acquire", lid, item.context_expr, inner)
                    inner = inner | {lid}
            yield from rec(st.body, inner)
        elif isinstance(st, (ast.If, ast.While)):
            for n in ast.walk(st.test):
                yield ("node", n, held)
            yield from rec(st.body, held)
            yield from rec(st.orelse, held)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            for n in ast.walk(st.iter):
                yield ("node", n, held)
            yield from rec(st.body, held)
            yield from rec(st.orelse, held)
        elif isinstance(st, ast.Try):
            yield from rec(st.body, held)
            for h in st.handlers:
                yield from rec(h.body, held)
            yield from rec(st.orelse, held)
            yield from rec(st.finalbody, held)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A closure defined here may run later on any thread.
            if descend_closures:
                yield from rec(st.body, frozenset())
        elif isinstance(st, ast.ClassDef):
            yield from rec(st.body, held)
        else:
            for n in ast.walk(st):
                yield ("node", n, held)


# ---- interprocedural lock attribution ---------------------------------------


def always_locked(
    graph: CallGraph, site_locked: Callable[[CallSite], bool]
) -> set[str]:
    """Function keys provably entered with the lock held on *every* path."""
    safe = {k for k in graph.functions if graph.callers_of(k)}
    changed = True
    while changed:
        changed = False
        for k in list(safe):
            ok = all(
                site_locked(s) or s.caller in safe for s in graph.callers_of(k)
            )
            if not ok:
                safe.discard(k)
                changed = True
    return safe


def transitive_acquires(
    graph: CallGraph,
    direct: dict[str, frozenset],
) -> dict[str, frozenset]:
    """Close ``direct`` (locks each function acquires in its own body)
    over call edges: what a call to each function may end up holding."""
    acq = {k: direct.get(k, frozenset()) for k in graph.functions}
    work = deque(graph.functions)
    while work:
        k = work.popleft()
        total = acq[k]
        for site in graph.callees_of(k):
            total = total | acq.get(site.callee, frozenset())
        if total != acq[k]:
            acq[k] = total
            for site in graph.callers_of(k):
                work.append(site.caller)
    return acq


# ---- interprocedural taint --------------------------------------------------


class TaintPolicy:
    """What taints, what cleans, and what must never receive taint.

    Subclasses (the rules) override the hooks; the engine stays generic.
    """

    #: Callee short names that do not receive caller taint: sanctioned
    #: trust boundaries (parsers whose *output* re-enters as fresh taint,
    #: and pre-verified handlers whose callers were already audited).
    boundaries: frozenset = frozenset()

    def in_scope(self, mod: ModuleInfo) -> bool:
        return True

    def is_source(self, mod: ModuleInfo, call: ast.Call) -> bool:
        return False

    def is_sanitizer(self, mod: ModuleInfo, call: ast.Call) -> bool:
        return False

    def check_call(
        self, mod: ModuleInfo, call: ast.Call, tainted: Callable[[ast.AST], bool]
    ) -> Iterable[Finding]:
        """Call-shaped sinks (reads, allocations, parses, mutator writes)."""
        return ()

    def check_write(
        self,
        mod: ModuleInfo,
        node: ast.AST,
        target: ast.AST,
        value_tainted: bool,
        tainted: Callable[[ast.AST], bool],
    ) -> Iterable[Finding]:
        """Assignment-shaped sinks (``self.state[...] = tainted``)."""
        return ()


@dataclasses.dataclass
class _Summary:
    tainted_params: set = dataclasses.field(default_factory=set)
    returns_tainted: bool = False
    findings: list = dataclasses.field(default_factory=list)


def _is_upper_const(e: ast.AST) -> bool:
    if isinstance(e, ast.Constant):
        return True
    if isinstance(e, ast.Name) and e.id.isupper():
        return True
    if isinstance(e, ast.Attribute) and e.attr.isupper():
        return True
    return False


class TaintEngine:
    """Fixpoint driver + per-function abstract interpreter."""

    _MAX_POPS = 20000  # termination backstop; never reached in practice

    def __init__(
        self, mods: list[ModuleInfo], graph: CallGraph, policy: TaintPolicy
    ) -> None:
        self.graph = graph
        self.policy = policy
        self.scope_keys = [
            fn.key
            for fn in graph.functions.values()
            if policy.in_scope(fn.mod)
        ]
        self.summaries: dict[str, _Summary] = {
            k: _Summary() for k in graph.functions
        }
        self._work: deque[str] = deque()
        self._queued: set[str] = set()

    def run(self) -> list[Finding]:
        for k in self.scope_keys:
            self._enqueue(k)
        pops = 0
        while self._work and pops < self._MAX_POPS:
            key = self._work.popleft()
            self._queued.discard(key)
            pops += 1
            self._analyze(key)
        findings: list[Finding] = []
        for k in self.scope_keys:
            findings.extend(self.summaries[k].findings)
        return findings

    # -- worklist plumbing -------------------------------------------------

    def _enqueue(self, key: str) -> None:
        if key not in self._queued and key in self.summaries:
            self._queued.add(key)
            self._work.append(key)

    def add_param_taint(self, callee_key: str, params: set) -> None:
        summ = self.summaries.get(callee_key)
        if summ is None or params <= summ.tainted_params:
            return
        summ.tainted_params |= params
        fn = self.graph.functions.get(callee_key)
        if fn is not None and self.policy.in_scope(fn.mod):
            self._enqueue(callee_key)

    def returns_tainted(self, callee_key: str) -> bool:
        summ = self.summaries.get(callee_key)
        return bool(summ and summ.returns_tainted)

    def _analyze(self, key: str) -> None:
        fn = self.graph.functions[key]
        summ = self.summaries[key]
        scan = _FunctionScan(self, fn)
        scan.run()
        summ.findings = scan.findings
        if scan.returns_tainted and not summ.returns_tainted:
            summ.returns_tainted = True  # monotone: never un-taints
            for site in self.graph.callers_of(key):
                self._enqueue(site.caller)


class _FunctionScan:
    """One in-order abstract pass over a function body.

    Variable-level taint only (object attributes are not tracked as
    separate cells — a tainted object taints every expression built from
    it). Branches are not joined: taint accumulates, and only assignment
    of a clean value or a sanitizer call removes it. Both choices bias
    toward flagging, then sanitizers pull the false-positive rate down.
    """

    def __init__(self, engine: TaintEngine, fn: FunctionNode) -> None:
        self.engine = engine
        self.policy = engine.policy
        self.fn = fn
        self.mod = fn.mod
        self.tainted: set[str] = set(
            engine.summaries[fn.key].tainted_params
        )
        self.returns_tainted = False
        self.findings: list[Finding] = []
        self._checked_calls: set[int] = set()

    def run(self) -> None:
        self._visit_stmts(self.fn.node.body)

    # -- expressions -------------------------------------------------------

    def _tainted(self, e: Optional[ast.AST]) -> bool:
        if e is None:
            return False
        if isinstance(e, ast.Name):
            return e.id in self.tainted
        if isinstance(e, ast.Call):
            return self._call(e)
        if isinstance(e, ast.Lambda):
            return False
        return any(self._tainted(c) for c in ast.iter_child_nodes(e))

    def _call(self, call: ast.Call) -> bool:
        mod = self.mod
        if self.policy.is_sanitizer(mod, call):
            self._sanitize_names(call)
            return False
        arg_tainted = [self._tainted(a) for a in call.args]
        kw_tainted = {
            kw.arg: self._tainted(kw.value)
            for kw in call.keywords
            if kw.arg is not None
        }
        recv_tainted = (
            self._tainted(call.func.value)
            if isinstance(call.func, ast.Attribute)
            else False
        )
        if id(call) not in self._checked_calls:
            self._checked_calls.add(id(call))
            self.findings.extend(
                self.policy.check_call(mod, call, self._tainted)
            )
        if self.policy.is_source(mod, call):
            return True
        callee_key = self.engine.graph.resolved_calls.get(id(call))
        if callee_key is not None:
            callee = self.engine.graph.functions[callee_key]
            if callee.short_name not in self.policy.boundaries:
                params = callee.param_names()
                flow = {
                    params[i]
                    for i, t in enumerate(arg_tainted)
                    if t and i < len(params)
                }
                flow |= {k for k, t in kw_tainted.items() if t and k in params}
                if flow:
                    self.engine.add_param_taint(callee_key, flow)
            return self.engine.returns_tainted(callee_key)
        # Unresolved call: taint flows through (bytes(x), x.decode(), ...).
        return any(arg_tainted) or any(kw_tainted.values()) or recv_tainted

    def _sanitize_names(self, call: ast.Call) -> None:
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for n in ast.walk(arg):
                if isinstance(n, ast.Name):
                    self.tainted.discard(n.id)

    def _apply_bound_checks(self, test: ast.AST) -> None:
        """Explicit shape validation sanitizes: comparing a tainted value
        (or its ``len()``) against a constant / ALL-CAPS bound means the
        code inspected the attacker-controlled quantity."""
        for cmp_node in ast.walk(test):
            if not isinstance(cmp_node, ast.Compare):
                continue
            sides = [cmp_node.left] + list(cmp_node.comparators)
            if not any(_is_upper_const(s) for s in sides):
                continue
            for side in sides:
                if isinstance(side, ast.Call) and isinstance(
                    side.func, ast.Name
                ) and side.func.id == "len":
                    for a in side.args:
                        for n in ast.walk(a):
                            if isinstance(n, ast.Name):
                                self.tainted.discard(n.id)
                elif isinstance(side, ast.Name):
                    self.tainted.discard(side.id)

    # -- statements --------------------------------------------------------

    def _assign_target(self, t: ast.AST, value_tainted: bool, node: ast.AST) -> None:
        if isinstance(t, ast.Name):
            if value_tainted:
                self.tainted.add(t.id)
            else:
                self.tainted.discard(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for elt in t.elts:
                inner = elt.value if isinstance(elt, ast.Starred) else elt
                self._assign_target(inner, value_tainted, node)
        elif isinstance(t, (ast.Attribute, ast.Subscript)):
            if isinstance(t, ast.Subscript):
                self._tainted(t.slice)
            self.findings.extend(
                self.policy.check_write(
                    self.mod, node, t, value_tainted, self._tainted
                )
            )

    def _visit_stmts(self, stmts: list[ast.stmt]) -> None:
        for st in stmts:
            if isinstance(st, ast.Assign):
                vt = self._tainted(st.value)
                for t in st.targets:
                    self._assign_target(t, vt, st)
            elif isinstance(st, ast.AnnAssign):
                vt = self._tainted(st.value) if st.value is not None else False
                self._assign_target(st.target, vt, st)
            elif isinstance(st, ast.AugAssign):
                vt = self._tainted(st.value)
                if isinstance(st.target, ast.Name):
                    if vt:
                        self.tainted.add(st.target.id)
                else:
                    self._assign_target(
                        st.target,
                        vt or self._tainted(st.target),
                        st,
                    )
            elif isinstance(st, ast.Expr):
                self._tainted(st.value)
            elif isinstance(st, ast.Return):
                if self._tainted(st.value):
                    self.returns_tainted = True
            elif isinstance(st, (ast.If, ast.While)):
                self._tainted(st.test)
                self._apply_bound_checks(st.test)
                self._visit_stmts(st.body)
                self._visit_stmts(st.orelse)
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                it = self._tainted(st.iter)
                self._assign_target(st.target, it, st)
                self._visit_stmts(st.body)
                self._visit_stmts(st.orelse)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    ct = self._tainted(item.context_expr)
                    if item.optional_vars is not None:
                        self._assign_target(item.optional_vars, ct, st)
                self._visit_stmts(st.body)
            elif isinstance(st, ast.Try):
                self._visit_stmts(st.body)
                for h in st.handlers:
                    self._visit_stmts(h.body)
                self._visit_stmts(st.orelse)
                self._visit_stmts(st.finalbody)
            elif isinstance(st, (ast.Raise, ast.Assert)):
                for child in ast.iter_child_nodes(st):
                    self._tainted(child)
            elif isinstance(st, ast.Delete):
                for t in st.targets:
                    if isinstance(t, ast.Name):
                        self.tainted.discard(t.id)
            # Nested defs are separate call-graph nodes; class bodies,
            # imports, and control keywords carry no taint.
