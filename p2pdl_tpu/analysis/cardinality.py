"""Telemetry-cardinality rules: metric labels must stay low-cardinality.

The registry caps each metric at ``DEFAULT_MAX_SERIES_PER_METRIC`` series
and folds the overflow into ``__other__`` — so a per-peer-id, per-digest,
or per-round label doesn't crash anything, it silently *destroys the
metric*: past the cap every new identity lands in one aggregate bucket
and the dashboard lies. Two rules over ``protocol/``, ``parallel/``, and
``runtime/``:

- ``telemetry-cardinality``: a ``telemetry.counter/gauge/histogram`` call
  whose label keyword is identity-named (``peer``, ``sender``, ``digest``,
  ``round``, ...) with a non-constant value. A constant (``peer="all"``)
  is a fixed partition, fine; a variable (``peer=pid``) mints one series
  per identity. Deliberate bounded cases (e.g. O(num_peers) series for a
  per-peer failure panel) carry an inline
  ``# p2plint: disable=telemetry-cardinality -- reason`` suppression.
- ``telemetry-label-splat``: ``**kwargs`` splatted into the label set —
  the label keys themselves become data-dependent, which no reviewer can
  bound by reading the call site.

The ``bounds`` keyword of ``histogram`` is the bucket config, not a
label, and is never flagged.
"""

from __future__ import annotations

import ast
from typing import Iterable

from p2pdl_tpu.analysis.engine import Finding, ModuleInfo, Rule, register

# "runtime/" deliberately covers runtime/tower.py too: the control tower's
# tower.* series face the same cardinality discipline as the planes it
# watches (its per-stream accounting is aggregated, never label-per-stream).
METRIC_SCOPE = ("protocol/", "parallel/", "runtime/")

# Metric factory call targets: module-level helpers and registry methods.
_METRIC_FNS = ("counter", "gauge", "histogram")

# Label names that name an identity or an unbounded sequence: one series
# per peer/digest/round is exactly the cardinality explosion the registry
# cap exists to contain.
_IDENTITY_LABELS = {
    "peer",
    "peer_id",
    "trainer",
    "sender",
    "src",
    "dst",
    "node",
    "node_id",
    "id",
    "digest",
    "hash",
    "addr",
    "host",
    "port",
    "seq",
    "round",
    "round_idx",
    "step",
}

# Keywords that are factory config, not labels.
_NON_LABEL_KWARGS = {"bounds"}


def _is_metric_call(mod: ModuleInfo, node: ast.Call) -> str | None:
    """Return the factory name (``counter``/...) when ``node`` constructs a
    telemetry series, else None. Matches ``telemetry.counter(...)``,
    ``MetricsRegistry``-style ``<obj>.counter(...)``, and a bare
    ``counter(...)`` imported from the telemetry module."""
    dotted = mod.dotted(node.func)
    if dotted is not None:
        parts = dotted.split(".")
        if parts[-1] in _METRIC_FNS and (
            len(parts) == 1 or "telemetry" in parts[0].lower() or "registry" in parts[0].lower()
        ):
            return parts[-1]
    if isinstance(node.func, ast.Attribute) and node.func.attr in _METRIC_FNS:
        # Method call on an unresolvable receiver (e.g. ``self._registry``):
        # still a metric factory by naming convention.
        return node.func.attr
    return None


def _is_constant_label(value: ast.AST) -> bool:
    return isinstance(value, ast.Constant)


class CardinalityRule(Rule):
    name = "telemetry-cardinality"
    description = "identity-valued metric label mints unbounded series"
    scope = METRIC_SCOPE

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _is_metric_call(mod, node)
            if fn is None:
                continue
            for kw in node.keywords:
                if kw.arg is None or kw.arg in _NON_LABEL_KWARGS:
                    continue
                if kw.arg in _IDENTITY_LABELS and not _is_constant_label(kw.value):
                    yield mod.finding(
                        self.name,
                        node,
                        f"`{fn}(...)` labels by identity `{kw.arg}=<expr>`: "
                        "one series per value, folded to `__other__` past "
                        "the registry cap; aggregate instead, or suppress "
                        "with a bounded-cardinality justification",
                    )


class LabelSplatRule(Rule):
    name = "telemetry-label-splat"
    description = "**kwargs splat into a metric label set"
    scope = METRIC_SCOPE

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _is_metric_call(mod, node)
            if fn is None:
                continue
            for kw in node.keywords:
                if kw.arg is None:
                    yield mod.finding(
                        self.name,
                        node,
                        f"`{fn}(...)` splats `**` into its label set: the "
                        "label keys become data-dependent and unbounded; "
                        "spell each label explicitly",
                    )


register(CardinalityRule())
register(LabelSplatRule())
