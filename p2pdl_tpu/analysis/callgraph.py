"""Conservative intra-package call graph for interprocedural rules.

The graph resolves only what a lexical reading of the tree can prove:

- bare calls to same-module functions (``helper(x)``),
- ``self.method()`` calls within the defining class,
- class-qualified calls (``Broadcaster.handle(b, m)``, ``Cls()`` to
  ``Cls.__init__``),
- module-qualified and ``from``-imported calls through the engine's
  import-alias map (``transport.recv_frame`` / ``recv_frame`` after
  ``from ..protocol.transport import recv_frame``).

Anything dynamic — callables stored in attributes or registries
(``self.handler(...)``), duck-typed method calls on arbitrary objects,
inheritance dispatch — produces *no* edge. Rules built on top must treat
an unresolved call conservatively (taint flows through its return;
lock-held attribution only trusts resolved paths), and the README
documents the soundness limit.

Function keys are ``"<relpath>::<qualname>"`` so the graph spans modules
without name collisions.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional, Union

from p2pdl_tpu.analysis.engine import ModuleInfo

FunctionDefT = Union[ast.FunctionDef, ast.AsyncFunctionDef]

#: Dotted-module prefix fixture trees lack but in-repo imports carry.
_PACKAGE = "p2pdl_tpu"


@dataclasses.dataclass
class FunctionNode:
    """One function or method definition."""

    key: str
    relpath: str  # ModuleInfo.relpath of the defining module
    qualname: str  # "Cls.method", "func", or "outer.inner"
    cls: Optional[str]  # enclosing class qualname for methods, else None
    node: FunctionDefT
    mod: ModuleInfo

    @property
    def short_name(self) -> str:
        return self.node.name

    @property
    def is_async(self) -> bool:
        return isinstance(self.node, ast.AsyncFunctionDef)

    def param_names(self, skip_self: bool = True) -> list[str]:
        a = self.node.args
        names = [p.arg for p in a.posonlyargs + a.args]
        if skip_self and self.cls is not None and names and names[0] in ("self", "cls"):
            names = names[1:]
        return names


@dataclasses.dataclass
class CallSite:
    """One resolved call edge: ``caller`` invokes ``callee`` at ``call``."""

    caller: str  # FunctionNode key
    callee: str  # FunctionNode key
    call: ast.Call
    relpath: str  # module containing the call site


class CallGraph:
    def __init__(self) -> None:
        self.functions: dict[str, FunctionNode] = {}
        self._by_caller: dict[str, list[CallSite]] = {}
        self._by_callee: dict[str, list[CallSite]] = {}
        #: id(ast.Call) -> callee key, for rules walking function bodies.
        self.resolved_calls: dict[int, str] = {}

    def add_edge(self, site: CallSite) -> None:
        self._by_caller.setdefault(site.caller, []).append(site)
        self._by_callee.setdefault(site.callee, []).append(site)
        self.resolved_calls[id(site.call)] = site.callee

    def callees_of(self, key: str) -> list[CallSite]:
        return self._by_caller.get(key, [])

    def callers_of(self, key: str) -> list[CallSite]:
        return self._by_callee.get(key, [])

    def methods_of(self, relpath: str, cls_qual: str) -> list[FunctionNode]:
        return [
            fn
            for fn in self.functions.values()
            if fn.relpath == relpath and fn.cls == cls_qual
        ]


def _module_dotted(mod: ModuleInfo) -> str:
    """``protocol/transport.py`` -> ``p2pdl_tpu.protocol.transport``."""
    p = mod.norm_relpath
    if p.endswith(".py"):
        p = p[: -len(".py")]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    dotted = p.replace("/", ".")
    return f"{_PACKAGE}.{dotted}" if dotted else _PACKAGE


class _ModuleIndex:
    """Per-module definition tables used during resolution."""

    def __init__(self, mod: ModuleInfo) -> None:
        self.mod = mod
        self.functions: dict[str, FunctionNode] = {}  # top-level name -> node
        self.methods: dict[tuple[str, str], FunctionNode] = {}  # (cls, name)
        self.classes: set[str] = set()


def _collect_definitions(
    mods: list[ModuleInfo], graph: CallGraph
) -> dict[str, _ModuleIndex]:
    indexes: dict[str, _ModuleIndex] = {}
    for mod in mods:
        idx = _ModuleIndex(mod)
        indexes[mod.relpath] = idx
        # Class methods: functions whose *direct* parent is a ClassDef.
        # NB: ``context_of`` on a def/class node is its *own* qualname.
        method_nodes: set[int] = set()
        for node in mod.walk():
            if not isinstance(node, ast.ClassDef):
                continue
            cls_qual = mod.context_of(node)
            idx.classes.add(cls_qual)
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = mod.context_of(item)
                    fn = FunctionNode(
                        key=f"{mod.relpath}::{qual}",
                        relpath=mod.relpath,
                        qualname=qual,
                        cls=cls_qual,
                        node=item,
                        mod=mod,
                    )
                    graph.functions[fn.key] = fn
                    idx.methods[(cls_qual, item.name)] = fn
                    method_nodes.add(id(item))
        # Plain functions (top-level and nested, but not methods).
        for node in mod.walk():
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if id(node) in method_nodes:
                continue
            qual = mod.context_of(node)
            fn = FunctionNode(
                key=f"{mod.relpath}::{qual}",
                relpath=mod.relpath,
                qualname=qual,
                cls=None,
                node=node,
                mod=mod,
            )
            graph.functions[fn.key] = fn
            if qual == node.name:  # top-level function
                idx.functions[node.name] = fn
    return indexes


def _resolve_dotted(
    dotted: str,
    idx: _ModuleIndex,
    by_module: dict[str, _ModuleIndex],
) -> Optional[FunctionNode]:
    """Resolve a canonical dotted chain to a definition.

    Tries, in order: same-module function, same-module ``Cls.method``,
    then the longest dotted-module prefix registered in ``by_module``
    with the remainder as ``func`` or ``Cls.method``.
    """
    parts = dotted.split(".")
    if len(parts) == 1:
        fn = idx.functions.get(parts[0])
        if fn is not None:
            return fn
        # Bare class name: constructor edge to Cls.__init__.
        if parts[0] in idx.classes:
            return idx.methods.get((parts[0], "__init__"))
        return None
    if len(parts) == 2 and parts[0] in idx.classes:
        return idx.methods.get((parts[0], parts[1]))
    for cut in range(len(parts) - 1, 0, -1):
        target = by_module.get(".".join(parts[:cut]))
        if target is None:
            continue
        rest = parts[cut:]
        if len(rest) == 1:
            fn = target.functions.get(rest[0])
            if fn is not None:
                return fn
            if rest[0] in target.classes:
                return target.methods.get((rest[0], "__init__"))
        elif len(rest) == 2 and rest[0] in target.classes:
            return target.methods.get((rest[0], rest[1]))
        return None
    return None


def build_callgraph(mods: list[ModuleInfo]) -> CallGraph:
    graph = CallGraph()
    indexes = _collect_definitions(mods, graph)
    by_module: dict[str, _ModuleIndex] = {}
    for idx in indexes.values():
        dotted = _module_dotted(idx.mod)
        by_module[dotted] = idx
        # Fixture trees import without the package prefix; register both.
        if dotted.startswith(_PACKAGE + "."):
            by_module.setdefault(dotted[len(_PACKAGE) + 1 :], idx)

    # Caller attribution: enclosing-context qualname -> FunctionNode.
    for mod in mods:
        idx = indexes[mod.relpath]
        quals = {
            fn.qualname: fn
            for fn in graph.functions.values()
            if fn.relpath == mod.relpath
        }
        for node in mod.walk():
            if not isinstance(node, ast.Call):
                continue
            caller = quals.get(mod.context_of(node))
            if caller is None:
                continue  # module-level call (import time): not tracked
            callee = _resolve_call(node, caller, idx, by_module)
            if callee is not None:
                graph.add_edge(
                    CallSite(
                        caller=caller.key,
                        callee=callee.key,
                        call=node,
                        relpath=mod.relpath,
                    )
                )
    return graph


def _resolve_call(
    call: ast.Call,
    caller: FunctionNode,
    idx: _ModuleIndex,
    by_module: dict[str, _ModuleIndex],
) -> Optional[FunctionNode]:
    func = call.func
    # self.method() within the defining class (single-class, no MRO walk).
    if (
        isinstance(func, ast.Attribute)
        and isinstance(func.value, ast.Name)
        and func.value.id == "self"
        and caller.cls is not None
    ):
        return idx.methods.get((caller.cls, func.attr))
    dotted = idx.mod.dotted(func)
    if dotted is None:
        return None
    return _resolve_dotted(dotted, idx, by_module)
