"""Async concurrency rules: event-loop blocking, hybrid locks, lifecycle.

The asyncio TCP plane (``protocol/aio_transport.py``) runs one event loop
on a dedicated thread while the trainer threads talk to it through
thread-safe entry points. That split creates three whole-program
invariants no per-file rule can see:

1. **Nothing reachable from the loop may block.** :class:`AsyncModel`
   colors every call-graph function with a "runs on the event loop"
   context — seeded from ``async def`` bodies and from sync callbacks
   handed to ``call_soon`` / ``call_soon_threadsafe`` / ``call_later`` /
   ``call_at`` / ``add_done_callback`` — and propagates it through
   resolved call edges, keeping the witness chain for the report.
   ``async-blocking-call`` then flags blocking sinks (``time.sleep``,
   sync ``socket.*`` / ``subprocess.*``, file I/O, ``Future.result()``,
   blocking ``queue.Queue`` methods, ``Condition.wait``) in any colored
   function. A ``threading.Lock`` acquisition on the loop is flagged only
   when the lock is *slow* — held across an ``await`` or a blocking sink
   somewhere in the program — so the transport's short stats-guarding
   critical sections stay clean while a genuinely stall-prone lock is
   caught at every loop-side acquisition.

2. **The loop must never suspend while holding a thread lock.**
   ``async-lock-stall`` flags ``await`` / ``async with`` / ``async for``
   with a ``threading.Lock`` identity held: the coroutine parks with the
   lock taken and every thread (and every coroutine that needs the lock)
   stalls behind a suspension of unbounded length. The lock identities
   are the hybrid :mod:`lockflow` model's — ``asyncio.Lock`` /
   ``Condition`` get program-unique identities through the same factories
   table, so lock-order cycle detection spans the thread↔loop boundary.

3. **Coroutine objects and loop-owned state have an ownership
   discipline.** ``async-coroutine-drop`` flags a resolved call to an
   ``async def`` used as an expression statement (the coroutine is built
   and discarded, its body never runs) and a ``create_task`` /
   ``ensure_future`` / ``run_coroutine_threadsafe`` result that is
   dropped (task exceptions vanish with the last reference).
   ``async-loop-state`` flags an attribute written both by loop-colored
   and by thread-side methods of one class with no common ``threading``
   lock guarding every site (lexically or via call-graph attribution) —
   the fix is routing the thread-side mutation through
   ``call_soon_threadsafe`` / ``run_coroutine_threadsafe`` or guarding
   both sides.

Soundness limits mirror the call graph's: dynamic dispatch produces no
edge, so a handler invoked through a stored callable is not colored and
its body is not checked; coloring one level of ``functools.partial`` or
closures handed to the loop is out of scope. The model is conservative
the other way too: a helper called from both worlds is colored and must
be loop-safe.
"""

from __future__ import annotations

import ast
from collections import deque
from typing import Iterable, Optional

from p2pdl_tpu.analysis.callgraph import FunctionNode
from p2pdl_tpu.analysis.engine import (
    Finding,
    Program,
    ProgramRule,
    register,
)
from p2pdl_tpu.analysis.lockflow import LockModel, lock_model_for, own_nodes
from p2pdl_tpu.analysis.locks import _self_attr

#: Loop APIs whose result must be retained (silent-exception sink).
_SPAWNERS = frozenset({"create_task", "ensure_future", "run_coroutine_threadsafe"})
#: Loop APIs taking a sync callback: method name -> callback arg index.
_CALLBACK_ARG = {
    "call_soon": 0,
    "call_soon_threadsafe": 0,
    "add_done_callback": 0,
    "call_later": 1,
    "call_at": 1,
}
#: Canonical dotted names that block the calling thread outright.
_BLOCKING_DOTTED = frozenset(
    {
        "time.sleep",
        "open",
        "io.open",
        "os.read",
        "os.write",
        "os.popen",
        "select.select",
    }
)
#: Any module-level call into these modules is synchronous I/O.
_BLOCKING_MODULES = frozenset({"subprocess", "socket"})
#: Stdlib thread-queue factories (Queue.get/put block by default).
_QUEUE_FACTORIES = frozenset(
    {"queue.Queue", "queue.LifoQueue", "queue.PriorityQueue", "queue.SimpleQueue"}
)
_QUEUE_BLOCKING_METHODS = frozenset({"get", "put", "join"})

_AMBIGUOUS = ("<ambiguous>",)


def _is_thread_lock(model: LockModel, lid: tuple) -> bool:
    factory = model.lock_factory(lid)
    return factory is not None and factory.startswith("threading.")


def _call_nonblocking(call: ast.Call) -> bool:
    """``q.get(False)`` / ``q.get(block=False)`` do not block."""
    if call.args and isinstance(call.args[0], ast.Constant):
        if call.args[0].value is False:
            return True
    for kw in call.keywords:
        if (
            kw.arg == "block"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is False
        ):
            return True
    return False


class AsyncModel:
    """Loop-context coloring + slow-lock facts, shared by the async rules."""

    def __init__(self, program: Program) -> None:
        self.program = program
        self.graph = program.callgraph
        self.locks = lock_model_for(program)
        #: fn key -> witness chain of fn keys from a loop root to it.
        self.loop_ctx: dict[str, tuple[str, ...]] = {}
        #: loop-root fn key -> how it enters the loop (for the report).
        self.root_kind: dict[str, str] = {}
        #: thread-lock id -> why it can stall its holder ("slow" locks).
        self.slow_locks: dict[tuple, str] = {}
        #: (relpath, cls_qual) -> queue attr names; mirrors the lock model.
        self._queue_class_attrs: dict[tuple[str, str], set[str]] = {}
        self._queue_attr_owner: dict[str, tuple] = {}
        self._queue_globals: dict[str, set[str]] = {}
        self._collect_queues()
        self._color()
        self._find_slow_locks()

    # -- queue ownership (same shape as LockModel's lock ownership) --------

    def _collect_queues(self) -> None:
        for mod in self.program.mods:
            for node in mod.walk():
                if not isinstance(node, ast.ClassDef):
                    continue
                attrs: set[str] = set()
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) and isinstance(
                        sub.value, ast.Call
                    ):
                        if mod.dotted(sub.value.func) in _QUEUE_FACTORIES:
                            for t in sub.targets:
                                attr = _self_attr(t)
                                if attr is not None:
                                    attrs.add(attr)
                if attrs:
                    key = (mod.relpath, mod.context_of(node))
                    self._queue_class_attrs[key] = attrs
                    for attr in attrs:
                        if attr in self._queue_attr_owner:
                            self._queue_attr_owner[attr] = _AMBIGUOUS
                        else:
                            self._queue_attr_owner[attr] = key
            globs = {
                t.id
                for st in mod.tree.body
                if isinstance(st, ast.Assign) and isinstance(st.value, ast.Call)
                if mod.dotted(st.value.func) in _QUEUE_FACTORIES
                for t in st.targets
                if isinstance(t, ast.Name)
            }
            if globs:
                self._queue_globals[mod.relpath] = globs

    def queue_display(self, fn: FunctionNode, expr: ast.AST) -> Optional[str]:
        """Display name of a known thread-queue receiver, else None."""
        attr = _self_attr(expr)
        if attr is not None:
            if fn.cls is not None and attr in self._queue_class_attrs.get(
                (fn.relpath, fn.cls), set()
            ):
                return f"self.{attr}"
            return None
        if isinstance(expr, ast.Name):
            if expr.id in self._queue_globals.get(fn.relpath, set()):
                return expr.id
            return None
        if isinstance(expr, ast.Attribute):
            owner = self._queue_attr_owner.get(expr.attr)
            if owner is not None and owner != _AMBIGUOUS:
                return f".{expr.attr}"
        return None

    # -- loop-context coloring ---------------------------------------------

    def _resolve_ref(self, fn: FunctionNode, expr: ast.AST) -> Optional[FunctionNode]:
        """A bare function reference (``self._wake`` / ``helper``) handed
        to a loop API, resolved with the call graph's conservatism."""
        attr = _self_attr(expr)
        if attr is not None and fn.cls is not None:
            return self.graph.functions.get(f"{fn.relpath}::{fn.cls}.{attr}")
        if isinstance(expr, ast.Name):
            for qual in (f"{fn.qualname}.{expr.id}", expr.id):
                target = self.graph.functions.get(f"{fn.relpath}::{qual}")
                if target is not None:
                    return target
        return None

    def _color(self) -> None:
        roots: list[tuple[str, str]] = [
            (key, "an `async def`")
            for key, fn in self.graph.functions.items()
            if fn.is_async
        ]
        for key, fn in self.graph.functions.items():
            for node in own_nodes(fn):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                ):
                    continue
                idx = _CALLBACK_ARG.get(node.func.attr)
                if idx is None or len(node.args) <= idx:
                    continue
                target = self._resolve_ref(fn, node.args[idx])
                if target is not None and not target.is_async:
                    roots.append(
                        (
                            target.key,
                            "a loop callback registered in "
                            f"`{fn.qualname}` via `{node.func.attr}`",
                        )
                    )
        work: deque[str] = deque()
        for key, kind in roots:
            if key in self.loop_ctx:
                continue
            self.loop_ctx[key] = (key,)
            self.root_kind[key] = kind
            work.append(key)
        while work:
            k = work.popleft()
            for site in self.graph.callees_of(k):
                if site.callee in self.loop_ctx:
                    continue
                self.loop_ctx[site.callee] = self.loop_ctx[k] + (site.callee,)
                work.append(site.callee)

    def chain_display(self, key: str) -> str:
        chain = self.loop_ctx[key]
        quals = [self.graph.functions[k].qualname for k in chain]
        head = f"`{quals[0]}`, {self.root_kind.get(chain[0], 'an `async def`')}"
        if len(quals) == 1:
            return head
        return head + ", via " + " -> ".join(f"`{q}`" for q in quals[1:])

    # -- blocking-sink classification --------------------------------------

    def blocking_call(self, fn: FunctionNode, call: ast.Call) -> Optional[str]:
        """Description of a call that blocks its thread, else None.

        Thread-lock ``.acquire()`` is *not* classified here — the blocking
        rule applies the slow-lock refinement to acquisitions itself.
        """
        dotted = fn.mod.dotted(call.func)
        if dotted is not None:
            if dotted in _BLOCKING_DOTTED:
                return f"{dotted}()"
            parts = dotted.split(".")
            if len(parts) >= 2 and parts[0] in _BLOCKING_MODULES:
                return f"{dotted}()"
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            if attr == "result":
                return ".result()"
            if attr in _QUEUE_BLOCKING_METHODS and not _call_nonblocking(call):
                q = self.queue_display(fn, call.func.value)
                if q is not None:
                    return f"{q}.{attr}()"
            if attr in ("wait", "wait_for"):
                lid = self.locks.lock_id(fn, call.func.value)
                if lid is not None and _is_thread_lock(self.locks, lid):
                    return f"{self.locks.display(lid)}.{attr}()"
        return None

    # -- slow threading locks ----------------------------------------------

    def _mark_slow(self, held: Iterable[tuple], reason: str) -> None:
        for lid in sorted(held):
            if _is_thread_lock(self.locks, lid) and lid not in self.slow_locks:
                self.slow_locks[lid] = reason

    def _find_slow_locks(self) -> None:
        lm = self.locks
        #: fn key -> why its own body can block/suspend (first reason wins).
        own_block: dict[str, str] = {}
        for key, fn in self.graph.functions.items():
            for node in own_nodes(fn):
                if isinstance(node, ast.Await):
                    reason = f"an `await` in `{fn.qualname}`"
                    self._mark_slow(lm.held_at(key, node), reason)
                    own_block.setdefault(key, reason)
                elif isinstance(node, ast.AsyncWith):
                    anchor = node.items[0].context_expr
                    reason = f"an `async with` suspension in `{fn.qualname}`"
                    self._mark_slow(lm.held_at(key, anchor), reason)
                    own_block.setdefault(key, reason)
                elif isinstance(node, ast.AsyncFor):
                    reason = f"an `async for` suspension in `{fn.qualname}`"
                    self._mark_slow(lm.held_at(key, node.iter), reason)
                    own_block.setdefault(key, reason)
                elif isinstance(node, ast.Call):
                    desc = self.blocking_call(fn, node)
                    if desc is None:
                        continue
                    held = set(lm.held_at(key, node))
                    # Condition.wait releases its own lock while parked.
                    if isinstance(node.func, ast.Attribute) and node.func.attr in (
                        "wait",
                        "wait_for",
                    ):
                        held.discard(lm.lock_id(fn, node.func.value))
                    reason = f"`{desc}` in `{fn.qualname}`"
                    self._mark_slow(held, reason)
                    own_block.setdefault(key, reason)
        # A lock held across a *call* whose callee (transitively) blocks is
        # just as slow as one held across the sink itself.
        may_block = dict(own_block)
        changed = True
        while changed:
            changed = False
            for key in self.graph.functions:
                if key in may_block:
                    continue
                for site in self.graph.callees_of(key):
                    reason = may_block.get(site.callee)
                    if reason is not None:
                        may_block[key] = reason
                        changed = True
                        break
        for key, fn in self.graph.functions.items():
            for site in self.graph.callees_of(key):
                reason = may_block.get(site.callee)
                if reason is None:
                    continue
                held = lm.held_at(key, site.call)
                if held:
                    callee = self.graph.functions[site.callee]
                    self._mark_slow(
                        held,
                        f"a call to `{callee.qualname}` (which reaches "
                        f"{reason})",
                    )


def async_model_for(program: Program) -> AsyncModel:
    model = getattr(program, "_async_model", None)
    if model is None:
        model = AsyncModel(program)
        program._async_model = model
    return model


# ---- async-blocking-call ------------------------------------------------------


class EventLoopBlockingRule(ProgramRule):
    name = "async-blocking-call"
    description = (
        "blocking sink reachable from event-loop context "
        "(stalls every coroutine on the loop)"
    )
    scope = None  # everywhere

    def check_program(self, program: Program) -> Iterable[Finding]:
        model = async_model_for(program)
        lm = model.locks
        for key in model.loop_ctx:
            fn = model.graph.functions[key]
            if not self.applies(fn.mod):
                continue
            chain = model.chain_display(key)
            for node in own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                desc = model.blocking_call(fn, node)
                if desc is not None:
                    yield fn.mod.finding(
                        self.name,
                        node,
                        f"blocking call `{desc}` runs on the event loop "
                        f"(reached from {chain}) — every coroutine on the "
                        "loop stalls behind it; use the async equivalent or "
                        "offload via `run_in_executor`",
                    )
                    continue
                # Explicit lock.acquire(): slow-lock refinement.
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"
                ):
                    lid = lm.lock_id(fn, node.func.value)
                    if (
                        lid is not None
                        and _is_thread_lock(lm, lid)
                        and lid in model.slow_locks
                    ):
                        yield self._slow_lock_finding(
                            fn, node, lm, model, lid, chain
                        )
            for lid, expr, _held_before in lm.acquires.get(key, ()):
                if _is_thread_lock(lm, lid) and lid in model.slow_locks:
                    yield self._slow_lock_finding(fn, expr, lm, model, lid, chain)

    def _slow_lock_finding(self, fn, node, lm, model, lid, chain) -> Finding:
        return fn.mod.finding(
            self.name,
            node,
            f"threading lock `{lm.display(lid)}` is taken on the event loop "
            f"(reached from {chain}) but is held across "
            f"{model.slow_locks[lid]} — a stalled holder freezes the loop",
        )


# ---- async-lock-stall ---------------------------------------------------------


class AwaitUnderThreadLockRule(ProgramRule):
    name = "async-lock-stall"
    description = (
        "coroutine suspends (`await` / `async with` / `async for`) while a "
        "threading lock is held"
    )
    scope = None  # everywhere

    def check_program(self, program: Program) -> Iterable[Finding]:
        lm = lock_model_for(program)
        for key, fn in lm.graph.functions.items():
            if not self.applies(fn.mod):
                continue
            reported: set[tuple] = set()
            for node in own_nodes(fn):
                if isinstance(node, ast.Await):
                    anchor, label = node, "`await`"
                elif isinstance(node, ast.AsyncWith):
                    anchor, label = node.items[0].context_expr, "`async with`"
                elif isinstance(node, ast.AsyncFor):
                    anchor, label = node.iter, "`async for`"
                else:
                    continue
                for lid in sorted(lm.held_at(key, anchor)):
                    if not _is_thread_lock(lm, lid) or lid in reported:
                        continue
                    reported.add(lid)
                    yield fn.mod.finding(
                        self.name,
                        node,
                        f"{label} in `{fn.qualname}` while threading lock "
                        f"`{lm.display(lid)}` is held — the coroutine parks "
                        "with the lock taken, stalling every thread and "
                        "coroutine that needs it; release before suspending "
                        "or switch to `asyncio.Lock`",
                    )


# ---- async-coroutine-drop -----------------------------------------------------


class CoroutineLifecycleRule(ProgramRule):
    name = "async-coroutine-drop"
    description = (
        "coroutine built but never awaited, or task handle dropped "
        "(silent-exception sink)"
    )
    scope = None  # everywhere

    def check_program(self, program: Program) -> Iterable[Finding]:
        graph = program.callgraph
        for key, fn in graph.functions.items():
            if not self.applies(fn.mod):
                continue
            for node in own_nodes(fn):
                if not (
                    isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)
                ):
                    continue
                call = node.value
                callee_key = graph.resolved_calls.get(id(call))
                if callee_key is not None and graph.functions[callee_key].is_async:
                    callee = graph.functions[callee_key]
                    yield fn.mod.finding(
                        self.name,
                        node,
                        f"coroutine `{callee.short_name}()` is called but "
                        "never awaited — the coroutine object is discarded "
                        "and its body never runs",
                    )
                    continue
                spawner = self._spawner_name(fn, call)
                if spawner is not None:
                    yield fn.mod.finding(
                        self.name,
                        node,
                        f"`{spawner}(...)` result is dropped — keep the "
                        "task/future reference (or add a done-callback); "
                        "otherwise it can be garbage-collected mid-flight "
                        "and its exceptions vanish",
                    )

    @staticmethod
    def _spawner_name(fn: FunctionNode, call: ast.Call) -> Optional[str]:
        if isinstance(call.func, ast.Attribute) and call.func.attr in _SPAWNERS:
            return call.func.attr
        dotted = fn.mod.dotted(call.func)
        if dotted is not None and dotted.split(".")[-1] in _SPAWNERS:
            return dotted.split(".")[-1]
        return None


# ---- async-loop-state ---------------------------------------------------------


class LoopStateRule(ProgramRule):
    name = "async-loop-state"
    description = (
        "attribute written both on the event loop and from plain threads "
        "with no common lock"
    )
    scope = None  # everywhere

    def check_program(self, program: Program) -> Iterable[Finding]:
        from p2pdl_tpu.analysis.lockflow import _write_targets

        model = async_model_for(program)
        lm = model.locks
        classes: dict[tuple[str, str], list[FunctionNode]] = {}
        for fn in model.graph.functions.values():
            if fn.cls is not None:
                classes.setdefault((fn.relpath, fn.cls), []).append(fn)
        # Nested defs (closures in methods) write through captured `self`.
        for fn in model.graph.functions.values():
            if fn.cls is not None:
                continue
            for (relpath, cls_qual), fns in classes.items():
                if fn.relpath == relpath and fn.qualname.startswith(cls_qual + "."):
                    fns.append(fn)
                    break
        for (relpath, cls_qual) in sorted(classes):
            mod = program.module(relpath)
            if mod is None or not self.applies(mod):
                continue
            lock_attrs = set(lm.class_locks.get((relpath, cls_qual), {}))
            writes: dict[str, dict[str, list[tuple[FunctionNode, ast.AST]]]] = {}
            for fn in classes[(relpath, cls_qual)]:
                if fn.qualname == f"{cls_qual}.__init__":
                    continue  # not yet shared across threads
                side = "loop" if fn.key in model.loop_ctx else "thread"
                for node in own_nodes(fn):
                    for target in _write_targets(node):
                        attr = _self_attr(target)
                        if attr is None or attr in lock_attrs:
                            continue
                        writes.setdefault(
                            attr, {"loop": [], "thread": []}
                        )[side].append((fn, node))
            thread_lids = [
                lid
                for lid in lm.class_lock_ids(relpath, cls_qual)
                if _is_thread_lock(lm, lid)
            ]
            for attr in sorted(writes):
                sides = writes[attr]
                if not sides["loop"] or not sides["thread"]:
                    continue
                all_sites = sides["loop"] + sides["thread"]
                if any(
                    self._guards_all(lm, lid, all_sites) for lid in thread_lids
                ):
                    continue
                loop_qual = sorted(f.qualname for f, _ in sides["loop"])[0]
                site_fn, site = min(
                    sides["thread"], key=lambda p: getattr(p[1], "lineno", 0)
                )
                yield mod.finding(
                    self.name,
                    site,
                    f"`self.{attr}` of `{cls_qual}` is written on the event "
                    f"loop (`{loop_qual}`) and from plain threads "
                    f"(`{site_fn.qualname}`) with no common lock — route the "
                    "thread-side mutation through `call_soon_threadsafe` / "
                    "`run_coroutine_threadsafe`, or guard every write site",
                )

    @staticmethod
    def _guards_all(lm: LockModel, lid: tuple, sites) -> bool:
        return all(
            lid in lm.held_at(fn.key, node) or lm.entered_locked(fn.key, [lid])
            for fn, node in sites
        )


register(EventLoopBlockingRule())
register(AwaitUnderThreadLockRule())
register(CoroutineLifecycleRule())
register(LoopStateRule())
