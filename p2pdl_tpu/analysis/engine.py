"""p2plint engine: AST rule runner, suppressions, baseline, reporters.

A project-native static-analysis pass: the protocol invariants the paper's
trust plane rests on (injective wire encodings, bit-identical replay, one
device->host transfer per round, lock discipline around shared state) are
properties of the *source tree*, not of any one test run — so they are
checked as such. The engine is deliberately small and stdlib-only (``ast``
plus ``struct`` for format validation): it must run anywhere the repo
checks out, with no backend and no third-party linter framework.

Moving parts:

- **Rules** (:class:`Rule`) are registered checker objects; each declares a
  stable ``name`` (the suppression/baseline key) and an optional
  package-relative ``scope``. The four rule families live in sibling
  modules (``determinism``, ``hostsync``, ``locks``, ``wire``).
- **Suppressions**: ``# p2plint: disable=rule-a,rule-b -- reason`` on the
  offending line (or on a standalone comment line directly above it)
  silences those rules for that line; ``# p2plint: disable-file=rule``
  anywhere in a file silences the rule file-wide. ``all`` matches every
  rule. The ``-- reason`` tail is for the human reader and is required by
  convention (the gate test has no way to check intent, reviewers do).
- **Baseline**: pre-existing, justified findings live in a committed JSON
  file keyed by ``(rule, path, context, message)`` — deliberately *not* by
  line number, so unrelated edits above a finding do not invalidate the
  baseline. Every entry carries a ``reason`` string. Regenerate with
  ``python -m p2pdl_tpu.cli lint --write-baseline`` (existing reasons are
  preserved; new entries get a TODO placeholder that a human must edit).
- **Reporters**: human text (``path:line:col: rule: message``) and a JSON
  document (``--json``) for tooling.

The tier-1 gate (``tests/test_lint_gate.py``) runs :func:`run_lint` over
the package tree and fails on any finding that is neither suppressed nor
baselined — so the invariants ride the existing verify command with no CI
infrastructure.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import json
import os
import subprocess
import time
from typing import Any, Iterable, Optional

DIRECTIVE = "p2plint:"
ALL_RULES_TOKEN = "all"

#: Default lint root: the installed package tree.
PACKAGE_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
#: Default committed baseline location.
DEFAULT_BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``context`` is the enclosing qualname (``Class.method`` or
    ``<module>``); the baseline fingerprint is ``(rule, path, context,
    message)`` — line/col are for the human report only, so findings
    survive unrelated line-number drift.
    """

    rule: str
    path: str  # package-relative posix path
    line: int
    col: int
    message: str
    context: str

    def fingerprint(self) -> tuple[str, str, str, str]:
        return (self.rule, self.path, self.context, self.message)

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


class Suppressions:
    """Per-file suppression index parsed from ``# p2plint:`` comments."""

    def __init__(self, lines: list[str]) -> None:
        self.line_rules: dict[int, set[str]] = {}
        self.file_rules: set[str] = set()
        for i, raw in enumerate(lines, start=1):
            hash_pos = raw.find("#")
            if hash_pos < 0:
                continue
            comment = raw[hash_pos:]
            d = comment.find(DIRECTIVE)
            if d < 0:
                continue
            body = comment[d + len(DIRECTIVE) :].strip()
            # Strip the human-readable reason tail.
            body = body.split("--", 1)[0].strip()
            rules: Optional[set[str]] = None
            target_file = False
            if body.startswith("disable-file="):
                rules = {r.strip() for r in body[len("disable-file=") :].split(",")}
                target_file = True
            elif body.startswith("disable="):
                rules = {r.strip() for r in body[len("disable=") :].split(",")}
            if not rules:
                continue
            rules = {r for r in rules if r}
            if target_file:
                self.file_rules |= rules
            else:
                self.line_rules.setdefault(i, set()).update(rules)
                # A standalone comment line suppresses the line below it.
                if raw[:hash_pos].strip() == "":
                    self.line_rules.setdefault(i + 1, set()).update(rules)

    def is_suppressed(self, rule: str, line: int) -> bool:
        for pool in (self.file_rules, self.line_rules.get(line, ())):
            if rule in pool or ALL_RULES_TOKEN in pool:
                return True
        return False


def _build_contexts(tree: ast.AST) -> dict[ast.AST, str]:
    """Map every node to its enclosing qualname (``Class.method`` etc.)."""
    contexts: dict[ast.AST, str] = {tree: "<module>"}

    def walk(node: ast.AST, name: str) -> None:
        for child in ast.iter_child_nodes(node):
            child_name = name
            if isinstance(
                child, (ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                child_name = f"{name}.{child.name}" if name else child.name
            contexts[child] = child_name or "<module>"
            walk(child, child_name)

    walk(tree, "")
    return contexts


def _build_aliases(tree: ast.AST) -> dict[str, str]:
    """Import alias map: local name -> canonical dotted origin.

    ``import numpy as np`` -> ``{"np": "numpy"}``; ``from os import urandom``
    -> ``{"urandom": "os.urandom"}``. Rules match canonical names, so
    renamed imports cannot dodge a checker.
    """
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for n in node.names:
                if n.asname:
                    aliases[n.asname] = n.name
                else:
                    first = n.name.split(".")[0]
                    aliases.setdefault(first, first)
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for n in node.names:
                if n.name == "*":
                    continue
                aliases[n.asname or n.name] = f"{node.module}.{n.name}"
    return aliases


class ModuleInfo:
    """One parsed source file plus the indexes the rules share."""

    def __init__(self, source: str, relpath: str, path: str = "") -> None:
        self.source = source
        self.relpath = relpath.replace(os.sep, "/")
        self.path = path or relpath
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        self.contexts = _build_contexts(self.tree)
        self.aliases = _build_aliases(self.tree)
        self.suppressions = Suppressions(self.lines)
        self._walk_cache: Optional[list[ast.AST]] = None

    def walk(self) -> list[ast.AST]:
        """Every AST node, computed once and shared by all rules (each rule
        used to re-run ``ast.walk`` over the same tree)."""
        if self._walk_cache is None:
            self._walk_cache = list(ast.walk(self.tree))
        return self._walk_cache

    @property
    def norm_relpath(self) -> str:
        """Package-relative path: a leading ``p2pdl_tpu/`` is stripped so
        rule scopes match both an in-repo root and a fixture tree."""
        p = self.relpath
        if p.startswith("p2pdl_tpu/"):
            p = p[len("p2pdl_tpu/") :]
        return p

    def context_of(self, node: ast.AST) -> str:
        return self.contexts.get(node, "<module>")

    def dotted(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of a Name/Attribute chain, imports
        resolved; None for anything not a plain chain."""
        parts: list[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        parts.append(cur.id)
        parts.reverse()
        parts[0] = self.aliases.get(parts[0], parts[0])
        return ".".join(parts)

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=rule,
            path=self.relpath,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            message=message,
            context=self.context_of(node),
        )


class Rule:
    """Base checker: a stable ``name``, an optional package-relative
    ``scope`` (tuple of path prefixes; ``None`` = every file), and a
    ``check(mod)`` returning findings. Subclasses are registered once as
    instances via :func:`register`."""

    name: str = ""
    description: str = ""
    scope: Optional[tuple[str, ...]] = None

    def applies(self, mod: ModuleInfo) -> bool:
        if self.scope is None:
            return True
        p = mod.norm_relpath
        return any(
            p == s or (s.endswith("/") and p.startswith(s)) for s in self.scope
        )

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


class Program:
    """The whole-tree view program rules analyze: every parsed module plus
    a lazily-built conservative call graph shared across rules."""

    def __init__(self, mods: list[ModuleInfo]) -> None:
        self.mods = mods
        self._by_relpath = {m.relpath: m for m in mods}
        self._callgraph: Any = None

    def module(self, relpath: str) -> Optional[ModuleInfo]:
        return self._by_relpath.get(relpath)

    @property
    def callgraph(self):
        if self._callgraph is None:
            from p2pdl_tpu.analysis.callgraph import build_callgraph

            self._callgraph = build_callgraph(self.mods)
        return self._callgraph


class ProgramRule(Rule):
    """A whole-program checker: sees every module at once (plus the shared
    call graph) instead of one file at a time. ``scope`` still applies —
    use :meth:`applies` inside ``check_program`` to filter modules."""

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError("program rules implement check_program")

    def check_program(
        self, program: Program
    ) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


_RULES: dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    if not rule.name:
        raise ValueError("rule needs a stable name")
    if rule.name in _RULES:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    _RULES[rule.name] = rule
    return rule


def all_rules() -> list[Rule]:
    """Every registered rule, rule modules imported on first use.

    The import is unconditional (not guarded on ``_RULES`` being empty):
    rule modules import each other — ``asyncflow`` pulls in ``lockflow``
    and ``locks`` — so a direct import of one of them pre-populates the
    registry and an emptiness guard would then skip the remaining
    families forever. Re-imports are cached no-ops, so this stays cheap
    and each module still registers exactly once.
    """
    from p2pdl_tpu.analysis import (  # noqa: F401
        asyncflow,
        cardinality,
        determinism,
        donation,
        hostsync,
        lockflow,
        locks,
        wire,
        wiretaint,
    )

    return list(_RULES.values())


def _parse_error_finding(relpath: str, e: SyntaxError) -> Finding:
    return Finding(
        rule="parse-error",
        path=relpath.replace(os.sep, "/"),
        line=e.lineno or 0,
        col=e.offset or 0,
        message=f"file does not parse: {e.msg}",
        context="<module>",
    )


def lint_program(
    mods: list[ModuleInfo],
    rules: Optional[list[Rule]] = None,
    timings: Optional[dict[str, float]] = None,
) -> list[Finding]:
    """Run per-module rules over each module and program rules once over
    the whole module set; suppressions apply uniformly. ``timings``, if
    given, accumulates per-rule wall seconds."""
    rules = rules if rules is not None else all_rules()
    per_module = [r for r in rules if not isinstance(r, ProgramRule)]
    program_rules = [r for r in rules if isinstance(r, ProgramRule)]
    by_relpath = {m.relpath: m for m in mods}
    raw: list[Finding] = []
    for rule in per_module:
        t0 = time.perf_counter()
        for mod in mods:
            if rule.applies(mod):
                raw.extend(rule.check(mod))
        if timings is not None:
            timings[rule.name] = timings.get(rule.name, 0.0) + (
                time.perf_counter() - t0
            )
    if program_rules:
        program = Program(mods)
        for rule in program_rules:
            t0 = time.perf_counter()
            raw.extend(rule.check_program(program))
            if timings is not None:
                timings[rule.name] = timings.get(rule.name, 0.0) + (
                    time.perf_counter() - t0
                )
    findings: list[Finding] = []
    for f in raw:
        mod = by_relpath.get(f.path)
        if mod is not None and mod.suppressions.is_suppressed(f.rule, f.line):
            continue
        findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_module(mod: ModuleInfo, rules: Optional[list[Rule]] = None) -> list[Finding]:
    """Back-compat single-module entry point (program rules see a
    one-module program)."""
    return lint_program([mod], rules)


def lint_source(
    source: str, relpath: str, rules: Optional[list[Rule]] = None
) -> list[Finding]:
    """Lint one in-memory source blob (the test-fixture entry point)."""
    try:
        mod = ModuleInfo(source, relpath)
    except SyntaxError as e:
        return [_parse_error_finding(relpath, e)]
    return lint_program([mod], rules)


def iter_python_files(root: str) -> Iterable[tuple[str, str]]:
    """Yield ``(abspath, relpath)`` for every ``.py`` under ``root``."""
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d not in ("__pycache__", ".git")
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                full = os.path.join(dirpath, fn)
                yield full, os.path.relpath(full, root).replace(os.sep, "/")


def lint_tree(
    root: Optional[str] = None,
    rules: Optional[list[Rule]] = None,
    files: Optional[Iterable[str]] = None,
    timings: Optional[dict[str, float]] = None,
) -> tuple[list[Finding], int]:
    """Lint every Python file under ``root`` (default: the package tree);
    returns ``(findings, files_scanned)``. ``files`` restricts the scan to
    the given root-relative paths (``--changed``); program rules then see
    only that subset, so cross-file attribution degrades conservatively."""
    root = root or PACKAGE_ROOT
    wanted = None if files is None else {p.replace(os.sep, "/") for p in files}
    findings: list[Finding] = []
    mods: list[ModuleInfo] = []
    n_files = 0
    for full, rel in iter_python_files(root):
        if wanted is not None and rel not in wanted:
            continue
        n_files += 1
        with open(full, encoding="utf-8") as f:
            source = f.read()
        try:
            mods.append(ModuleInfo(source, rel, path=full))
        except SyntaxError as e:
            findings.append(_parse_error_finding(rel, e))
    findings.extend(lint_program(mods, rules, timings))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, n_files


# ---- Baseline ---------------------------------------------------------------


def load_baseline(path: Optional[str] = None) -> list[dict[str, Any]]:
    """Baseline entries; a missing file is an empty baseline, a malformed
    one is an error (a silently-ignored baseline would un-gate the tree)."""
    path = path or DEFAULT_BASELINE_PATH
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    entries = doc.get("entries") if isinstance(doc, dict) else None
    if not isinstance(entries, list):
        raise ValueError(f"{path}: expected {{'entries': [...]}} baseline document")
    return entries


def _entry_fp(entry: dict[str, Any]) -> tuple[str, str, str, str]:
    return (
        str(entry.get("rule", "")),
        str(entry.get("path", "")),
        str(entry.get("context", "")),
        str(entry.get("message", "")),
    )


def apply_baseline(
    findings: list[Finding], entries: list[dict[str, Any]]
) -> tuple[list[Finding], list[Finding], list[dict[str, Any]]]:
    """Split findings into ``(new, baselined)`` and return the baseline
    entries that matched nothing (``stale``) — drift in either direction is
    visible."""
    known = {_entry_fp(e) for e in entries}
    matched: set[tuple[str, str, str, str]] = set()
    new: list[Finding] = []
    baselined: list[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if fp in known:
            matched.add(fp)
            baselined.append(f)
        else:
            new.append(f)
    stale = [e for e in entries if _entry_fp(e) not in matched]
    return new, baselined, stale


TODO_REASON = "TODO: justify this finding or fix the code"


def write_baseline_file(
    path: str, findings: list[Finding], existing: Optional[list[dict[str, Any]]] = None
) -> int:
    """Write a baseline covering every current finding. Reasons from
    ``existing`` entries are preserved by fingerprint; genuinely new
    entries get :data:`TODO_REASON` (a human must replace it — the gate
    test refuses TODO reasons). Returns the number of entries written."""
    reasons = {_entry_fp(e): e.get("reason", TODO_REASON) for e in existing or []}
    entries = []
    seen: set[tuple[str, str, str, str]] = set()
    for f in sorted(findings, key=lambda f: (f.path, f.rule, f.context, f.message)):
        fp = f.fingerprint()
        if fp in seen:
            continue  # one entry suppresses every identical-fingerprint finding
        seen.add(fp)
        entries.append(
            {
                "rule": f.rule,
                "path": f.path,
                "context": f.context,
                "message": f.message,
                "line": f.line,  # informational only; never matched on
                "reason": reasons.get(fp, TODO_REASON),
            }
        )
    doc = {
        "comment": (
            "p2plint baseline: pre-existing, justified findings. Matched by "
            "(rule, path, context, message) — 'line' is informational. Every "
            "entry needs a real 'reason'; regenerate with "
            "`python -m p2pdl_tpu.cli lint --write-baseline` (reasons are "
            "preserved) and justify anything new."
        ),
        "entries": entries,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")
    return len(entries)


# ---- Orchestration + reporters ---------------------------------------------


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]  # everything, pre-baseline
    new: list[Finding]
    baselined: list[Finding]
    stale_entries: list[dict[str, Any]]
    files_scanned: int
    rule_seconds: dict[str, float] = dataclasses.field(default_factory=dict)


def run_lint(
    root: Optional[str] = None,
    baseline_path: Optional[str] = None,
    rules: Optional[list[Rule]] = None,
    files: Optional[Iterable[str]] = None,
) -> LintResult:
    timings: dict[str, float] = {}
    findings, n_files = lint_tree(root, rules, files=files, timings=timings)
    entries = load_baseline(baseline_path)
    if files is not None:
        # A partial scan can neither match nor invalidate baseline entries
        # for files it never read.
        scanned = {p.replace(os.sep, "/") for p in files}
        entries = [e for e in entries if str(e.get("path", "")) in scanned]
    if rules is not None:
        active = {r.name for r in rules}
        entries = [e for e in entries if str(e.get("rule", "")) in active]
    new, baselined, stale = apply_baseline(findings, entries)
    return LintResult(
        findings=findings,
        new=new,
        baselined=baselined,
        stale_entries=stale,
        files_scanned=n_files,
        rule_seconds=timings,
    )


def render_text(result: LintResult) -> str:
    out: list[str] = []
    for f in result.new:
        out.append(f"{f.path}:{f.line}:{f.col}: {f.rule}: {f.message} [{f.context}]")
    for e in result.stale_entries:
        out.append(
            f"stale baseline entry: {e.get('rule')} @ {e.get('path')} "
            f"[{e.get('context')}]: {e.get('message')}"
        )
    out.append(
        f"p2plint: {result.files_scanned} files, "
        f"{len(result.new)} new finding(s), "
        f"{len(result.baselined)} baselined, "
        f"{len(result.stale_entries)} stale baseline entr(y/ies)"
    )
    return "\n".join(out)


def render_json(result: LintResult) -> dict[str, Any]:
    return {
        "files_scanned": result.files_scanned,
        "new_findings": [f.to_dict() for f in result.new],
        "baselined_count": len(result.baselined),
        "stale_baseline_entries": result.stale_entries,
        "rule_seconds": {
            name: round(secs, 6)
            for name, secs in sorted(result.rule_seconds.items())
        },
        "exit_code": 1 if result.new else 0,
    }


def render_sarif(
    result: LintResult, rules: Optional[list[Rule]] = None
) -> dict[str, Any]:
    """SARIF 2.1.0 document over the *new* findings (baselined findings are
    accepted debt, not review items)."""
    rule_meta = [
        {
            "id": r.name,
            "shortDescription": {"text": r.description or r.name},
        }
        for r in sorted(rules if rules is not None else all_rules(), key=lambda r: r.name)
    ]
    results = [
        {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f"{f.message} [{f.context}]"},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {
                            "startLine": max(f.line, 1),
                            "startColumn": f.col + 1,
                        },
                    }
                }
            ],
        }
        for f in result.new
    ]
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "p2plint",
                        "informationUri": "https://example.invalid/p2pdl-tpu",
                        "rules": rule_meta,
                    }
                },
                "results": results,
            }
        ],
    }


def changed_files(root: str) -> list[str]:
    """Root-relative ``.py`` files touched vs HEAD (staged, unstaged, and
    untracked) for ``cli lint --changed``. Raises RuntimeError when git is
    unusable — the caller turns that into a usage error, not a clean run."""
    root = os.path.abspath(root)
    try:
        top = subprocess.run(
            ["git", "-C", root, "rev-parse", "--show-toplevel"],
            capture_output=True,
            text=True,
            timeout=30,
            check=False,
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        raise RuntimeError(f"git unavailable for --changed: {e}") from e
    if top.returncode != 0:
        raise RuntimeError(
            f"--changed needs a git checkout: {top.stderr.strip() or 'rev-parse failed'}"
        )
    toplevel = top.stdout.strip()
    out: set[str] = set()
    for argv in (
        ["git", "-C", root, "diff", "--name-only", "HEAD", "--"],
        # --full-name: ls-files is cwd-relative by default (diff is not).
        ["git", "-C", root, "ls-files", "--others", "--exclude-standard", "--full-name"],
    ):
        try:
            proc = subprocess.run(
                argv, capture_output=True, text=True, timeout=30, check=False
            )
        except (OSError, subprocess.TimeoutExpired) as e:
            raise RuntimeError(f"git unavailable for --changed: {e}") from e
        if proc.returncode != 0:
            raise RuntimeError(
                f"`{' '.join(argv)}` failed: {proc.stderr.strip() or proc.returncode}"
            )
        for line in proc.stdout.splitlines():
            line = line.strip()
            if not line.endswith(".py"):
                continue
            # git paths are repo-root-relative; re-anchor on the lint root.
            rel = os.path.relpath(os.path.join(toplevel, line), root)
            if not rel.startswith(".."):
                out.add(rel.replace(os.sep, "/"))
    return sorted(out)


def resolve_rules(only: Optional[str]) -> Optional[list[Rule]]:
    """``--only a,b`` -> rule instances. Entries may be ``fnmatch`` globs
    (``async-*`` selects the whole family); a name or pattern matching no
    registered rule raises ValueError."""
    if not only:
        return None
    names = [n.strip() for n in only.split(",") if n.strip()]
    by_name = {r.name: r for r in all_rules()}
    selected: list[str] = []
    unknown: list[str] = []
    for n in names:
        if any(ch in n for ch in "*?["):
            hits = sorted(k for k in by_name if fnmatch.fnmatchcase(k, n))
            if not hits:
                unknown.append(n)
            selected.extend(h for h in hits if h not in selected)
        elif n in by_name:
            if n not in selected:
                selected.append(n)
        else:
            unknown.append(n)
    if unknown:
        raise ValueError(
            f"unknown rule(s): {', '.join(unknown)} "
            f"(known: {', '.join(sorted(by_name))})"
        )
    return [by_name[n] for n in selected]


def cli_lint(
    root: Optional[str] = None,
    baseline_path: Optional[str] = None,
    json_out: bool = False,
    write_baseline: bool = False,
    sarif_out: bool = False,
    only: Optional[str] = None,
    changed: bool = False,
) -> int:
    """The ``p2pdl_tpu.cli lint`` implementation. Exit 0 iff the tree is
    clean modulo the baseline (stale entries print as warnings but do not
    fail the CLI — the gate test is the strict consumer); exit 2 on usage
    errors. The exit-code matrix for findings is unchanged by ``--only`` /
    ``--changed`` / ``--sarif``."""
    baseline_path = baseline_path or DEFAULT_BASELINE_PATH
    try:
        rules = resolve_rules(only)
    except ValueError as e:
        print(f"p2plint: {e}")
        return 2
    files: Optional[list[str]] = None
    if changed:
        try:
            files = changed_files(root or PACKAGE_ROOT)
        except RuntimeError as e:
            print(f"p2plint: {e}")
            return 2
    if write_baseline and (rules is not None or files is not None):
        # A partial scan would silently drop every out-of-scope entry.
        print("p2plint: --write-baseline cannot combine with --only/--changed")
        return 2
    result = run_lint(root, baseline_path, rules=rules, files=files)
    if write_baseline:
        existing = load_baseline(baseline_path)
        current = {f.fingerprint() for f in result.findings}
        pruned = [e for e in existing if _entry_fp(e) not in current]
        n = write_baseline_file(baseline_path, result.findings, existing)
        for e in pruned:
            print(
                f"p2plint: pruned stale baseline entry: {e.get('rule')} @ "
                f"{e.get('path')} [{e.get('context')}]: {e.get('message')}"
            )
        print(
            f"p2plint: wrote {n} baseline entr(y/ies) to {baseline_path}"
            + (f" ({len(pruned)} pruned)" if pruned else "")
        )
        return 0
    if sarif_out:
        print(json.dumps(render_sarif(result, rules), indent=2))
    elif json_out:
        print(json.dumps(render_json(result), indent=2))
    else:
        print(render_text(result))
    return 1 if result.new else 0
