"""Wire-conformance rules: struct formats, signing injectivity, kind codes.

PR 4's review found a real forgery: the v1 ``BRBBatch.signing_bytes``
joined variable-width fields with ``b"|"``, so two different batches could
produce one signed byte string (re-framing attack). The fix was fixed-width
``struct.pack`` fields; these rules make that pattern — and basic wire
hygiene — machine-checked:

- ``wire-struct``: every ``struct.pack``/``unpack``/``Struct`` call with a
  literal format string is validated (``calcsize``), ``pack`` argument
  counts must match the format's consumed-value count, and ``unpack``
  buffer lengths are checked when statically known (``f.read(4)``,
  ``_read_exact(f, 4)``, a bytes literal, a constant slice).
- ``wire-signing``: inside any function whose name contains ``signing``,
  a ``.join`` with a non-empty literal delimiter is flagged (delimiter
  joins of attacker-influenced fields are not injective), as is any
  variable-width ``str(...).encode()`` field. ``b"".join`` of fixed-width
  pieces — the sanctioned PR 4 pattern — is clean.
- ``wire-kind-dup``: module/class-level dict literals whose name looks
  like a kind/code registry must register each key and each code exactly
  once, and the registry itself must be assigned only once.
"""

from __future__ import annotations

import ast
import re
import struct
from typing import Iterable, Optional

from p2pdl_tpu.analysis.engine import Finding, ModuleInfo, Rule, register

_STRUCT_CALLS = {
    "struct.pack",
    "struct.pack_into",
    "struct.unpack",
    "struct.unpack_from",
    "struct.Struct",
    "struct.calcsize",
}
_FMT_TOKEN = re.compile(r"(\d*)([xcbB?hHiIlLqQnNefdspP])")


def _fmt_arg_count(fmt: str) -> int:
    """How many Python values a struct format consumes/produces.

    ``s``/``p`` consume one value regardless of count; ``x`` consumes
    none; every other code consumes ``count`` values.
    """
    body = fmt.strip()
    if body and body[0] in "@=<>!":
        body = body[1:]
    n = 0
    for count, code in _FMT_TOKEN.findall(body.replace(" ", "")):
        k = int(count) if count else 1
        if code == "x":
            continue
        if code in "sp":
            n += 1
        else:
            n += k
    return n


def _static_buffer_len(mod: ModuleInfo, node: ast.AST) -> Optional[int]:
    """Statically-known byte length of an unpack buffer argument, if any."""
    if isinstance(node, ast.Constant) and isinstance(node.value, (bytes, bytearray)):
        return len(node.value)
    if isinstance(node, ast.Call):
        # f.read(4) / stream.read(N)
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "read"
            and len(node.args) == 1
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, int)
        ):
            return node.args[0].value
        # _read_exact(f, 4) helpers
        dotted = mod.dotted(node.func)
        if dotted is not None and dotted.split(".")[-1] in (
            "_read_exact",
            "read_exact",
        ):
            for a in node.args:
                if isinstance(a, ast.Constant) and isinstance(a.value, int):
                    return a.value
    if isinstance(node, ast.Subscript) and isinstance(node.slice, ast.Slice):
        lo, hi = node.slice.lower, node.slice.upper
        lo_v = 0 if lo is None else (lo.value if isinstance(lo, ast.Constant) else None)
        hi_v = hi.value if isinstance(hi, ast.Constant) else None
        if (
            isinstance(lo_v, int)
            and isinstance(hi_v, int)
            and lo_v >= 0
            and hi_v >= lo_v
            and node.slice.step is None
        ):
            return hi_v - lo_v
    return None


class StructFormatRule(Rule):
    name = "wire-struct"
    description = "struct format / argument / buffer-length consistency"
    scope = None  # everywhere

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.dotted(node.func)
            if dotted not in _STRUCT_CALLS or not node.args:
                continue
            fmt_node = node.args[0]
            if not (
                isinstance(fmt_node, ast.Constant)
                and isinstance(fmt_node.value, (str, bytes))
            ):
                continue  # dynamic formats are out of static reach
            fmt = (
                fmt_node.value.decode("ascii", "replace")
                if isinstance(fmt_node.value, bytes)
                else fmt_node.value
            )
            try:
                size = struct.calcsize(fmt)
            except struct.error as e:
                yield mod.finding(
                    self.name, node, f"invalid struct format {fmt!r}: {e}"
                )
                continue
            if any(isinstance(a, ast.Starred) for a in node.args) or node.keywords:
                continue  # splatted values: count unknowable
            expected = _fmt_arg_count(fmt)
            if dotted == "struct.pack":
                got = len(node.args) - 1
                if got != expected:
                    yield mod.finding(
                        self.name,
                        node,
                        f"struct.pack format {fmt!r} consumes {expected} "
                        f"value(s) but the call passes {got}",
                    )
            elif dotted == "struct.pack_into":
                got = len(node.args) - 3  # fmt, buffer, offset, *values
                if got >= 0 and got != expected:
                    yield mod.finding(
                        self.name,
                        node,
                        f"struct.pack_into format {fmt!r} consumes {expected} "
                        f"value(s) but the call passes {got}",
                    )
            elif dotted == "struct.unpack" and len(node.args) >= 2:
                buf_len = _static_buffer_len(mod, node.args[1])
                if buf_len is not None and buf_len != size:
                    yield mod.finding(
                        self.name,
                        node,
                        f"struct.unpack format {fmt!r} needs exactly {size} "
                        f"byte(s) but the buffer provides {buf_len}",
                    )


class SigningBytesRule(Rule):
    name = "wire-signing"
    description = "signing-bytes builders must use fixed-width fields"
    scope = None  # everywhere

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if "signing" not in fn.name:
                continue
            flagged_join = False
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"
                    and isinstance(node.func.value, ast.Constant)
                    and isinstance(node.func.value.value, (str, bytes))
                    and len(node.func.value.value) > 0
                ):
                    flagged_join = True
                    yield mod.finding(
                        self.name,
                        node,
                        f"delimiter join `{node.func.value.value!r}.join(...)` "
                        "in a signing-bytes builder is not injective "
                        "(re-framing forgery); pack fixed-width fields with "
                        "struct instead",
                    )
            if flagged_join:
                continue  # the join finding already covers its str() fields
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "encode"
                ):
                    if isinstance(node.func.value, ast.Call) and mod.dotted(
                        node.func.value.func
                    ) == "str":
                        yield mod.finding(
                            self.name,
                            node,
                            "variable-width `str(...).encode()` field in a "
                            "signing-bytes builder; use fixed-width struct "
                            "packing for injectivity",
                        )
                    elif isinstance(node.func.value, ast.JoinedStr):
                        yield mod.finding(
                            self.name,
                            node,
                            "variable-width f-string `.encode()` field in a "
                            "signing-bytes builder; use fixed-width struct "
                            "packing for injectivity",
                        )
                    elif isinstance(node.func.value, ast.Call) and mod.dotted(
                        node.func.value.func
                    ) in ("json.dumps", "dumps"):
                        yield mod.finding(
                            self.name,
                            node,
                            "variable-width `json.dumps(...).encode()` field "
                            "in a signing-bytes builder; JSON key order and "
                            "whitespace are not canonical — pack fixed-width "
                            "struct fields instead",
                        )
            yield from self._check_magic_collisions(mod, fn)

    def _check_magic_collisions(
        self, mod: ModuleInfo, fn: ast.AST
    ) -> Iterable[Finding]:
        """A versioned signing builder (the wire v2/v3 pattern) packs one
        header per revision; two different header layouts sharing one magic
        would make the revisions mutually forgeable — each struct format
        must open with its own distinct magic constant."""
        fmt_by_magic: dict[bytes, str] = {}
        for node in ast.walk(fn):
            if not (
                isinstance(node, ast.Call)
                and mod.dotted(node.func) == "struct.pack"
                and len(node.args) >= 2
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, (str, bytes))
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, bytes)
            ):
                continue
            fmt = node.args[0].value
            fmt = fmt.decode("ascii", "replace") if isinstance(fmt, bytes) else fmt
            magic = node.args[1].value
            prev = fmt_by_magic.get(magic)
            if prev is not None and prev != fmt:
                yield mod.finding(
                    self.name,
                    node,
                    f"signing-bytes builder packs two different header "
                    f"layouts ({prev!r} and {fmt!r}) under one magic "
                    f"{magic!r}; each wire revision needs its own magic for "
                    "mutual injectivity",
                )
            fmt_by_magic.setdefault(magic, fmt)


_REGISTRY_NAME = re.compile(r"(^|_)(KIND|KINDS|CODE|CODES|REGISTRY)(_|$)")


class KindCodeRule(Rule):
    name = "wire-kind-dup"
    description = "wire kind codes registered exactly once"
    scope = ("protocol/",)

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        assigned: dict[str, int] = {}
        # Module body plus class bodies: registries live at either level.
        bodies = [mod.tree.body] + [
            n.body for n in ast.walk(mod.tree) if isinstance(n, ast.ClassDef)
        ]
        for body in bodies:
            for st in body:
                if not isinstance(st, ast.Assign):
                    continue
                for t in st.targets:
                    if not isinstance(t, ast.Name):
                        continue
                    if not _REGISTRY_NAME.search(t.id):
                        continue
                    assigned[t.id] = assigned.get(t.id, 0) + 1
                    if assigned[t.id] > 1:
                        yield mod.finding(
                            self.name,
                            st,
                            f"wire registry `{t.id}` is assigned more than "
                            "once; kind codes must have a single source of "
                            "truth",
                        )
                    if isinstance(st.value, ast.Dict):
                        yield from self._check_dict(mod, t.id, st.value)

    def _check_dict(
        self, mod: ModuleInfo, name: str, node: ast.Dict
    ) -> Iterable[Finding]:
        seen_keys: dict[str, ast.AST] = {}
        seen_vals: dict[object, ast.AST] = {}
        for k, v in zip(node.keys, node.values):
            if k is None:
                continue  # ** expansion
            key_repr = (
                repr(k.value) if isinstance(k, ast.Constant) else ast.dump(k)
            )
            if key_repr in seen_keys:
                yield mod.finding(
                    self.name,
                    k,
                    f"wire registry `{name}` registers kind {key_repr} twice",
                )
            seen_keys[key_repr] = k
            if isinstance(v, ast.Constant) and isinstance(v.value, (int, str, bytes)):
                if v.value in seen_vals:
                    yield mod.finding(
                        self.name,
                        v,
                        f"wire registry `{name}` maps two kinds to the same "
                        f"code {v.value!r}",
                    )
                seen_vals[v.value] = v


register(StructFormatRule())
register(SigningBytesRule())
register(KindCodeRule())
