"""Donation-discipline rule: dispatch-site ``jax.jit`` must donate its
state carry.

The round programs donate their ``PeerState`` argument
(``donate_argnums=(0,)``) so XLA reuses the old state's buffers for the
new state instead of holding both live across the dispatch. The depth-k
pipelined loop raises the stakes: with ``pipeline_depth`` rounds in
flight, an undonated state carry keeps k+1 copies of the working set live
at once — at 1024 peers that is an OOM, not a slowdown.

This rule flags every ``jax.jit`` call (or bare ``@jax.jit`` decorator,
which cannot pass donation at all) in the dispatch-site module
(``parallel/round.py``) that does not pass ``donate_argnums`` /
``donate_argnames``. Sites that legitimately must NOT donate — the trust
pipeline's ``train_fn`` (the state is re-consumed by ``agg_fn``), the
digest pack (reads a delta the aggregate still needs), held-out eval (the
state is read every round) — are sanctioned in the committed baseline with
their reasons.
"""

from __future__ import annotations

import ast
from typing import Iterable

from p2pdl_tpu.analysis.engine import Finding, ModuleInfo, Rule, register

_DONATE_KEYWORDS = {"donate_argnums", "donate_argnames"}

_MSG = (
    "`jax.jit` at a dispatch site without `donate_argnums`: an undonated "
    "state carry keeps the previous buffers live across the dispatch "
    "(k+1 working sets with depth-k pipelining in flight); donate the "
    "state-carry args or sanction the site with a reason"
)

_DECORATOR_MSG = (
    "bare `@jax.jit` decorator at a dispatch site cannot pass "
    "`donate_argnums`; use `jax.jit(fn, donate_argnums=...)` or sanction "
    "the site with a reason"
)


class DonationRule(Rule):
    name = "donation-discipline"
    description = "dispatch-site jax.jit must donate its state-carry args"
    scope = ("parallel/round.py",)

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                if mod.dotted(node.func) != "jax.jit":
                    continue
                if any(kw.arg in _DONATE_KEYWORDS for kw in node.keywords):
                    continue
                yield mod.finding(self.name, node, _MSG)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    # A bare `@jax.jit` (no call parens) — a Call decorator
                    # is already handled by the branch above.
                    if not isinstance(dec, ast.Call) and (
                        mod.dotted(dec) == "jax.jit"
                    ):
                        yield mod.finding(self.name, dec, _DECORATOR_MSG)


register(DonationRule())
