"""wire-taint rule: unverified wire bytes must not reach protocol sinks.

This is the static form of the invariant PR 4 was twice caught violating:
attacker-controlled bytes must be shape-validated and signature-verified
before they touch protocol state, allocation sizes, or parsers.

- **Sources** — the functions where bytes leave the attacker's hands:
  ``recv_frame`` / ``_recv_exact`` (raw socket reads), their async-plane
  twins ``recv_frame_async`` / ``readexactly`` (StreamReader frames on the
  pooled event-loop transport), the control-plane
  parsers ``control_from_wire`` / ``brb_from_wire`` / ``batch_from_wire``
  (their *outputs* are attacker-shaped objects), and HTTP request bodies
  (``self.rfile.read``) in the orchestrator.
- **Sanitizers** — signature verification (``verify`` / ``crypto_ok`` /
  ``batch_ok``), key-membership checks (``has_key``), and explicit shape
  validation (comparing a tainted value or its ``len()`` against a
  constant / ALL-CAPS bound). ``handle_preverified`` is a declared trust
  boundary: its callers are audited (the batch path verifies first), so
  taint does not propagate into it.
- **Sinks** — protocol-state writes (``self.state[...] = ...`` and
  mutator calls) in protocol/runtime classes, reads or allocations sized
  by a tainted integer (``read(n)`` / ``recv(n)`` / ``bytearray(n)`` /
  ``range(n)``, plus the decompression buffers ``zeros(n)`` /
  ``empty(n)`` / ``frombuffer(buf, count=n)`` — the 4096x amplification
  shape: a codec that trusts a wire-carried element count allocates
  attacker-chosen memory before any signature check), ``struct.unpack``
  windows positioned by a tainted offset, and ``json.loads`` of an
  unverified payload.

Source functions are themselves boundaries: the sanctioned parsers are
not re-flagged for parsing (their callers see fresh taint instead).
"""

from __future__ import annotations

import ast
from typing import Callable, Iterable

from p2pdl_tpu.analysis.dataflow import TaintEngine, TaintPolicy
from p2pdl_tpu.analysis.engine import (
    Finding,
    ModuleInfo,
    Program,
    ProgramRule,
    register,
)
from p2pdl_tpu.analysis.locks import _MUTATORS, _self_attr

RULE_NAME = "wire-taint"

_SOURCES = frozenset(
    {
        "recv_frame",
        "recv_frame_async",
        "readexactly",
        "control_from_wire",
        "brb_from_wire",
        "batch_from_wire",
        "recv_exact",
        "_recv_exact",
    }
)
_SANITIZERS = frozenset({"verify", "crypto_ok", "batch_ok", "sign_ok", "has_key"})
_SIZED_READS = frozenset(
    {
        "read", "recv", "recvfrom", "recv_exact", "_recv_exact",
        "read_exact", "readexactly",
    }
)
_SIZED_ALLOCS = frozenset(
    {"bytearray", "range", "zeros", "empty", "frombuffer"}
)


def _last_segment(mod: ModuleInfo, func: ast.AST) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        dotted = mod.dotted(func) or func.id
        return dotted.split(".")[-1]
    return ""


class _WirePolicy(TaintPolicy):
    boundaries = _SOURCES | frozenset({"handle_preverified"})

    def __init__(self, rule: "WireTaintRule") -> None:
        self.rule = rule

    def in_scope(self, mod: ModuleInfo) -> bool:
        return self.rule.applies(mod)

    def is_source(self, mod: ModuleInfo, call: ast.Call) -> bool:
        if _last_segment(mod, call.func) in _SOURCES:
            return True
        dotted = mod.dotted(call.func)
        return bool(dotted and dotted.endswith("rfile.read"))

    def is_sanitizer(self, mod: ModuleInfo, call: ast.Call) -> bool:
        return _last_segment(mod, call.func) in _SANITIZERS

    # -- sinks -------------------------------------------------------------

    def check_call(
        self, mod: ModuleInfo, call: ast.Call, tainted: Callable[[ast.AST], bool]
    ) -> Iterable[Finding]:
        name = _last_segment(mod, call.func)
        findings: list[Finding] = []
        any_arg_tainted = any(tainted(a) for a in call.args) or any(
            tainted(kw.value) for kw in call.keywords
        )
        if name in _SIZED_READS and any_arg_tainted:
            findings.append(
                mod.finding(
                    RULE_NAME,
                    call,
                    f"`{name}` sized by an unverified wire integer — bound-check "
                    "it against a constant cap before reading",
                )
            )
        elif name in _SIZED_ALLOCS and any_arg_tainted:
            findings.append(
                mod.finding(
                    RULE_NAME,
                    call,
                    f"`{name}` sized by an unverified wire integer — the "
                    "amplification shape; validate the count first",
                )
            )
        elif name == "loads" and any_arg_tainted:
            findings.append(
                mod.finding(
                    RULE_NAME,
                    call,
                    "json.loads of an unverified wire payload — verify the "
                    "signature or validate the shape first",
                )
            )
        elif name in ("unpack", "unpack_from"):
            for arg in call.args:
                if isinstance(arg, ast.Subscript) and isinstance(
                    arg.slice, ast.Slice
                ):
                    bounds = (arg.slice.lower, arg.slice.upper, arg.slice.step)
                    if any(b is not None and tainted(b) for b in bounds):
                        findings.append(
                            mod.finding(
                                RULE_NAME,
                                call,
                                "struct unpack window positioned by an "
                                "unverified wire integer",
                            )
                        )
                        break
            if name == "unpack_from" and len(call.args) >= 3 and tainted(call.args[2]):
                findings.append(
                    mod.finding(
                        RULE_NAME,
                        call,
                        "struct unpack_from offset from an unverified wire integer",
                    )
                )
        # In-place protocol-state mutation: self.state.add(tainted) etc.
        if isinstance(call.func, ast.Attribute) and call.func.attr in _MUTATORS:
            attr = _self_attr(call.func.value)
            base = call.func.value
            if attr is None and isinstance(base, ast.Subscript):
                attr = _self_attr(base.value)
            if attr is not None and any_arg_tainted:
                findings.append(
                    mod.finding(
                        RULE_NAME,
                        call,
                        f"unverified wire data written into protocol state "
                        f"`self.{attr}` — verify the signature or validate "
                        "the shape first",
                    )
                )
        return findings

    def check_write(
        self,
        mod: ModuleInfo,
        node: ast.AST,
        target: ast.AST,
        value_tainted: bool,
        tainted: Callable[[ast.AST], bool],
    ) -> Iterable[Finding]:
        base = target.value if isinstance(target, ast.Subscript) else target
        attr = _self_attr(base)
        if attr is None:
            return ()
        key_tainted = isinstance(target, ast.Subscript) and tainted(target.slice)
        if not (value_tainted or key_tainted):
            return ()
        return [
            mod.finding(
                RULE_NAME,
                node,
                f"unverified wire data written into protocol state "
                f"`self.{attr}` — verify the signature or validate the "
                "shape first",
            )
        ]


class WireTaintRule(ProgramRule):
    name = RULE_NAME
    description = (
        "wire-derived data reaches protocol state, an allocation size, or a "
        "parser without signature verification or shape validation"
    )
    # ops/ joined when the compressed-delta codec landed: decode paths
    # allocate buffers sized by wire-carried counts, exactly the
    # amplification shape this rule exists to catch.
    scope = ("protocol/", "runtime/", "ops/")

    def check_program(self, program: Program) -> Iterable[Finding]:
        if not any(self.applies(m) for m in program.mods):
            return []
        engine = TaintEngine(program.mods, program.callgraph, _WirePolicy(self))
        return engine.run()


register(WireTaintRule())
