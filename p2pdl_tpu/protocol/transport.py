"""Control-plane transports: deterministic in-memory hub and framed TCP.

The reference's transport is inlined raw-socket code (reference
``node/node.py:81-112, 257-263, 289-297``): one fresh TCP connection per
message, 4-byte big-endian length prefix + **pickle** payload — with two
landmines this module deliberately fixes:

- ``connect()`` sends its pickle *without* the length prefix
  (``node/node.py:259``) while the receive path always reads one
  (``node/node.py:99-102``), so every handshake is silently dropped
  (SURVEY §2 #9). Here a single ``send_frame``/``recv_frame`` pair is the
  only wire codec, used by every path.
- pickle deserialization of network input is arbitrary code execution;
  messages here are JSON with base64-encoded byte fields.

Simulation uses ``InMemoryHub``: a synchronous FIFO message pump with
injectable drop/corrupt/delay faults — the deterministic test harness the
reference lacks (SURVEY §5 "failure detection": its only timeout mechanism
is inoperative, ``utils/waiting.py``).
"""

from __future__ import annotations

import base64
import collections
import json
import socket
import struct
import threading
from typing import Callable, Optional

from p2pdl_tpu.protocol.brb import BRBMessage
from p2pdl_tpu.utils import telemetry

Handler = Callable[[int, bytes], None]  # (src_id, data) -> None

_LEN = struct.Struct(">I")
MAX_FRAME = 1 << 30


def send_frame(sock: socket.socket, data: bytes) -> None:
    """Length-prefixed send (reference framing, ``node/node.py:289-296``)."""
    sock.sendall(_LEN.pack(len(data)) + data)


def recv_frame(sock: socket.socket) -> Optional[bytes]:
    """Read one length-prefixed frame; None on EOF/oversize."""
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        return None
    return _recv_exact(sock, length)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(65536, n - len(buf)))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def brb_to_wire(msg: BRBMessage) -> bytes:
    def b64(x):
        return base64.b64encode(x).decode() if x is not None else None

    return json.dumps(
        {
            "kind": msg.kind,
            "sender": msg.sender,
            "seq": msg.seq,
            "from_id": msg.from_id,
            "digest": b64(msg.digest),
            "payload": b64(msg.payload),
            "signature": b64(msg.signature),
        }
    ).encode()


def brb_from_wire(data: bytes) -> Optional[BRBMessage]:
    """Parse a wire message; None (not an exception) on malformed input —
    garbage from the network must not take down the node."""
    try:
        d = json.loads(data)

        def unb64(x):
            return base64.b64decode(x) if x is not None else None

        return BRBMessage(
            kind=str(d["kind"]),
            sender=int(d["sender"]),
            seq=int(d["seq"]),
            from_id=int(d["from_id"]),
            digest=unb64(d["digest"]),
            payload=unb64(d.get("payload")),
            signature=unb64(d.get("signature")),
        )
    except (ValueError, KeyError, TypeError):
        return None


class InMemoryHub:
    """Deterministic synchronous message router with fault injection.

    ``drop(src, dst, data) -> bool`` and ``corrupt(src, dst, data) -> bytes``
    hooks inject network faults; ``pump()`` delivers queued messages FIFO
    until quiescence, so protocol cascades (echo storms) run to completion
    deterministically — no threads, no races.

    Accounting contract: ``messages_sent`` counts send *attempts*;
    ``bytes_sent`` counts only bytes actually enqueued, at their
    post-corruption length (what the wire would carry — a dropped frame
    costs no bytes, a corrupted one costs what arrives). Drops and
    corruptions are tracked separately (``messages_dropped`` /
    ``bytes_dropped`` / ``messages_corrupted``), and ``pump()`` tracks
    the delivered side (``messages_delivered`` / ``bytes_delivered``).
    Every counter mirrors into the telemetry registry under
    ``transport.messages{transport=hub,...}`` / ``transport.bytes{...}``;
    registry series are resolved at construction, so ``telemetry.reset()``
    in tests should precede hub creation.
    """

    def __init__(
        self,
        drop: Optional[Callable[[int, int, bytes], bool]] = None,
        corrupt: Optional[Callable[[int, int, bytes], bytes]] = None,
    ) -> None:
        self._handlers: dict[int, Handler] = {}
        self._queue: collections.deque[tuple[int, int, bytes]] = collections.deque()
        self.drop = drop
        self.corrupt = corrupt
        self.messages_sent = 0
        self.bytes_sent = 0
        self.messages_dropped = 0
        self.bytes_dropped = 0
        self.messages_corrupted = 0
        self.messages_delivered = 0
        self.bytes_delivered = 0
        self._c_sent = telemetry.counter("transport.messages", transport="hub", event="sent")
        self._c_bytes = telemetry.counter("transport.bytes", transport="hub", event="sent")
        self._c_drop = telemetry.counter("transport.messages", transport="hub", event="dropped")
        self._c_bytes_drop = telemetry.counter("transport.bytes", transport="hub", event="dropped")
        self._c_corrupt = telemetry.counter("transport.messages", transport="hub", event="corrupted")
        self._c_deliver = telemetry.counter("transport.messages", transport="hub", event="delivered")
        self._c_bytes_deliver = telemetry.counter("transport.bytes", transport="hub", event="delivered")

    def register(self, peer_id: int, handler: Handler) -> None:
        self._handlers[peer_id] = handler

    def send(self, src: int, dst: int, data: bytes) -> None:
        self.messages_sent += 1
        self._c_sent.inc()
        if self.drop is not None and self.drop(src, dst, data):
            self.messages_dropped += 1
            self.bytes_dropped += len(data)
            self._c_drop.inc()
            self._c_bytes_drop.inc(len(data))
            return
        if self.corrupt is not None:
            corrupted = self.corrupt(src, dst, data)
            if corrupted != data:
                self.messages_corrupted += 1
                self._c_corrupt.inc()
            data = corrupted
        self.bytes_sent += len(data)
        self._c_bytes.inc(len(data))
        self._queue.append((src, dst, data))

    def pump(self, max_messages: int = 1_000_000) -> int:
        """Deliver until quiescent; returns number delivered."""
        delivered = 0
        while self._queue and delivered < max_messages:
            src, dst, data = self._queue.popleft()
            handler = self._handlers.get(dst)
            if handler is not None:
                handler(src, data)
            delivered += 1
            self.messages_delivered += 1
            self.bytes_delivered += len(data)
            self._c_deliver.inc()
            self._c_bytes_deliver.inc(len(data))
        return delivered


class TCPTransport:
    """Framed-TCP transport: one listener thread, fresh connection per send
    (the reference's connection discipline, ``aggregator/aggregation.py:72-77``,
    kept deliberately — control messages are small and rare; the data plane
    never touches TCP)."""

    def __init__(self, my_id: int, host: str, port: int, handler: Handler) -> None:
        self.my_id = my_id
        self.host = host
        self.port = port
        self.handler = handler
        self.peers: dict[int, tuple[str, int]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._sock: Optional[socket.socket] = None
        self._c_sent = telemetry.counter("transport.messages", transport="tcp", event="sent")
        self._c_bytes = telemetry.counter("transport.bytes", transport="tcp", event="sent")
        self._c_fail = telemetry.counter("transport.messages", transport="tcp", event="send_failed")
        self._c_deliver = telemetry.counter("transport.messages", transport="tcp", event="delivered")
        self._c_bytes_deliver = telemetry.counter("transport.bytes", transport="tcp", event="delivered")
        self._c_reject = telemetry.counter("transport.messages", transport="tcp", event="rejected")

    def add_peer(self, peer_id: int, host: str, port: int) -> None:
        self.peers[peer_id] = (host, port)

    def start(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self.port = self._sock.getsockname()[1]  # resolve port 0
        self._sock.listen(64)
        self._sock.settimeout(0.2)
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        with conn:
            frame = recv_frame(conn)
            if frame is None or len(frame) < _LEN.size:
                self._c_reject.inc()  # malformed/oversize/truncated frame
                return
            (src,) = _LEN.unpack(frame[: _LEN.size])
            self._c_deliver.inc()
            self._c_bytes_deliver.inc(len(frame) - _LEN.size)
            self.handler(src, frame[_LEN.size :])

    def send(self, dst: int, data: bytes) -> bool:
        addr = self.peers.get(dst)
        if addr is None:
            self._c_fail.inc()
            return False
        try:
            # Fresh connection per frame: a refused/reset connection is the
            # reconnect-failure signal this counter pair captures.
            with socket.create_connection(addr, timeout=5.0) as s:
                send_frame(s, _LEN.pack(self.my_id) + data)
            self._c_sent.inc()
            self._c_bytes.inc(len(data))
            return True
        except OSError:
            self._c_fail.inc()
            return False

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            self._sock.close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
