"""Control-plane transports: deterministic in-memory hub and framed TCP.

The reference's transport is inlined raw-socket code (reference
``node/node.py:81-112, 257-263, 289-297``): one fresh TCP connection per
message, 4-byte big-endian length prefix + **pickle** payload — with two
landmines this module deliberately fixes:

- ``connect()`` sends its pickle *without* the length prefix
  (``node/node.py:259``) while the receive path always reads one
  (``node/node.py:99-102``), so every handshake is silently dropped
  (SURVEY §2 #9). Here a single ``send_frame``/``recv_frame`` pair is the
  only wire codec, used by every path.
- pickle deserialization of network input is arbitrary code execution;
  messages here are JSON with base64-encoded byte fields.

Simulation uses ``InMemoryHub``: a synchronous FIFO message pump with
injectable drop/corrupt/delay faults — the deterministic test harness the
reference lacks (SURVEY §5 "failure detection": its only timeout mechanism
is inoperative, ``utils/waiting.py``).
"""

from __future__ import annotations

import base64
import collections
import hashlib
import json
import socket
import struct
import threading
import time
from typing import Callable, Optional

from p2pdl_tpu.protocol.brb import (
    _SIGNING_MAGIC_CODES,
    BRBBatch,
    BRBMessage,
    TraceTag,
)
from p2pdl_tpu.utils import telemetry

Handler = Callable[[int, bytes], None]  # (src_id, data) -> None

# Control wire format version. v1: one JSON object per BRBMessage (no
# version field). v2 adds the batched frame (`{"v": 2, "type": "batch"}`)
# carrying a peer's echo/ready votes for all of a round's concurrent BRB
# instances under one signature. v1 messages remain valid in v2 — SENDs
# always travel per-message — and a v1-only receiver ignores batch frames
# (they lack the "sender"/"digest" keys, so brb_from_wire returns None).
# v3 adds the optional causal-trace header: a "trace" key of
# [peer, local_seq, lamport] on both frame shapes. Backward compatible in
# both directions — older receivers ignore unknown JSON keys, and a
# traceless frame parses here as trace=None (signing stays BRB2 for it).
# The version number is the BRB3 signing-magic code: one source of truth
# for "which header revision is current".
CONTROL_WIRE_VERSION = _SIGNING_MAGIC_CODES[b"BRB3"]

_LEN = struct.Struct(">I")
MAX_FRAME = 1 << 30


def send_frame(sock: socket.socket, data: bytes) -> None:
    """Length-prefixed send (reference framing, ``node/node.py:289-296``)."""
    sock.sendall(_LEN.pack(len(data)) + data)


def recv_frame(sock: socket.socket) -> Optional[bytes]:
    """Read one length-prefixed frame; None on EOF/oversize.

    An oversize length prefix means the stream is unframeable garbage (or
    hostile): the bytes that follow can't be skipped reliably, so the
    socket is *closed* rather than left desynchronized mid-stream where
    the next read would parse payload bytes as a header. Counted under the
    existing rejected series.
    """
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        telemetry.counter(
            "transport.messages", transport="tcp", event="rejected"
        ).inc()
        sock.close()
        return None
    return _recv_exact(sock, length)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(65536, n - len(buf)))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _trace_to_wire(trace: Optional[TraceTag]):
    return None if trace is None else [trace.peer, trace.lseq, trace.lamport]


def _trace_from_wire(raw) -> Optional[TraceTag]:
    if raw is None:
        return None
    peer, lseq, lamport = raw
    return TraceTag(int(peer), int(lseq), int(lamport))


def brb_to_wire(msg: BRBMessage) -> bytes:
    def b64(x):
        return base64.b64encode(x).decode() if x is not None else None

    return json.dumps(
        {
            "kind": msg.kind,
            "sender": msg.sender,
            "seq": msg.seq,
            "from_id": msg.from_id,
            "digest": b64(msg.digest),
            "payload": b64(msg.payload),
            "signature": b64(msg.signature),
            "trace": _trace_to_wire(msg.trace),
        }
    ).encode()


def brb_from_wire(data: bytes) -> Optional[BRBMessage]:
    """Parse a wire message; None (not an exception) on malformed input —
    garbage from the network must not take down the node."""
    try:
        d = json.loads(data)

        def unb64(x):
            return base64.b64decode(x) if x is not None else None

        return BRBMessage(
            kind=str(d["kind"]),
            sender=int(d["sender"]),
            seq=int(d["seq"]),
            from_id=int(d["from_id"]),
            digest=unb64(d["digest"]),
            payload=unb64(d.get("payload")),
            signature=unb64(d.get("signature")),
            trace=_trace_from_wire(d.get("trace")),
        )
    except (ValueError, KeyError, TypeError):
        return None


def batch_to_wire(batch: BRBBatch) -> bytes:
    def b64(x):
        return base64.b64encode(x).decode() if x is not None else None

    return json.dumps(
        {
            "v": CONTROL_WIRE_VERSION,
            "type": "batch",
            "kind": batch.kind,
            "from_id": batch.from_id,
            "seq": batch.seq,
            "items": [[s, b64(d)] for s, d in batch.items],
            "signature": b64(batch.signature),
            "trace": _trace_to_wire(batch.trace),
        }
    ).encode()


def control_from_wire(data: bytes):
    """Parse either control frame shape: a v2 ``BRBBatch`` or a v1
    ``BRBMessage``. None (not an exception) on malformed input."""
    try:
        d = json.loads(data)
        if not isinstance(d, dict) or d.get("type") != "batch":
            return brb_from_wire(data)
        sig = d.get("signature")
        return BRBBatch(
            kind=str(d["kind"]),
            from_id=int(d["from_id"]),
            seq=int(d["seq"]),
            items=tuple(
                (int(s), base64.b64decode(dg)) for s, dg in d["items"]
            ),
            signature=base64.b64decode(sig) if sig is not None else None,
            trace=_trace_from_wire(d.get("trace")),
        )
    except (ValueError, KeyError, TypeError):
        return None


class InMemoryHub:
    """Deterministic synchronous message router with fault injection.

    Fault hooks, all ``(src, dst, data)``-keyed and optional:

    - ``drop(...) -> bool``: message vanishes.
    - ``corrupt(...) -> bytes``: payload replaced (bit flips).
    - ``delay(...) -> int``: ticks to hold the message in the delay queue
      (0 = deliver normally). A "tick" is one quiescence point: delayed
      messages are promoted only once the main queue drains, so a delay
      reorders the message past the current protocol cascade while
      ``pump()`` still runs to *true* quiescence — ``while hub.pump()``
      loops cannot hang on a delayed message, and replay stays exact.
    - ``duplicate(...) -> bool``: enqueue the message twice.
    - ``reorder(...) -> bool``: the message jumps ahead of the most
      recently queued one.

    ``set_partition(groups)`` cuts messages between different groups
    (peers absent from every group are unrestricted) until
    ``clear_partition()``.

    Accounting contract: ``messages_sent`` counts send *attempts*;
    ``bytes_sent`` counts only bytes actually enqueued, at their
    post-corruption length and once per copy (what the wire would carry —
    a dropped or partition-cut frame costs no bytes, a corrupted one costs
    what arrives, a duplicated one costs double). Drops, partition cuts,
    and corruptions are tracked separately (``messages_dropped`` /
    ``bytes_dropped`` / ``messages_partitioned`` / ``messages_corrupted``),
    and ``pump()`` tracks the delivered side (``messages_delivered`` /
    ``bytes_delivered``). Every counter mirrors into the telemetry
    registry under ``transport.messages{transport=hub,...}`` /
    ``transport.bytes{...}``; registry series are resolved at
    construction, so ``telemetry.reset()`` in tests should precede hub
    creation.
    """

    def __init__(
        self,
        drop: Optional[Callable[[int, int, bytes], bool]] = None,
        corrupt: Optional[Callable[[int, int, bytes], bytes]] = None,
        delay: Optional[Callable[[int, int, bytes], int]] = None,
        duplicate: Optional[Callable[[int, int, bytes], bool]] = None,
        reorder: Optional[Callable[[int, int, bytes], bool]] = None,
    ) -> None:
        self._handlers: dict[int, Handler] = {}
        self._queue: collections.deque[tuple[int, int, bytes]] = collections.deque()
        # (due_tick, seq, src, dst, data); seq keeps promotion FIFO-stable.
        self._delayed: list[tuple[int, int, int, int, bytes]] = []
        self._seq = 0
        self._tick = 0
        self._partition: Optional[tuple[frozenset[int], ...]] = None
        self.drop = drop
        self.corrupt = corrupt
        self.delay = delay
        self.duplicate = duplicate
        self.reorder = reorder
        self.messages_sent = 0
        self.bytes_sent = 0
        self.messages_dropped = 0
        self.bytes_dropped = 0
        self.messages_partitioned = 0
        self.messages_corrupted = 0
        self.messages_delayed = 0
        self.messages_duplicated = 0
        self.messages_reordered = 0
        self.messages_delivered = 0
        self.bytes_delivered = 0
        self.pump_capped = 0
        self._c_sent = telemetry.counter("transport.messages", transport="hub", event="sent")
        self._c_bytes = telemetry.counter("transport.bytes", transport="hub", event="sent")
        self._c_drop = telemetry.counter("transport.messages", transport="hub", event="dropped")
        self._c_bytes_drop = telemetry.counter("transport.bytes", transport="hub", event="dropped")
        self._c_partition = telemetry.counter("transport.messages", transport="hub", event="partitioned")
        self._c_corrupt = telemetry.counter("transport.messages", transport="hub", event="corrupted")
        self._c_delay = telemetry.counter("transport.messages", transport="hub", event="delayed")
        self._c_dup = telemetry.counter("transport.messages", transport="hub", event="duplicated")
        self._c_reorder = telemetry.counter("transport.messages", transport="hub", event="reordered")
        self._c_deliver = telemetry.counter("transport.messages", transport="hub", event="delivered")
        self._c_bytes_deliver = telemetry.counter("transport.bytes", transport="hub", event="delivered")
        self._c_capped = telemetry.counter("transport.pump_capped", transport="hub")

    def register(self, peer_id: int, handler: Handler) -> None:
        self._handlers[peer_id] = handler

    def set_partition(self, groups) -> None:
        self._partition = tuple(frozenset(g) for g in groups)

    def clear_partition(self) -> None:
        self._partition = None

    def _cut(self, src: int, dst: int) -> bool:
        if self._partition is None:
            return False
        src_g = dst_g = None
        for i, g in enumerate(self._partition):
            if src in g:
                src_g = i
            if dst in g:
                dst_g = i
        return src_g is not None and dst_g is not None and src_g != dst_g

    def send(self, src: int, dst: int, data: bytes) -> None:
        self.messages_sent += 1
        self._c_sent.inc()
        if self.drop is not None and self.drop(src, dst, data):
            self.messages_dropped += 1
            self.bytes_dropped += len(data)
            self._c_drop.inc()
            self._c_bytes_drop.inc(len(data))
            return
        if self._cut(src, dst):
            self.messages_partitioned += 1
            self._c_partition.inc()
            return
        if self.corrupt is not None:
            corrupted = self.corrupt(src, dst, data)
            if corrupted != data:
                self.messages_corrupted += 1
                self._c_corrupt.inc()
            data = corrupted
        copies = 1
        if self.duplicate is not None and self.duplicate(src, dst, data):
            copies = 2
            self.messages_duplicated += 1
            self._c_dup.inc()
        for _ in range(copies):
            self.bytes_sent += len(data)
            self._c_bytes.inc(len(data))
            ticks = self.delay(src, dst, data) if self.delay is not None else 0
            if ticks > 0:
                self._seq += 1
                self._delayed.append((self._tick + ticks, self._seq, src, dst, data))
                self.messages_delayed += 1
                self._c_delay.inc()
            elif (
                self.reorder is not None
                and self._queue
                and self.reorder(src, dst, data)
            ):
                self._queue.insert(len(self._queue) - 1, (src, dst, data))
                self.messages_reordered += 1
                self._c_reorder.inc()
            else:
                self._queue.append((src, dst, data))

    def pending(self) -> int:
        """Messages not yet delivered: queued + held in the delay queue."""
        return len(self._queue) + len(self._delayed)

    def _promote_due(self) -> None:
        """Advance the clock to the earliest due delayed message and move
        everything due onto the main queue (oldest first)."""
        self._tick = min(d[0] for d in self._delayed)
        due = sorted(d for d in self._delayed if d[0] <= self._tick)
        self._delayed = [d for d in self._delayed if d[0] > self._tick]
        for _, _, src, dst, data in due:
            self._queue.append((src, dst, data))

    def pump(self, max_messages: int = 1_000_000) -> int:
        """Deliver until quiescent; returns number delivered.

        Quiescence includes the delay queue: when the main queue drains,
        due delayed messages are promoted (ticking the clock forward) and
        delivery continues. A capped exit with work still pending is *not*
        quiescence — it bumps ``pump_capped`` and a telemetry warning
        counter so a too-small ``max_messages`` can't silently truncate a
        protocol cascade.
        """
        delivered = 0
        while delivered < max_messages:
            if not self._queue:
                if not self._delayed:
                    break
                self._promote_due()
                continue
            src, dst, data = self._queue.popleft()
            handler = self._handlers.get(dst)
            if handler is not None:
                handler(src, data)
            delivered += 1
            self.messages_delivered += 1
            self.bytes_delivered += len(data)
            self._c_deliver.inc()
            self._c_bytes_deliver.inc(len(data))
        if delivered >= max_messages and self.pending():
            self.pump_capped += 1
            self._c_capped.inc()
        return delivered


class TCPTransport:
    """Framed-TCP transport: one listener thread, fresh connection per send
    (the reference's connection discipline, ``aggregator/aggregation.py:72-77``,
    kept deliberately — control messages are small and rare; the data plane
    never touches TCP)."""

    def __init__(
        self,
        my_id: int,
        host: str,
        port: int,
        handler: Handler,
        send_retries: int = 2,
        send_backoff_s: float = 0.05,
        send_timeout_s: float = 5.0,
    ) -> None:
        self.my_id = my_id
        self.host = host
        self.port = port
        self.handler = handler
        self.send_retries = send_retries
        self.send_backoff_s = send_backoff_s
        self.send_timeout_s = send_timeout_s
        self.peers: dict[int, tuple[str, int]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._sock: Optional[socket.socket] = None
        # Live connection threads, tracked so stop() can join them: the old
        # fire-and-forget daemon threads could outlive stop() mid-recv.
        self._conn_lock = threading.Lock()
        self._conns: list[tuple[threading.Thread, socket.socket]] = []
        # Per-peer cumulative payload bytes (frame minus the src header),
        # written under _conn_lock — stats-dict material, never telemetry
        # labels (peer ids are unbounded identity values).
        self._tx_bytes: dict[int, int] = {}
        self._rx_bytes: dict[int, int] = {}
        self._sent = 0
        self._delivered = 0
        self._send_failed = 0
        self._c_sent = telemetry.counter("transport.messages", transport="tcp", event="sent")
        self._c_bytes = telemetry.counter("transport.bytes", transport="tcp", event="sent")
        self._c_fail = telemetry.counter("transport.messages", transport="tcp", event="send_failed")
        self._c_deliver = telemetry.counter("transport.messages", transport="tcp", event="delivered")
        self._c_bytes_deliver = telemetry.counter("transport.bytes", transport="tcp", event="delivered")
        self._c_reject = telemetry.counter("transport.messages", transport="tcp", event="rejected")
        self._c_retry = telemetry.counter("transport.messages", transport="tcp", event="retry")

    def add_peer(self, peer_id: int, host: str, port: int) -> None:
        with self._conn_lock:
            self.peers[peer_id] = (host, port)

    def start(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self.port = self._sock.getsockname()[1]  # resolve port 0
        self._sock.listen(64)
        self._sock.settimeout(0.2)
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(
                target=self._serve, args=(conn,),
                name=f"tcp-serve-{self.my_id}", daemon=True,
            )
            with self._conn_lock:
                self._conns = [
                    (th, c) for th, c in self._conns if th.is_alive()
                ]
                self._conns.append((t, conn))
            t.start()

    def _serve(self, conn: socket.socket) -> None:
        with conn:
            try:
                frame = recv_frame(conn)
            except OSError:
                return  # connection torn down under us (e.g. stop())
            if frame is None or len(frame) < _LEN.size:
                if conn.fileno() != -1:  # oversize already counted+closed in recv_frame
                    self._c_reject.inc()  # malformed/truncated frame
                return
            (src,) = _LEN.unpack(frame[: _LEN.size])
            with self._conn_lock:
                self._delivered += 1
                self._rx_bytes[src] = (
                    self._rx_bytes.get(src, 0) + len(frame) - _LEN.size
                )
            self._c_deliver.inc()
            self._c_bytes_deliver.inc(len(frame) - _LEN.size)
            self.handler(src, frame[_LEN.size :])

    def send(self, dst: int, data: bytes) -> bool:
        """Send one frame with bounded retries.

        Fresh connection per frame (the reference's discipline); each
        attempt gets its own ``send_timeout_s``, and failed attempts back
        off exponentially with deterministic jitter (keyed on route +
        attempt, not a global RNG) before retrying — transient refusals
        during peer restarts no longer fail the round outright. The final
        failure still returns False and counts ``event=send_failed``;
        intermediate attempts count ``event=retry``.
        """
        addr = self.peers.get(dst)
        if addr is None:
            self._c_fail.inc()
            return False
        backoff = self.send_backoff_s
        for attempt in range(self.send_retries + 1):
            try:
                with socket.create_connection(addr, timeout=self.send_timeout_s) as s:
                    send_frame(s, _LEN.pack(self.my_id) + data)
                with self._conn_lock:
                    self._sent += 1
                    self._tx_bytes[dst] = self._tx_bytes.get(dst, 0) + len(data)
                self._c_sent.inc()
                self._c_bytes.inc(len(data))
                return True
            except OSError:
                if attempt == self.send_retries:
                    break
                self._c_retry.inc()
                h = hashlib.sha256(f"{self.my_id}|{dst}|{attempt}".encode()).digest()
                time.sleep(backoff * (1.0 + h[0] / 255.0 * 0.5))
                backoff *= 2.0
        with self._conn_lock:
            self._send_failed += 1
        self._c_fail.inc()
        return False

    def transport_stats(self) -> dict:
        """JSON-ready snapshot mirroring ``AsyncTCPTransport.transport_stats``
        (the subset this one-shot transport can observe). Per-peer byte
        totals live here — a stats dict, never telemetry labels."""
        with self._conn_lock:
            return {
                "transport": "tcp",
                "sent": self._sent,
                "delivered": self._delivered,
                "send_failed": self._send_failed,
                "tx_bytes": sum(self._tx_bytes.values()),
                "rx_bytes": sum(self._rx_bytes.values()),
                "tx_bytes_by_peer": {
                    str(p): b for p, b in sorted(self._tx_bytes.items())
                },
                "rx_bytes_by_peer": {
                    str(p): b for p, b in sorted(self._rx_bytes.items())
                },
            }

    def stop(self) -> None:
        """Idempotent shutdown: close the listener, join the accept loop,
        then force-close and join every live connection thread (bounded) —
        no thread outlives stop()."""
        self._stop.set()
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2.0)
        with self._conn_lock:
            conns, self._conns = list(self._conns), []
        deadline = time.monotonic() + 2.0
        for _, conn in conns:
            try:
                # shutdown() (not just close()) is what actually unblocks a
                # thread parked in recv mid-frame.
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        for t, _ in conns:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
