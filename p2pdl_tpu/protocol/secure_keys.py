"""ECDH pairwise key agreement for secure aggregation.

Round 3's secure aggregation derived every pairwise mask from ONE shared
experiment key (``fold_in(PRNGKey(cfg.seed), ...)``), which the
aggregating driver — the party masks are supposed to hide updates from —
could trivially re-derive. This module replaces that with real key
agreement over the curve the PKI already uses (reference
``utils/crypto.py:42-48`` is the per-node P-256 key infrastructure this
piggybacks on; the reference itself has no masking to key):

- every peer holds an ECDH P-256 keypair (distinct from its ECDSA signing
  key — signing and agreement keys are never reused for each other);
- the pair seed for peers ``(i, j)`` is ``HKDF-SHA256(ECDH(priv_i, pub_j))``
  with the sorted pair ids in the HKDF ``info`` — symmetric (both
  endpoints derive the same 64-bit seed), and underivable from the public
  directory alone (deriving it without ``priv_i`` or ``priv_j`` is ECDLP);
- seeds feed the on-device PRF masks as a ``[P, P, 2]`` uint32 matrix
  (``ops/secure_agg.pairwise_mask``'s ``pair_seeds`` path);
- each peer Shamir-shares its ECDH private scalar among the peer set
  (``protocol/shamir``), so a threshold of survivors can reconstruct a
  DROPPED peer's seeds and the aggregate can cancel orphaned masks
  (Bonawitz et al. CCS 2017 §4 dropout recovery).

Simulation note (honest scope): the SPMD driver simulates every peer, so
it necessarily holds all private scalars in-process; what this module
establishes is the *protocol* property — an observer of public state
(the key directory + masked updates) cannot derive any mask, and the
dropout path exercises exactly the share-collection flow a distributed
deployment would run. ``seed=None`` uses OS entropy; the driver passes
``cfg.seed`` so experiments stay bit-for-bit reproducible/resumable.

Disclosure scope (honest delta vs the full Bonawitz protocol): keys here
are PER-EXPERIMENT, while Bonawitz's are per-execution (fresh DH every
aggregation round). Reconstructing a dropped peer's scalar therefore
discloses its pair seeds for every round UP TO the drop — an aggregator
that logged its earlier masked updates can unmask them retroactively.
What bounds the damage going FORWARD is :meth:`rotate`: the round driver
re-keys every peer whose scalar became reconstructible (BRB gate-out
under the gated pipeline), so a peer that later re-joins masks under a
fresh scalar the old shares say nothing about. For the full
per-execution semantics — reconstruction can ever disclose exactly ONE
round — set ``cfg.secure_agg_rekey="round"``: the driver re-keys every
round (fresh scalars + fresh shares), restricted to the BRB-gated path,
whose seed matrix is a runtime argument. Under the full Bonawitz mask
graph that costs O(P^2/2) host ECDH per round (config-capped at 256
peers); under the Bell k-ring (``secure_agg_neighbors=k``) only the
round's ring pairs ever mask, so the driver rotates just the round's
trainers and derives O(T*k) pair seeds (:meth:`seed_matrix_ring`), with
Shamir shares held by each peer's 2k-neighbor COMMITTEE on the static id
ring (:func:`ring_committees`) instead of the whole peer set — per-round
freshness at 1024+ peers.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import secrets as _secrets

import numpy as np

try:
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF

    HAVE_CRYPTOGRAPHY = True
except ImportError:  # pragma: no cover - exercised only on bare images
    HAVE_CRYPTOGRAPHY = False

from p2pdl_tpu.protocol import shamir

_INFO = b"p2pdl-tpu secure-agg v1"

# ---- dependency gate: integer-DH fallback ----------------------------
# Without ``cryptography`` the keyring swaps P-256 ECDH for classic
# finite-field Diffie-Hellman over the RFC 3526 group-14 (2048-bit MODP)
# prime, generator 2, and the HKDF for a single hashlib HMAC
# extract-and-expand. Commutativity (g^ab == g^ba mod p) gives the same
# symmetric pair-seed property the protocol pins; scalars stay in
# [1, P256_ORDER) so Shamir sharing/reconstruction over the P-256 order
# field is unchanged. Simulation-grade only (no constant-time arithmetic).

_DH_PRIME = int(
    "FFFFFFFFFFFFFFFFC90FDAA22168C234C4C6628B80DC1CD129024E088A67CC74"
    "020BBEA63B139B22514A08798E3404DDEF9519B3CD3A431B302B0A6DF25F1437"
    "4FE1356D6D51C245E485B576625E7EC6F44C42E9A637ED6B0BFF5CB6F406B7ED"
    "EE386BFB5A899FA5AE9F24117C4B1FE649286651ECE45B3DC2007CB8A163BF05"
    "98DA48361C55D39A69163FA8FD24CF5F83655D23DCA3AD961C62F356208552BB"
    "9ED529077096966D670C354E4ABC9804F1746C08CA18217C32905E462E36CE3B"
    "E39E772C180E86039B2783A2EC07A28FB5C55DF06F4C52C9DE2BCBF695581718"
    "3995497CEA956AE515D2261898FA051015728E5A8AACAA68FFFFFFFFFFFFFFFF",
    16,
)
_DH_GENERATOR = 2
_DH_BYTES = (_DH_PRIME.bit_length() + 7) // 8


class _DhPrivateNumbers:
    __slots__ = ("private_value",)

    def __init__(self, private_value: int) -> None:
        self.private_value = private_value


class _DhPublicKey:
    __slots__ = ("y",)

    def __init__(self, y: int) -> None:
        self.y = y


class _DhPrivateKey:
    """Fallback agreement key mirroring the ``cryptography`` private-key
    surface this module touches (``public_key``, ``private_numbers``)."""

    __slots__ = ("x", "_pub")

    def __init__(self, x: int) -> None:
        self.x = x
        self._pub = _DhPublicKey(pow(_DH_GENERATOR, x, _DH_PRIME))

    def public_key(self) -> _DhPublicKey:
        return self._pub

    def private_numbers(self) -> _DhPrivateNumbers:
        return _DhPrivateNumbers(self.x)


def generate_agreement_key():
    """Fresh agreement private key (P-256, or fallback DH) from OS entropy."""
    if HAVE_CRYPTOGRAPHY:
        return ec.generate_private_key(ec.SECP256R1())
    # p2plint: disable=determinism-entropy -- sanctioned: agreement-key generation; keys are identity, not replayed state
    return _DhPrivateKey(_secrets.randbelow(shamir.P256_ORDER - 1) + 1)


def derive_agreement_key(scalar: int):
    """Agreement private key from an explicit scalar in [1, P256_ORDER) —
    the reconstruction/reproducible-simulation path."""
    if HAVE_CRYPTOGRAPHY:
        return ec.derive_private_key(scalar, ec.SECP256R1())
    return _DhPrivateKey(scalar)


def _exchange(priv, pub) -> bytes:
    if HAVE_CRYPTOGRAPHY:
        return priv.exchange(ec.ECDH(), pub)
    return pow(pub.y, priv.x, _DH_PRIME).to_bytes(_DH_BYTES, "big")


def _kdf8(shared: bytes, info: bytes) -> bytes:
    """8 bytes of HKDF-SHA256(shared, info) — library or hashlib-only."""
    if HAVE_CRYPTOGRAPHY:
        return HKDF(
            algorithm=hashes.SHA256(), length=8, salt=None, info=info
        ).derive(shared)
    prk = _hmac.new(b"\x00" * 32, shared, hashlib.sha256).digest()
    return _hmac.new(prk, info + b"\x01", hashlib.sha256).digest()[:8]


def ring_committees(num_peers: int, k: int) -> list[list[int]]:
    """Per-peer Shamir-share holder committees on the STATIC peer-id ring:
    peer ``i``'s committee is its 2k ring neighbors ``(i +- d) mod P``,
    ``d = 1..k`` (Bell et al. CCS 2020's neighbor-held shares — the same
    trust radius the k-ring mask graph already assumes). The id ring is
    deliberately NOT the per-round mask ring (rank among sampled
    trainers): committees must be stable across rounds so holders keep
    shares for peers that were not sampled with them."""
    out = []
    for i in range(num_peers):
        seen = []
        for d in range(1, k + 1):
            for j in ((i + d) % num_peers, (i - d) % num_peers):
                if j != i and j not in seen:
                    seen.append(j)
        out.append(seen)
    return out


def ring_pairs(trainer_ids, neighbors: int) -> set[tuple[int, int]]:
    """The set of (lo, hi) global-id pairs the round's mask graph uses —
    the HOST mirror of ``ops/secure_agg._partner_ids`` (ring by RANK among
    the live entries of the pre-gate trainer vector, positional order,
    wraparound when ``n_live <= neighbors``). The per-round rekey derives
    ECDH seeds for exactly these pairs; the two MUST agree or a used pair
    would mask under an unfilled (zero) seed — cancellation would still
    hold (the matrix stays symmetric) but the mask would be derivable
    from public state, silently voiding the privacy property."""
    ids = [int(t) for t in trainer_ids]
    live = [t for t in ids if t >= 0]  # positional order, like _partner_ids
    n = len(live)
    pairs: set[tuple[int, int]] = set()
    if n <= 1:
        return pairs
    if not (neighbors and neighbors < len(ids) - 1):
        for a in range(n):
            for b in range(a + 1, n):
                i, j = live[a], live[b]
                if i != j:
                    pairs.add((min(i, j), max(i, j)))
        return pairs
    half = neighbors // 2
    for rank, i in enumerate(live):
        for d in range(1, half + 1):
            for j in (live[(rank + d) % n], live[(rank - d) % n]):
                if j != i:
                    pairs.add((min(i, j), max(i, j)))
    return pairs


def _derive_scalar(seed: int, peer_id: int, generation: int = 0) -> int:
    """Deterministic private scalar in [1, order) from (seed, peer_id,
    key generation — bumped by :meth:`SecureAggKeyring.rotate`).

    SHA-512 output reduced mod (order - 1) + 1: the 512-bit intermediate
    makes the mod bias negligible (~2^-256). Used only for reproducible
    simulation; real deployments pass ``seed=None`` for OS entropy.
    """
    h = hashlib.sha512(
        _INFO + b"|keygen|%d|%d|%d" % (seed, peer_id, generation)
    )
    return int.from_bytes(h.digest(), "big") % (shamir.P256_ORDER - 1) + 1


class SecureAggKeyring:
    """Per-peer ECDH keypairs + pairwise seed derivation + Shamir shares."""

    def __init__(self, num_peers: int, seed: int | None = None, share_threshold: int | None = None):
        self.num_peers = num_peers
        # Honest majority by default: reconstruction needs floor(P/2)+1
        # shares, so no minority coalition can unmask a live peer by
        # pretending it dropped.
        self.share_threshold = share_threshold or (num_peers // 2 + 1)
        self._seed = seed
        self._generation = [0] * num_peers
        if seed is None:
            self._privs = [generate_agreement_key() for _ in range(num_peers)]
        else:
            self._privs = [
                derive_agreement_key(_derive_scalar(seed, i))
                for i in range(num_peers)
            ]
        # The public directory — what a deployment would publish through
        # the KeyServer. Everything an outside observer sees.
        self.public_keys = [k.public_key() for k in self._privs]
        self._shares: list[list[tuple[int, int]]] | None = None
        # committees[i] = ordered holder ids for peer i's shares (None =
        # every peer holds a share, the full-Bonawitz default).
        self._committees: list[list[int]] | None = None

    # -- pairwise seeds -------------------------------------------------
    @staticmethod
    def pair_seed_from(priv, pub, i: int, j: int) -> tuple[int, int]:
        """The (hi, lo) uint32 seed halves for pair (i, j), computed as one
        endpoint would: own private key + the other's public key. Symmetric
        in (i, j) because ECDH is and the HKDF info sorts the ids."""
        lo_id, hi_id = sorted((i, j))
        okm = _kdf8(
            _exchange(priv, pub), _INFO + b"|pair|%d|%d" % (lo_id, hi_id)
        )
        return int.from_bytes(okm[:4], "big"), int.from_bytes(okm[4:], "big")

    def pair_seed(self, i: int, j: int) -> tuple[int, int]:
        return self.pair_seed_from(self._privs[i], self.public_keys[j], i, j)

    def seed_matrix(self) -> np.ndarray:
        """``[P, P, 2]`` uint32: entry ``[i, j]`` is pair (i, j)'s PRF seed
        halves; symmetric; the diagonal is zeros (self-pairs are inert —
        ``sign(0) = 0`` in the mask sum).

        Cost: O(P^2 / 2) ECDH exchanges at ~125us each — ~0.7s at P=128,
        ~1min at P=1024, ONCE per experiment (in deployment each peer does
        its own P exchanges in parallel; the quadratic wall-clock is a
        simulation artifact of one host playing every peer)."""
        p = self.num_peers
        mat = np.zeros((p, p, 2), np.uint32)
        for i in range(p):
            for j in range(i + 1, p):
                hi, lo = self.pair_seed(i, j)
                mat[i, j] = mat[j, i] = (hi, lo)
        return mat

    def seed_matrix_ring(self, trainer_ids, neighbors: int) -> np.ndarray:
        """``[P, P, 2]`` uint32 seed matrix filled ONLY at the pairs this
        round's k-ring mask graph uses (:func:`ring_pairs` over the
        pre-gate trainer vector) — O(T x k) ECDH instead of O(P^2/2), the
        per-round rekey cost that makes ``secure_agg_rekey="round"``
        feasible at 1024+ peers. Unused entries stay zero; they are never
        read by the round (the pairing mirror guarantees it)."""
        mat = np.zeros((self.num_peers, self.num_peers, 2), np.uint32)
        for i, j in ring_pairs(trainer_ids, neighbors):
            hi, lo = self.pair_seed(i, j)
            mat[i, j] = mat[j, i] = (hi, lo)
        return mat

    def rotate(
        self,
        peer_id: int,
        mat: np.ndarray | None = None,
        rng=None,
        generation: int | None = None,
    ) -> None:
        """Re-key ``peer_id`` after its scalar became reconstructible (it
        was gated out of a round where recovery could have run): fresh
        keypair, fresh Shamir shares (if distributed), and — when ``mat``
        is given — an in-place O(P) refresh of its seed-matrix row/column.
        Old shares say nothing about the new scalar, so a re-joining peer
        masks with secrecy restored from this round forward.

        ``generation``: explicit key-schedule position. Per-round rekey
        passes the absolute round index so a checkpoint-resumed experiment
        re-derives the SAME per-round scalars as the uninterrupted run
        (an in-memory counter would replay early generations after resume,
        disclosing two rounds under one scalar). Default: bump by one
        (the post-exclusion rotation path, where only freshness matters)."""
        if generation is not None:
            self._generation[peer_id] = generation
        else:
            self._generation[peer_id] += 1
        if self._seed is None:
            priv = generate_agreement_key()
        else:
            priv = derive_agreement_key(
                _derive_scalar(self._seed, peer_id, self._generation[peer_id])
            )
        self._privs[peer_id] = priv
        self.public_keys[peer_id] = priv.public_key()
        if self._shares is not None:
            self._shares[peer_id] = self._split_for(peer_id, rng=rng)
        if mat is not None:
            for j in range(self.num_peers):
                if j == peer_id:
                    continue
                mat[peer_id, j] = mat[j, peer_id] = self.pair_seed(peer_id, j)

    # -- dropout recovery ----------------------------------------------
    def _split_for(self, owner: int, rng=None) -> list[tuple[int, int]]:
        secret = self._privs[owner].private_numbers().private_value
        if self._committees is None:
            return shamir.split_secret(secret, self.num_peers, self.share_threshold, rng=rng)
        committee = self._committees[owner]
        return shamir.split_secret(
            secret, len(committee), self.threshold_for(owner), rng=rng
        )

    def threshold_for(self, owner: int) -> int:
        """Shares needed to reconstruct ``owner``'s scalar: the global
        honest-majority threshold, or a committee majority when shares are
        committee-held (k+1 of the 2k ring neighbors at committee size 2k
        — no k-coalition can unmask, the same radius the k-ring mask graph
        already trusts)."""
        if self._committees is None:
            return self.share_threshold
        return len(self._committees[owner]) // 2 + 1

    @property
    def shares_distributed(self) -> bool:
        """Whether :meth:`distribute_shares` has run — i.e. dropout
        recovery (:meth:`reconstruct_seeds_for_dropped`) is available."""
        return self._shares is not None

    def distribute_shares(self, rng=None, committees: list[list[int]] | None = None) -> None:
        """Shamir-share every peer's private scalar — among the full peer
        set by default (share ``x = h + 1`` held by peer ``h``), or among
        per-peer ``committees`` (:func:`ring_committees`; share ``x = c + 1``
        held by the committee's c-th member). Committee sharing is what
        keeps per-round rekeying O(P x k^2) field ops instead of O(P^2 x t)
        at scale. In deployment each share travels to its holder over the
        authenticated transport."""
        self._committees = committees
        self._shares = [self._split_for(o, rng=rng) for o in range(self.num_peers)]

    def share_of(self, owner: int, holder: int) -> tuple[int, int]:
        """The share of ``owner``'s scalar held by peer ``holder``."""
        if self._shares is None:
            raise RuntimeError("distribute_shares() has not run")
        if self._committees is None:
            return self._shares[owner][holder]
        committee = self._committees[owner]
        if holder not in committee:
            raise ValueError(
                f"peer {holder} holds no share of {owner} "
                f"(committee: {committee})"
            )
        return self._shares[owner][committee.index(holder)]

    def reconstruct_seeds_for_dropped(
        self, dropped: int, holder_ids: list[int]
    ) -> np.ndarray:
        """The dropout-recovery flow: collect ``holder_ids``' shares of the
        dropped peer's scalar, reconstruct it, and re-derive the dropped
        peer's seed row ``[P, 2]`` from the PUBLIC directory — exactly what
        the aggregator needs to cancel orphaned masks. Raises if fewer than
        ``share_threshold`` holders respond."""
        if self._shares is None:
            raise RuntimeError("distribute_shares() has not run")
        holders = set(holder_ids)
        if self._committees is not None:
            holders &= set(self._committees[dropped])
        need = self.threshold_for(dropped)
        if len(holders) < need:
            raise ValueError(
                f"dropout recovery needs {need} shares, got {len(holders)}"
            )
        shares = [self.share_of(dropped, h) for h in holders]
        scalar = shamir.reconstruct_secret(shares)
        priv = derive_agreement_key(scalar)
        row = np.zeros((self.num_peers, 2), np.uint32)
        for j in range(self.num_peers):
            if j == dropped:
                continue
            row[j] = self.pair_seed_from(priv, self.public_keys[j], dropped, j)
        return row
