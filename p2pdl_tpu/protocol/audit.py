"""Online/offline protocol conformance auditor over flight event streams.

The BRB skeleton's correctness claims — agreement, quorum arithmetic,
digest lineage — are *cross-peer* properties: no single peer's counters can
certify them. This module consumes the flight recorder's structured events
(live, per round, in the driver; or offline over N JSONL dumps / ``/flight``
endpoints merged by causal order) and re-checks the safety invariants the
protocol is supposed to enforce:

- ``conflicting_deliver``: at most one delivered digest per ``(sender,
  seq)`` across all peers (BRB agreement).
- ``forged_quorum``: every deliver carries ``votes >= quorum``, its quorum
  is at least ``2f + 1`` for the instance's declared fault budget, and the
  recorded READY votes actually reach that quorum when the vote stream is
  present (no quorum claimed into existence).
- ``double_vote``: no ``(peer, sender, seq, kind, voter)`` vote is counted
  twice.
- ``unregistered_voter``: every counted vote names a voter the run knows a
  key for (explicit registry, or inferred from the stream's own peer
  universe).
- ``non_monotone_reconfig``: growing the suspicion set must never grow the
  live quorum view (a reconfig that *adds* voters under *more* suspicion is
  how split-brain quorums are minted).
- ``tainted_digest``: every digest admitted into aggregation
  (``agg_admit``) was BRB-delivered for that ``(trainer, round)`` — the
  digest-lineage taint rule.

Ring-truncation tolerance: the flight ring is a contiguous *suffix* of the
event stream, so any round whose ``round_begin`` marker survives is fully
present. Cross-event checks therefore restrict themselves to marked rounds
when markers exist; a stream with no markers (hand-built fixtures, unit
probes) is audited in full.

Determinism: the auditor is pure host bookkeeping over already-deterministic
events — no wall clock, no entropy, sorted traversal everywhere — so the
merged stream's ``causal_digest`` is bit-identical across same-seed runs.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Iterable, Optional

__all__ = [
    "INVARIANTS",
    "Violation",
    "ProtocolAuditor",
    "merge_key",
    "merge_streams",
    "StreamingMerger",
    "causal_digest",
]

INVARIANTS = (
    "conflicting_deliver",
    "forged_quorum",
    "double_vote",
    "unregistered_voter",
    "non_monotone_reconfig",
    "tainted_digest",
)


@dataclasses.dataclass(frozen=True)
class Violation:
    """One failed invariant, with enough context to find the evidence."""

    invariant: str
    detail: str
    round: Optional[int] = None

    def to_dict(self) -> dict[str, Any]:
        return {
            "invariant": self.invariant,
            "detail": self.detail,
            "round": self.round,
        }


def _round_of(ev: dict) -> int:
    """Round coordinate of an event: explicit ``round``, else the BRB
    ``seq`` (instances are keyed by round index), else -1 (pre-round)."""
    r = ev.get("round")
    if r is None:
        r = ev.get("seq")
    return int(r) if isinstance(r, int) else -1


class ProtocolAuditor:
    """Incremental conformance state machine over flight events.

    ``feed(ev)`` applies the per-event checks and accumulates cross-event
    state; ``check()`` runs the cross-event invariants over everything fed
    so far. Both are idempotent per violation (each distinct violation is
    reported exactly once, however often ``check()`` runs), so the driver
    can call them every round and offline audits once at the end.

    ``registered``: the voter universe (peer ids holding registered keys).
    When None it is inferred from the stream itself — the peers that appear
    as instance owners/senders and round trainers.
    """

    def __init__(self, registered: Optional[Iterable[int]] = None) -> None:
        self.registered: Optional[frozenset[int]] = (
            frozenset(int(p) for p in registered)
            if registered is not None
            else None
        )
        self.violations: list[Violation] = []
        self._reported: set[tuple] = set()
        # (sender, seq) -> sorted-unique delivered digest hexes
        self._delivered: dict[tuple[int, int], list[str]] = {}
        # brb_deliver facts: (peer, sender, seq, digest, votes, quorum)
        self._delivers: list[tuple[int, int, int, str, int, int]] = []
        # (peer, sender, seq) -> f declared at instance init
        self._init_f: dict[tuple[int, int, int], int] = {}
        # counted votes: (peer, sender, seq, kind, voter) -> count
        self._votes: dict[tuple[int, int, int, str, int], int] = {}
        # READY recount per (peer, sender, seq, digest) -> distinct voters
        self._ready_voters: dict[tuple[int, int, int, str], set[int]] = {}
        # quorum_reconfig facts in stream order
        self._reconfigs: list[dict[str, Any]] = []
        # agg_admit facts: (round, trainer, digest)
        self._admits: list[tuple[int, int, str]] = []
        self._rounds_marked: set[int] = set()
        self._inferred: set[int] = set()

    # ---- reporting -----------------------------------------------------------

    def _emit(
        self, invariant: str, key: tuple, detail: str, round: Optional[int]
    ) -> Optional[Violation]:
        full_key = (invariant,) + key
        if full_key in self._reported:
            return None
        self._reported.add(full_key)
        v = Violation(invariant=invariant, detail=detail, round=round)
        self.violations.append(v)
        return v

    # ---- ingest --------------------------------------------------------------

    def feed(self, ev: dict) -> list[Violation]:
        """Consume one event; returns any violations it triggered."""
        out: list[Violation] = []
        kind = ev.get("kind")
        if kind == "round_begin":
            self._rounds_marked.add(_round_of(ev))
            for t in ev.get("trainers") or []:
                self._inferred.add(int(t))
        elif kind == "brb_init":
            peer, sender, seq = ev.get("peer"), ev.get("sender"), ev.get("seq")
            if peer is not None:
                self._inferred.add(int(peer))
            if sender is not None:
                self._inferred.add(int(sender))
            if peer is not None and sender is not None and seq is not None:
                f = ev.get("f")
                if f is not None:
                    self._init_f[(int(peer), int(sender), int(seq))] = int(f)
        elif kind == "brb_vote":
            out.extend(self._feed_vote(ev))
        elif kind == "brb_deliver":
            out.extend(self._feed_deliver(ev))
        elif kind == "quorum_reconfig":
            self._reconfigs.append(ev)
        elif kind == "agg_admit":
            r, t, d = ev.get("round"), ev.get("trainer"), ev.get("digest")
            if r is not None and t is not None and d is not None:
                self._admits.append((int(r), int(t), str(d)))
        elif kind == "membership":
            p = ev.get("peer")
            if p is not None:
                self._inferred.add(int(p))
        return out

    def _feed_vote(self, ev: dict) -> list[Violation]:
        out: list[Violation] = []
        peer, sender, seq = ev.get("peer"), ev.get("sender"), ev.get("seq")
        vote, voter = ev.get("vote"), ev.get("voter")
        if None in (sender, seq, vote, voter):
            return out
        peer = int(peer) if peer is not None else -1
        key = (peer, int(sender), int(seq), str(vote), int(voter))
        self._votes[key] = self._votes.get(key, 0) + 1
        if self._votes[key] == 2:  # report once, at first duplicate
            v = self._emit(
                "double_vote",
                key,
                f"peer {peer} counted {vote} vote from {voter} twice for "
                f"instance ({sender}, {seq})",
                round=_round_of(ev),
            )
            if v:
                out.append(v)
        if str(vote) == "ready" and ev.get("digest") is not None:
            self._ready_voters.setdefault(
                (peer, int(sender), int(seq), str(ev["digest"])), set()
            ).add(int(voter))
        return out

    def _feed_deliver(self, ev: dict) -> list[Violation]:
        out: list[Violation] = []
        sender, seq = ev.get("sender"), ev.get("seq")
        if sender is None or seq is None:
            return out
        sender, seq = int(sender), int(seq)
        peer = int(ev["peer"]) if ev.get("peer") is not None else -1
        digest = str(ev["digest"]) if ev.get("digest") is not None else None
        votes = ev.get("votes")
        quorum = ev.get("quorum")
        if digest is not None:
            seen = self._delivered.setdefault((sender, seq), [])
            if digest not in seen:
                seen.append(digest)
                if len(seen) > 1:
                    v = self._emit(
                        "conflicting_deliver",
                        (sender, seq, digest),
                        f"instance ({sender}, {seq}) delivered "
                        f"{len(seen)} distinct digests across peers: "
                        + ", ".join(d[:12] for d in sorted(seen)),
                        round=seq,
                    )
                    if v:
                        out.append(v)
        if votes is not None and quorum is not None and int(votes) < int(quorum):
            v = self._emit(
                "forged_quorum",
                ("votes", peer, sender, seq),
                f"peer {peer} delivered ({sender}, {seq}) with "
                f"{votes} votes below its own quorum {quorum}",
                round=seq,
            )
            if v:
                out.append(v)
        self._delivers.append(
            (
                peer,
                sender,
                seq,
                digest if digest is not None else "",
                int(votes) if votes is not None else -1,
                int(quorum) if quorum is not None else -1,
            )
        )
        return out

    # ---- cross-event checks --------------------------------------------------

    def _round_complete(self, r: int) -> bool:
        """True when round ``r``'s events are fully present: either the
        stream carries no round markers at all (assume complete), or this
        round's ``round_begin`` survived the ring."""
        return not self._rounds_marked or r in self._rounds_marked

    def check(self) -> list[Violation]:
        """Run the cross-event invariants over everything fed so far;
        returns only violations not already reported."""
        out: list[Violation] = []
        out.extend(self._check_quorums())
        out.extend(self._check_voters())
        out.extend(self._check_reconfigs())
        out.extend(self._check_lineage())
        return out

    def _check_quorums(self) -> list[Violation]:
        out: list[Violation] = []
        for peer, sender, seq, digest, votes, quorum in self._delivers:
            if not self._round_complete(seq):
                continue
            f = self._init_f.get((peer, sender, seq))
            if f is not None and quorum >= 0 and quorum < 2 * f + 1:
                v = self._emit(
                    "forged_quorum",
                    ("config", peer, sender, seq),
                    f"peer {peer} delivered ({sender}, {seq}) under quorum "
                    f"{quorum} < 2f+1 = {2 * f + 1}",
                    round=seq,
                )
                if v:
                    out.append(v)
            # Recount: the claimed quorum must be backed by distinct
            # recorded READY votes — only when this instance's vote stream
            # is present at all (older dumps predate brb_vote).
            if digest and quorum >= 0:
                has_votes = any(
                    k[0] == peer and k[1] == sender and k[2] == seq
                    for k in self._votes
                )
                if has_votes:
                    backing = len(
                        self._ready_voters.get((peer, sender, seq, digest), ())
                    )
                    if backing < quorum:
                        v = self._emit(
                            "forged_quorum",
                            ("recount", peer, sender, seq, digest),
                            f"peer {peer} delivered ({sender}, {seq}) "
                            f"claiming quorum {quorum} but only {backing} "
                            "distinct ready votes are on record",
                            round=seq,
                        )
                        if v:
                            out.append(v)
        return out

    def _check_voters(self) -> list[Violation]:
        out: list[Violation] = []
        universe = self.registered
        if universe is None:
            if not self._inferred:
                return out  # nothing to check against
            universe = frozenset(self._inferred)
        for key in sorted(self._votes):
            peer, sender, seq, vote, voter = key
            if not self._round_complete(seq):
                continue
            if voter not in universe:
                v = self._emit(
                    "unregistered_voter",
                    key,
                    f"peer {peer} counted a {vote} vote from unregistered "
                    f"peer {voter} for instance ({sender}, {seq})",
                    round=seq,
                )
                if v:
                    out.append(v)
        return out

    def _check_reconfigs(self) -> list[Violation]:
        out: list[Violation] = []
        for ev in self._reconfigs:
            live, committee = ev.get("live"), ev.get("committee")
            if live is not None and committee is not None and live > committee:
                v = self._emit(
                    "non_monotone_reconfig",
                    ("overfull", ev.get("round"), live, committee),
                    f"round {ev.get('round')} reconfigured to {live} live "
                    f"voters out of a {committee}-member committee",
                    round=ev.get("round"),
                )
                if v:
                    out.append(v)
        for prev, cur in zip(self._reconfigs, self._reconfigs[1:]):
            s_prev = set(prev.get("suspected") or [])
            s_cur = set(cur.get("suspected") or [])
            live_prev, live_cur = prev.get("live"), cur.get("live")
            if live_prev is None or live_cur is None:
                continue
            if s_cur > s_prev and live_cur > live_prev:
                v = self._emit(
                    "non_monotone_reconfig",
                    ("grow", prev.get("round"), cur.get("round")),
                    f"suspicion grew {sorted(s_prev)} -> {sorted(s_cur)} "
                    f"but the live quorum view grew {live_prev} -> "
                    f"{live_cur} (round {prev.get('round')} -> "
                    f"{cur.get('round')})",
                    round=cur.get("round"),
                )
                if v:
                    out.append(v)
        return out

    def _check_lineage(self) -> list[Violation]:
        out: list[Violation] = []
        delivered_digests: dict[tuple[int, int], set[str]] = {}
        for _, sender, seq, digest, _, _ in self._delivers:
            if digest:
                delivered_digests.setdefault((sender, seq), set()).add(digest)
        for r, trainer, digest in self._admits:
            if not self._round_complete(r):
                continue
            if digest not in delivered_digests.get((trainer, r), ()):
                v = self._emit(
                    "tainted_digest",
                    (r, trainer, digest),
                    f"round {r} admitted trainer {trainer}'s digest "
                    f"{digest[:12]} into aggregation without a matching "
                    "BRB delivery",
                    round=r,
                )
                if v:
                    out.append(v)
        return out

    # ---- convenience ---------------------------------------------------------

    def audit(self, events: Iterable[dict]) -> list[Violation]:
        """Feed a whole stream, run the cross-event checks, and return every
        violation found (the offline entry point)."""
        for ev in events:
            self.feed(ev)
        self.check()
        return list(self.violations)

    def summary(self) -> dict[str, Any]:
        by_invariant: dict[str, int] = {}
        for v in self.violations:
            by_invariant[v.invariant] = by_invariant.get(v.invariant, 0) + 1
        return {
            "violations": len(self.violations),
            "by_invariant": dict(sorted(by_invariant.items())),
        }


def merge_key(ev: dict, stream_index: int) -> tuple[int, int, int, int]:
    """The canonical causal-merge sort key ``(round, lamport, stream, n)``.

    Round groups the protocol phases, the Lamport time orders
    causally-related events within a round (a receive always sorts after
    its send), and the (stream, n) tail breaks the remaining concurrency
    ties identically on every run. Shared by the offline ``merge_streams``,
    the tower's ``StreamingMerger``, and divergence alignment so all three
    agree on what "the same position" means.
    """
    lamport = ev.get("lamport")
    return (
        _round_of(ev),
        int(lamport) if isinstance(lamport, int) else -1,
        stream_index,
        int(ev.get("n", 0)),
    )


def merge_streams(streams: list[list[dict]]) -> list[dict]:
    """Deterministically merge N per-process event streams into one.

    Sorts by ``merge_key``. The auditor's checks are order-insensitive;
    the merged order exists so ``causal_digest`` is a stable cross-peer
    fingerprint.
    """
    keyed = []
    for si, evs in enumerate(streams):
        for ev in evs:
            keyed.append((merge_key(ev, si), ev))
    keyed.sort(key=lambda t: t[0])
    return [t[1] for t in keyed]


class StreamingMerger:
    """Incremental ``merge_streams``: per-stream buffers + round watermarks.

    ``push(stream, events)`` buffers a batch from one stream (events arrive
    in local ``n`` order but *not* key order — a depth-k pipeline flushes
    round ``r`` events up to k rounds late, and ``membership`` stop events
    carry no round at all). ``poll()`` emits, in global ``merge_key`` order,
    every buffered event whose round coordinate is strictly below the
    *frontier* — ``min`` over live (non-closed) streams of the largest round
    seen, minus ``hold_rounds`` of pipeline slack — because a stream that
    has shown round ``W`` can still produce events for rounds down to
    ``W - hold_rounds`` but no lower. ``close(stream)`` removes a stream
    from the frontier; ``finalize()`` closes everything and drains.

    The rolling ``digest()`` folds each emitted event (time-stripped,
    sorted-keys JSON — exactly ``causal_digest``'s encoding) in emission
    order. As long as no *late* event arrives (key at or below the last
    emitted key — ``late_events`` counts them), the emitted sequence is
    bit-identical to ``merge_streams`` over the same events, so the rolling
    digest equals the offline ``causal_digest`` at every prefix and, after
    ``finalize()``, over the whole run.
    """

    def __init__(self, n_streams: int, hold_rounds: int = 2) -> None:
        if n_streams < 1:
            raise ValueError("StreamingMerger needs at least one stream")
        self.n_streams = n_streams
        self.hold_rounds = max(0, int(hold_rounds))
        self._pending: list[tuple[tuple[int, int, int, int], dict]] = []
        # Largest round coordinate seen per stream; -2 = nothing yet (so a
        # silent stream holds the frontier below every real round, incl. -1).
        self._max_round = [-2] * n_streams
        self._closed = [False] * n_streams
        self._last_key: Optional[tuple[int, int, int, int]] = None
        self._hash = hashlib.sha256()
        self.emitted = 0
        self.late_events = 0
        self.buffered_high_water = 0

    def push(self, stream_index: int, events: Iterable[dict]) -> int:
        """Buffer one batch from ``stream_index``; returns events accepted."""
        if not 0 <= stream_index < self.n_streams:
            raise IndexError(f"stream {stream_index} out of range")
        count = 0
        for ev in events:
            key = merge_key(ev, stream_index)
            self._pending.append((key, ev))
            if key[0] > self._max_round[stream_index]:
                self._max_round[stream_index] = key[0]
            count += 1
        self.buffered_high_water = max(self.buffered_high_water, len(self._pending))
        return count

    def close(self, stream_index: int) -> None:
        """Mark a stream complete: it no longer holds back the frontier."""
        self._closed[stream_index] = True

    @property
    def frontier(self) -> Optional[int]:
        """Exclusive round bound below which emission is safe; None when
        every stream is closed (everything buffered is safe)."""
        live = [
            self._max_round[i]
            for i in range(self.n_streams)
            if not self._closed[i]
        ]
        if not live:
            return None
        return min(live) - self.hold_rounds

    def poll(self) -> list[dict]:
        """Emit the safe sorted prefix of the buffered events."""
        frontier = self.frontier
        if frontier is None:
            ready, self._pending = self._pending, []
        else:
            ready = [kv for kv in self._pending if kv[0][0] < frontier]
            if not ready:
                return []
            self._pending = [kv for kv in self._pending if kv[0][0] >= frontier]
        ready.sort(key=lambda kv: kv[0])
        out = []
        for key, ev in ready:
            if self._last_key is not None and key <= self._last_key:
                # Ordered emission already passed this key: the event still
                # flows downstream (the auditor is order-insensitive) but the
                # rolling digest can no longer match the offline merge.
                self.late_events += 1
            else:
                self._last_key = key
            stripped = {k: v for k, v in ev.items() if k != "ts"}
            self._hash.update(json.dumps(stripped, sort_keys=True).encode())
            self.emitted += 1
            out.append(ev)
        return out

    def finalize(self) -> list[dict]:
        """Close every stream and drain the remaining buffer in order."""
        for i in range(self.n_streams):
            self._closed[i] = True
        return self.poll()

    def digest(self) -> str:
        """Rolling causal digest over everything emitted so far."""
        return self._hash.copy().hexdigest()


def causal_digest(events: Iterable[dict]) -> str:
    """SHA-256 over the time-stripped merged stream — two same-seed runs
    produce the same digest (the cross-peer bit-identity check)."""
    h = hashlib.sha256()
    for ev in events:
        ev = {k: v for k, v in ev.items() if k != "ts"}
        h.update(json.dumps(ev, sort_keys=True).encode())
    return h.hexdigest()
