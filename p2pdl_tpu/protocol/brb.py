"""Byzantine Reliable Broadcast (Bracha) with ECDSA-signed digests.

Capability parity with the reference's echo/ready/sup protocol (reference
``utils/broadcast.py:8-141``, handlers ``node/node.py:146-240``) — rebuilt as
the *correct, parameterized* Bracha state machine the reference approximates:

- The reference hard-codes every quorum to 4 (``node/node.py:165,209``),
  contradicting its own ``(n-1)//3`` fault formula (``node/node.py:232``);
  here the quorums derive from (n, f): echo quorum ``ceil((n+f+1)/2)``,
  ready amplification ``f+1``, delivery ``2f+1`` — the standard thresholds
  that tolerate f Byzantine peers for n > 3f.
- The reference's tester increments its ready counter once per *signature in
  one message* (``node/node.py:204`` — a single valid 'ready' yields cnt=4),
  so one forged message can trigger delivery; here each counted vote is a
  distinct signed message from a distinct peer.
- Messages carry a 32-byte canonical digest (``crypto.digest_update``), not
  the pickled update (reference signs and ships pickle,
  ``utils/broadcast.py:19-30``); payload travels once in SEND, and the data
  plane in simulation keeps it on-device entirely.

The state machine is transport-agnostic and synchronous: ``handle(msg)``
returns the messages to emit, the driver/transport decides how they travel
(in-memory channels in simulation, framed TCP across hosts).
"""

from __future__ import annotations

import dataclasses
import hashlib
import math
import struct
import time
from typing import Optional

from p2pdl_tpu.protocol import crypto
from p2pdl_tpu.utils import flight, telemetry

SEND, ECHO, READY = "send", "echo", "ready"

# Every digest on the wire is a SHA-256 output; anything else is malformed.
DIGEST_LEN = 32

_BATCH_KIND_CODE = {ECHO: 1, READY: 2}

# Signed-header magics, one per revision of the batch signing encoding:
# BRB2 is the fixed-width header without a trace tag, BRB3 appends the
# emitter's (peer, local_seq, lamport) coordinates. Distinct magics keep
# the two encodings injective against each other — a BRB3 byte string can
# never verify as a BRB2 one (and p2plint's wire-kind registry check
# enforces that no two revisions share a magic).
_SIGNING_MAGIC_CODES = {b"BRB2": 2, b"BRB3": 3}


@dataclasses.dataclass(frozen=True)
class TraceTag:
    """Causal origin of one control message: which peer emitted it, its
    per-peer emission counter, and the emitter's Lamport time at emission.

    ``(peer, lseq)`` uniquely names the emission event process-wide;
    ``lamport`` orders it against every causally-related event, so a
    merged multi-peer event stream can reconstruct send->recv edges
    without any wall clock (replay-exact by construction)."""

    peer: int
    lseq: int
    lamport: int


class LamportClock:
    """Per-peer logical clock (Lamport 1978): ``tick()`` on every emission,
    ``observe()`` (max-merge + 1) on every receipt. Purely logical — no
    wall-clock reads — so clock values are bit-identical across same-seed
    replays and never perturb protocol state."""

    def __init__(self, peer: int) -> None:
        self.peer = peer
        self.time = 0
        self._lseq = 0

    def tick(self) -> TraceTag:
        """Advance for a local emission; returns the message's trace tag."""
        self.time += 1
        self._lseq += 1
        return TraceTag(self.peer, self._lseq, self.time)

    def observe(self, lamport: int) -> None:
        """Merge a received message's Lamport time (receive rule)."""
        self.time = max(self.time, int(lamport)) + 1


@dataclasses.dataclass(frozen=True)
class BRBConfig:
    n: int  # total peers
    f: int  # Byzantine fault budget

    def __post_init__(self) -> None:
        if self.n <= 3 * self.f:
            raise ValueError(f"Bracha BRB requires n > 3f, got n={self.n}, f={self.f}")

    @property
    def echo_quorum(self) -> int:
        return math.ceil((self.n + self.f + 1) / 2)

    @property
    def ready_amplify(self) -> int:
        return self.f + 1

    @property
    def deliver_quorum(self) -> int:
        return 2 * self.f + 1


@dataclasses.dataclass(frozen=True)
class BRBMessage:
    kind: str  # send | echo | ready
    sender: int  # originator of the broadcast
    seq: int  # broadcast sequence number (e.g. round index)
    from_id: int  # peer that emitted this message
    digest: bytes
    payload: Optional[bytes] = None  # only on SEND
    signature: Optional[bytes] = None  # over signing_bytes(), except SEND payload sig
    # Causal-trace header (wire v3). Unsigned on the per-message path so a
    # v3 message verifies under the unchanged v1/v2 signing bytes — the
    # trace is observability metadata, not a protocol input, and a
    # stripped/forged tag can at worst mislabel a flight-recorder edge.
    trace: Optional[TraceTag] = None

    def signing_bytes(self) -> bytes:
        return b"|".join(
            [
                self.kind.encode(),
                str(self.sender).encode(),
                str(self.seq).encode(),
                self.digest,
            ]
        )


@dataclasses.dataclass(frozen=True)
class BRBBatch:
    """One peer's coalesced echo/ready votes for every concurrent BRB
    instance of a round (wire v2, ``Config.control_batching``).

    With T trainers broadcasting per round, the per-message framing costs
    O(T * committee^2) control frames and signatures; a batch carries the
    (sender, digest) vote for all T instances in ONE frame per (src, dst)
    pair per phase, under ONE signature covering the whole vote list —
    verified once on receipt (``Broadcaster.handle_batch``), then each
    vote advances its instance through the pre-verified path. Protocol
    outcomes are identical to per-message framing: votes still land in
    the same per-digest, one-vote-per-peer sets.
    """

    kind: str  # echo | ready (SEND carries a payload and travels alone)
    from_id: int  # peer whose votes these are (and whose key signs)
    seq: int  # broadcast sequence number (round index)
    items: tuple[tuple[int, bytes], ...]  # (sender, digest) per instance
    signature: Optional[bytes] = None  # over signing_bytes()
    # Causal-trace header (wire v3). SIGNED on the batch path: the whole
    # frame is one signature anyway, so covering the tag costs nothing and
    # pins the emitter's claimed causal coordinates.
    trace: Optional[TraceTag] = None

    def signing_bytes(self) -> bytes:
        # Injective, fixed-width encoding: every field has a known width and
        # the item count is part of the header, so no two distinct vote
        # lists serialize to the same signed bytes. (A delimiter-joined
        # layout is NOT injective once variable-length digests sit next to
        # integer fields: adjacent votes can re-frame across the delimiter
        # and an honest signature would verify for a different vote list.)
        # Traceless batches sign the BRB2 header, traced ones the BRB3
        # header with the fixed-width trace coordinates appended; the
        # distinct magics keep the two revisions mutually injective.
        code = _BATCH_KIND_CODE.get(self.kind)
        if code is None:
            raise ValueError(f"unsignable batch kind: {self.kind!r}")
        if self.trace is None:
            header = struct.pack(
                ">4sBqqI", b"BRB2", code, self.from_id, self.seq, len(self.items)
            )
        else:
            header = struct.pack(
                ">4sBqqIqqq", b"BRB3", code, self.from_id, self.seq,
                len(self.items), self.trace.peer, self.trace.lseq,
                self.trace.lamport,
            )
        parts = [header]
        for sender, digest in self.items:
            if len(digest) != DIGEST_LEN:
                raise ValueError(
                    f"batch digest must be {DIGEST_LEN} bytes, got {len(digest)}"
                )
            parts.append(struct.pack(">q", sender))
            parts.append(digest)
        return b"".join(parts)


# A batch larger than this is hostile (it could mint that many instances
# in one frame) and is rejected outright; honest batches carry at most one
# vote per concurrent broadcast, far below this.
MAX_BATCH_ITEMS = 4096


class BRBInstance:
    """One (sender, seq) broadcast as seen by one peer.

    All votes are counted **per digest** (``dict[digest, set[from_id]]``):
    with digest-blind counting, an equivocating sender plus f Byzantine
    voters can assemble a mixed-digest READY quorum at a peer that never saw
    the honest SEND and make it deliver a conflicting payload — per-digest
    sets plus the sha256(payload) == quorum-digest delivery check exclude
    that with up to f faults.
    """

    # Payload storage is keyed by digest; honest peers can only ever form a
    # quorum for one digest, so a small cap bounds a spamming sender.
    MAX_STORED_PAYLOADS = 4

    def __init__(
        self,
        cfg: BRBConfig,
        my_id: int,
        key_server,
        private_key,
        sign_control: bool = True,
        sender: Optional[int] = None,
        seq: Optional[int] = None,
        clock: Optional[LamportClock] = None,
    ) -> None:
        self.cfg = cfg
        self.my_id = my_id
        self.key_server = key_server
        self.private_key = private_key
        # Causal clock: shared across a Broadcaster's instances (one clock
        # per peer, the Lamport model); standalone instances get their own.
        self.clock = clock if clock is not None else LamportClock(my_id)
        # Trace tag of the message currently being processed — the *cause*
        # of whatever this instance emits/records next (None at origin).
        self._cause: Optional[str] = None
        # With control batching, this peer's echoes/readies only ever
        # travel inside a signed BRBBatch — the per-message signature would
        # be dead weight (and the dominant host cost), so it is skipped.
        # SENDs always carry their own signature: the payload travels once,
        # per message, in both framings.
        self.sign_control = sign_control
        # Instance identity for the flight recorder's per-instance timelines
        # (None when constructed outside a Broadcaster, e.g. unit tests).
        self.sender = sender
        self.seq = seq
        self.payloads: dict[bytes, bytes] = {}
        self.accepted_digest: Optional[bytes] = None  # first valid SEND wins the echo
        self.echoes: dict[bytes, set[int]] = {}
        self.readies: dict[bytes, set[int]] = {}
        # One counted vote per peer per kind: a Byzantine voter emitting many
        # digests gets exactly one entry, bounding state at O(n) per instance.
        self._echo_voted: set[int] = set()
        self._ready_voted: set[int] = set()
        self.sent_echo = False
        self.sent_ready = False
        self.delivered: Optional[bytes] = None
        self.delivered_digest: Optional[bytes] = None
        self.delivery_latency_s: Optional[float] = None
        # perf_counter stamp of this peer's own ECHO emission — start of the
        # echo->deliver latency observation (None until the echo goes out).
        self._echo_at: Optional[float] = None

    def _flight(self, kind: str, **fields) -> None:
        # Every event carries the peer's Lamport time plus the trace tag of
        # the message that caused it ("peer:lamport" of the emission), so a
        # merged multi-peer stream reconstructs send->recv edges offline.
        flight.record(
            kind, sender=self.sender, seq=self.seq, peer=self.my_id,
            lamport=self.clock.time, cause=self._cause, **fields,
        )

    def _make(self, kind: str, sender: int, seq: int, digest: bytes, payload=None) -> BRBMessage:
        telemetry.counter("brb.messages", kind=kind, dir="tx").inc()
        trace = self.clock.tick()
        msg = BRBMessage(kind, sender, seq, self.my_id, digest, payload, trace=trace)
        if kind != SEND and not self.sign_control:
            return msg  # valid only inside a signed BRBBatch
        return dataclasses.replace(
            msg, signature=crypto.sign_data(self.private_key, msg.signing_bytes())
        )

    def _observe(self, msg: BRBMessage) -> None:
        """Receive rule: merge the sender's Lamport time and remember the
        message's trace tag as the cause of what this instance does next."""
        if msg.trace is not None:
            self.clock.observe(msg.trace.lamport)
            self._cause = f"{msg.trace.peer}:{msg.trace.lamport}"
        else:
            self._cause = None

    def broadcast(self, seq: int, payload: bytes) -> list[BRBMessage]:
        """Originate: emit SEND to all (caller fans out)."""
        digest = hashlib.sha256(payload).digest()
        self._cause = None  # origin event: nothing caused it
        msg = self._make(SEND, self.my_id, seq, digest, payload)
        self._flight("brb_send", digest=digest.hex())
        return [msg]

    def _try_deliver(self) -> None:
        if self.delivered is not None:
            return
        for digest, voters in self.readies.items():
            if len(voters) >= self.cfg.deliver_quorum and digest in self.payloads:
                # Delivery strictly requires the payload matching the digest
                # the quorum voted for (payloads dict only admits verified
                # sha256 matches).
                self.delivered = self.payloads[digest]
                self.delivered_digest = digest
                telemetry.counter("brb.delivered").inc()
                if self._echo_at is not None:
                    self.delivery_latency_s = time.perf_counter() - self._echo_at
                    telemetry.histogram("brb.echo_to_deliver_seconds").observe(
                        self.delivery_latency_s
                    )
                self._flight(
                    "brb_deliver",
                    votes=len(voters),
                    quorum=self.cfg.deliver_quorum,
                    margin=len(voters) - self.cfg.deliver_quorum,
                    digest=digest.hex(),
                )
                return

    def handle(self, msg: BRBMessage) -> list[BRBMessage]:
        """Advance the state machine; returns messages to fan out to all
        peers. Check ``.delivered`` after each call."""
        telemetry.counter("brb.messages", kind=msg.kind, dir="rx").inc()
        if not crypto_ok(self.key_server, msg):
            telemetry.counter("brb.signature_failures", kind=msg.kind).inc()
            return []
        return self._advance(msg)

    def handle_preverified(self, msg: BRBMessage) -> list[BRBMessage]:
        """Advance on a vote whose authenticity was already established by
        the batch signature covering it (``Broadcaster.handle_batch``
        verified the frame once); per-message crypto is skipped."""
        telemetry.counter("brb.messages", kind=msg.kind, dir="rx").inc()
        return self._advance(msg)

    def _advance(self, msg: BRBMessage) -> list[BRBMessage]:
        out: list[BRBMessage] = []
        self._observe(msg)

        if msg.kind == SEND:
            if msg.from_id != msg.sender or msg.payload is None:
                return []
            if hashlib.sha256(msg.payload).digest() != msg.digest:
                return []
            if msg.digest not in self.payloads and len(self.payloads) < self.MAX_STORED_PAYLOADS:
                self.payloads[msg.digest] = msg.payload
            # Echo at most once per (sender, seq), for the first valid SEND:
            # an equivocating sender splits the honest echo vote and neither
            # digest reaches the echo quorum.
            if self.accepted_digest is None:
                self.accepted_digest = msg.digest
            if self.accepted_digest == msg.digest and not self.sent_echo:
                self.sent_echo = True
                self._echo_at = time.perf_counter()
                # _make first: the recorded lamport is the emission's time.
                out.append(self._make(ECHO, msg.sender, msg.seq, msg.digest))
                self._flight("brb_echo", digest=msg.digest.hex()[:12])
            # A late SEND can complete a delivery whose READY quorum for this
            # digest already formed (payload was the missing piece).
            self._try_deliver()

        elif msg.kind == ECHO:
            if msg.from_id in self._echo_voted:
                return []
            self._echo_voted.add(msg.from_id)
            voters = self.echoes.setdefault(msg.digest, set())
            voters.add(msg.from_id)
            # One brb_vote per COUNTED vote (post-dedup): the conformance
            # auditor recounts quorums and double votes from these.
            self._flight(
                "brb_vote", vote=ECHO, voter=msg.from_id, digest=msg.digest.hex()
            )
            if len(voters) >= self.cfg.echo_quorum and not self.sent_ready:
                self.sent_ready = True
                out.append(self._make(READY, msg.sender, msg.seq, msg.digest))
                self._flight(
                    "brb_ready",
                    via="echo",
                    votes=len(voters),
                    quorum=self.cfg.echo_quorum,
                )

        elif msg.kind == READY:
            if msg.from_id in self._ready_voted:
                return []
            self._ready_voted.add(msg.from_id)
            voters = self.readies.setdefault(msg.digest, set())
            voters.add(msg.from_id)
            self._flight(
                "brb_vote", vote=READY, voter=msg.from_id, digest=msg.digest.hex()
            )
            if len(voters) >= self.cfg.ready_amplify and not self.sent_ready:
                self.sent_ready = True
                out.append(self._make(READY, msg.sender, msg.seq, msg.digest))
                self._flight(
                    "brb_ready",
                    via="amplify",
                    votes=len(voters),
                    quorum=self.cfg.ready_amplify,
                )
            self._try_deliver()

        return out


def crypto_ok(key_server, msg: BRBMessage) -> bool:
    if msg.signature is None:
        return False
    return key_server.verify(msg.from_id, msg.signature, msg.signing_bytes())


def batch_ok(key_server, batch: BRBBatch) -> bool:
    if batch.signature is None:
        return False
    return key_server.verify(batch.from_id, batch.signature, batch.signing_bytes())


class Broadcaster:
    """Per-peer BRB endpoint managing instances keyed by (sender, seq).

    The reference spreads this state across ``Node`` fields
    (``received_echo_cnt`` etc., ``node/node.py:46-52``) reset between
    rounds by ``reset_delivered_flag`` (``node/node.py:55-66``); here each
    broadcast is its own instance, so concurrent broadcasts cannot bleed
    counters into each other.
    """

    def __init__(
        self,
        cfg: BRBConfig,
        my_id: int,
        key_server,
        private_key,
        sign_control: bool = True,
    ) -> None:
        self.cfg = cfg
        self.my_id = my_id
        self.key_server = key_server
        self.private_key = private_key
        self.sign_control = sign_control
        # One Lamport clock per peer, shared by every instance: causal
        # order is a property of the peer's whole control plane, not of a
        # single broadcast.
        self.clock = LamportClock(my_id)
        self.instances: dict[tuple[int, int], BRBInstance] = {}

    def reconfigure(self, cfg: BRBConfig) -> None:
        """Swap the quorum config for *future* instances (live membership:
        when the failure detector shrinks the view, quorums recompute over
        the live set instead of timing out against dead voters). Instances
        already in flight keep the config they started with — changing a
        quorum mid-instance would let the same READY set count under two
        different thresholds."""
        self.cfg = cfg

    def _instance(self, sender: int, seq: int) -> BRBInstance:
        key = (sender, seq)
        if key not in self.instances:
            self.instances[key] = BRBInstance(
                self.cfg,
                self.my_id,
                self.key_server,
                self.private_key,
                sign_control=self.sign_control,
                sender=sender,
                seq=seq,
                clock=self.clock,
            )
            # Field name: "committee", NOT "n" — the recorder reserves "n"
            # for its own monotone sequence number, and a caller field named
            # "n" would silently overwrite it (dict update order).
            flight.record(
                "brb_init",
                sender=sender,
                seq=seq,
                peer=self.my_id,
                committee=self.cfg.n,
                f=self.cfg.f,
                lamport=self.clock.time,
            )
        return self.instances[key]

    def broadcast(self, seq: int, payload: bytes) -> list[BRBMessage]:
        return self._instance(self.my_id, seq).broadcast(seq, payload)

    def broadcast_equivocating(
        self, seq: int, payload_a: bytes, payload_b: bytes
    ) -> tuple[BRBMessage, BRBMessage]:
        """Byzantine-sender behavior for fault injection: two validly-signed,
        conflicting SENDs for the same (sender, seq). Correct BRB must never
        let honest peers deliver different payloads — the split echo vote
        means neither usually delivers at all."""
        inst = self._instance(self.my_id, seq)
        a = inst._make(SEND, self.my_id, seq, hashlib.sha256(payload_a).digest(), payload_a)
        b = inst._make(SEND, self.my_id, seq, hashlib.sha256(payload_b).digest(), payload_b)
        return a, b

    def handle(self, msg: BRBMessage) -> list[BRBMessage]:
        if msg.kind not in (SEND, ECHO, READY):
            return []
        return self._instance(msg.sender, msg.seq).handle(msg)

    def make_batch(self, kind: str, seq: int, items) -> BRBBatch:
        """Coalesce this peer's (sender, digest) votes for one (kind, seq)
        into a single signed frame (wire v2)."""
        batch = BRBBatch(
            kind=kind,
            from_id=self.my_id,
            seq=seq,
            items=tuple((int(s), bytes(d)) for s, d in items),
            trace=self.clock.tick(),
        )
        return dataclasses.replace(
            batch, signature=crypto.sign_data(self.private_key, batch.signing_bytes())
        )

    def handle_batch(self, batch: BRBBatch) -> list[BRBMessage]:
        """Verify the batch signature ONCE, then advance every covered
        instance through the pre-verified path. Duplicate or conflicting
        votes inside a batch are bounded by each instance's
        one-vote-per-peer caps, exactly as in the per-message framing."""
        if batch.kind not in (ECHO, READY) or len(batch.items) > MAX_BATCH_ITEMS:
            return []
        # Shape-validate every item BEFORE any crypto: a vote may only name
        # a registered peer as its broadcast sender and must carry exactly
        # one SHA-256 digest. Without this, one validly-signed frame could
        # mint instances for arbitrary sender ids and store arbitrarily
        # long byte strings as vote keys — a memory amplification the v1
        # per-message path never allowed. (Registered-key membership, not
        # ``cfg.n``, is the sender universe: live-membership reconfigure
        # shrinks ``cfg.n`` to the surviving committee while any registered
        # peer may still originate a broadcast.)
        for sender, digest in batch.items:
            if len(digest) != DIGEST_LEN or not self.key_server.has_key(int(sender)):
                telemetry.counter("brb.batch_rejected", reason="malformed_item").inc()
                flight.anomaly(
                    "batch_rejected",
                    round=batch.seq,
                    seq=batch.seq,
                    from_id=batch.from_id,
                    peer=self.my_id,
                    reason="malformed_item",
                )
                return []
        if not batch_ok(self.key_server, batch):
            telemetry.counter("brb.signature_failures", kind="batch").inc()
            return []
        out: list[BRBMessage] = []
        for sender, digest in batch.items:
            # Each unpacked vote carries the batch's trace tag: causally,
            # every vote in the frame is one emission event of the sender.
            msg = BRBMessage(
                batch.kind, int(sender), batch.seq, batch.from_id, digest,
                trace=batch.trace,
            )
            out.extend(self._instance(int(sender), batch.seq).handle_preverified(msg))
        return out

    def delivered(self, sender: int, seq: int) -> Optional[bytes]:
        inst = self.instances.get((sender, seq))
        return inst.delivered if inst else None

    def prune(self, before_seq: int, report_timeouts: bool = False) -> None:
        """Evict instances of completed rounds (seq < before_seq) — without
        this a long experiment leaks one instance per (sender, round).
        An evicted instance that never delivered is a timed-out broadcast
        (its round's deadline passed), counted as ``brb.instances{...}``.

        ``report_timeouts=True`` additionally raises a flight-recorder
        ``brb_timeout`` anomaly per undelivered instance — the trust plane
        enables it on committee broadcasters, where non-delivery is a real
        protocol failure (a trainer's own never-completed SEND instance on a
        non-committee peer is expected, not anomalous)."""
        for key in [k for k in self.instances if k[1] < before_seq]:
            inst = self.instances[key]
            outcome = "delivered" if inst.delivered is not None else "timed_out"
            telemetry.counter("brb.instances", outcome=outcome).inc()
            if report_timeouts and inst.delivered is None:
                ready_votes = max(
                    [len(v) for v in inst.readies.values()], default=0
                )
                flight.anomaly(
                    "brb_timeout",
                    round=key[1],
                    sender=key[0],
                    seq=key[1],
                    peer=self.my_id,
                    ready_votes=ready_votes,
                    quorum=inst.cfg.deliver_quorum,
                )
            del self.instances[key]
