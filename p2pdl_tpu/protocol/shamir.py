"""Shamir secret sharing over the P-256 group order.

Dropout recovery for secure aggregation (Bonawitz et al., CCS 2017 §4):
each trainer t-of-n shares its ECDH private scalar among the peer set at
setup. If it drops after shipping a masked update, any threshold of
survivors can hand the aggregator enough shares to reconstruct the
dropped trainer's ECDH key, re-derive its pairwise mask seeds, and cancel
the orphaned masks out of the aggregate (``ops/secure_agg.residual_mask_sum``).

The reference has no secrecy at all — updates travel as plaintext pickle
(reference ``utils/broadcast.py:8-37``) — so this subsystem has no
reference counterpart to cite beyond the ECDSA key infrastructure it
piggybacks on (reference ``utils/crypto.py:42-48``).

The field is GF(q) with q = the secp256r1 group order, so any valid ECDH
private scalar (1 <= s < q) is a field element and reconstruction returns
it exactly. Shares are (x, y) integer pairs with x in 1..n.
"""

from __future__ import annotations

import secrets

# secp256r1 (NIST P-256) group order — the scalar field of the curve the
# PKI already uses (protocol/crypto.py).
P256_ORDER = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551


def _eval_poly(coeffs: list[int], x: int, q: int) -> int:
    """Horner evaluation of ``sum(coeffs[k] * x^k)`` mod q."""
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * x + c) % q
    return acc


def split_secret(
    secret: int,
    n_shares: int,
    threshold: int,
    *,
    q: int = P256_ORDER,
    rng=None,
) -> list[tuple[int, int]]:
    """Split ``secret`` into ``n_shares`` points of a random degree
    ``threshold - 1`` polynomial with constant term ``secret``.

    Any ``threshold`` shares reconstruct exactly; fewer reveal nothing
    (every sub-threshold set is consistent with every possible secret).
    ``rng``: optional ``random.Random``-like source for deterministic
    tests; defaults to OS entropy.
    """
    if not (0 <= secret < q):
        raise ValueError("secret must be a field element in [0, q)")
    if not (1 <= threshold <= n_shares):
        raise ValueError(f"need 1 <= threshold({threshold}) <= n_shares({n_shares})")
    if n_shares >= q:  # unreachable for P-256 but keeps the math honest
        raise ValueError("n_shares must be < field size")
    # p2plint: disable=determinism-entropy -- sanctioned: secret-sharing blinding polynomial must be cryptographically random; callers needing replay pass rng=
    draw = (lambda: rng.randrange(q)) if rng is not None else (lambda: secrets.randbelow(q))
    coeffs = [secret] + [draw() for _ in range(threshold - 1)]
    return [(x, _eval_poly(coeffs, x, q)) for x in range(1, n_shares + 1)]


def reconstruct_secret(
    shares: list[tuple[int, int]], *, q: int = P256_ORDER
) -> int:
    """Lagrange interpolation at 0 over the given shares.

    Caller must supply at least ``threshold`` distinct shares; with fewer,
    the result is a uniformly random-looking field element, not an error —
    thresholdness is information-theoretic, not enforced here.
    """
    if not shares:
        raise ValueError("no shares given")
    xs = [x for x, _ in shares]
    if len(set(xs)) != len(xs):
        raise ValueError("duplicate share x-coordinates")
    acc = 0
    for i, (xi, yi) in enumerate(shares):
        num, den = 1, 1
        for j, (xj, _) in enumerate(shares):
            if i == j:
                continue
            num = (num * (-xj)) % q
            den = (den * (xi - xj)) % q
        acc = (acc + yi * num * pow(den, -1, q)) % q
    return acc
