"""Async control-plane transport: one event loop, pooled framed TCP.

The thread-per-connection ``TCPTransport`` keeps the reference's discipline
(fresh socket per frame, one listener thread) — fine for the small, rare
control messages of a simulated mesh, but wrong for the process-per-peer
deployment the paper implies: every send pays a connect round-trip, a slow
peer blocks its sender thread, and nothing bounds what a partitioned peer
can queue. This module is the production shape:

- **Single event loop** in a dedicated thread; every connection is a
  coroutine on it. ``send()`` stays thread-safe and non-blocking for the
  protocol threads that call it.
- **Connection pooling with lazy dial**: the first frame to a peer dials;
  the connection is kept and reused. Dial failures back off exponentially
  with deterministic SHA-256 jitter (same keying idiom as the legacy
  sender), and a peer that stays unreachable trips a fail-fast "down
  window" so one dead peer cannot stall its queue at dial timeout per
  frame.
- **Bounded backpressure**: one send queue per peer with a high-water
  mark. Beyond it the *newest* frame is dropped and counted
  (``transport.backpressure_dropped``) — the protocol's retry/quorum
  machinery owns recovery, the transport just refuses to buffer without
  bound.
- **Wire compatibility**: frames are exactly the v1/v2/v3 bytes —
  4-byte BE length, then 4-byte BE source id + payload. A legacy
  ``TCPTransport`` peer can dial us (we read frames until EOF, serving
  both its one-shot connections and pooled ones) and we can dial it (its
  one-frame-then-close serve loop EOFs our pooled connection; the reader
  task notices and the next frame re-dials).
- **Fault injection at the frame boundary**: an optional ``fault_filter``
  decides, per outgoing frame, how many copies actually hit the wire
  (0 = dropped by the chaos plane) — the hook `FaultInjector` drives so a
  seeded FaultPlan drops/duplicates frames on *real* connections.
  ``set_blocked()`` is the partition face: sends to blocked peers are
  refused, frames from them discarded, and their pooled connections torn
  down.
- **Graceful drain-on-stop**: ``stop()`` waits (bounded) for the queues to
  flush, then closes every connection, stops the loop, and joins the
  thread. Idempotent.

Determinism note: this plane is wall-clock-scheduled (dial backoff, drain
timeouts) and so is *not* itself replayed state. The bit-identity story
lives one layer up — ``runtime/lockstep.py`` sequences frame delivery into
deterministic epochs over this transport; the digests cover protocol
events, never transport timing.
"""

from __future__ import annotations

import asyncio
import collections
import hashlib
import threading
import time
from typing import Any, Callable, Optional

from p2pdl_tpu.protocol.transport import _LEN, MAX_FRAME
from p2pdl_tpu.utils import telemetry

Handler = Callable[[int, bytes], None]  # (src_id, data) -> None

__all__ = [
    "AsyncTCPTransport",
    "recv_frame_async",
    "send_frame_async",
    "DEFAULT_HIGH_WATER",
]

DEFAULT_HIGH_WATER = 512


async def recv_frame_async(reader: asyncio.StreamReader) -> Optional[bytes]:
    """Read one length-prefixed frame; None on EOF/reset/oversize.

    The oversize contract matches :func:`transport.recv_frame`: a length
    beyond ``MAX_FRAME`` means the stream is unframeable garbage, the
    event is counted under the rejected series, and the caller must close
    the connection (the bytes that follow cannot be resynchronized).
    """
    try:
        header = await reader.readexactly(_LEN.size)
    except (asyncio.IncompleteReadError, ConnectionError, OSError):
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        telemetry.counter(
            "transport.messages", transport="aio", event="rejected"
        ).inc()
        return None
    try:
        return await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionError, OSError):
        return None


async def send_frame_async(writer: asyncio.StreamWriter, data: bytes) -> None:
    """Length-prefixed send + drain (the flow-control point)."""
    writer.write(_LEN.pack(len(data)) + data)
    await writer.drain()


class AsyncTCPTransport:
    """Pooled single-event-loop framed-TCP transport (see module docstring).

    Thread contract: ``send`` / ``add_peer`` / ``set_blocked`` /
    ``transport_stats`` / ``stop`` are thread-safe and callable from any
    protocol thread; everything touching sockets runs on the loop thread.
    ``handler`` is invoked on the loop thread and must not block — hand
    off to a queue/condition if the work is heavy.
    """

    def __init__(
        self,
        my_id: int,
        host: str,
        port: int,
        handler: Handler,
        high_water: int = DEFAULT_HIGH_WATER,
        dial_retries: int = 2,
        dial_backoff_s: float = 0.05,
        dial_timeout_s: float = 5.0,
        drain_timeout_s: float = 5.0,
        fault_filter: Optional[Callable[[int, bytes], int]] = None,
    ) -> None:
        if high_water < 1:
            raise ValueError("high_water must be >= 1")
        self.my_id = my_id
        self.host = host
        self.port = port
        self.handler = handler
        self.high_water = high_water
        self.dial_retries = dial_retries
        self.dial_backoff_s = dial_backoff_s
        self.dial_timeout_s = dial_timeout_s
        self.drain_timeout_s = drain_timeout_s
        self.fault_filter = fault_filter
        self.peers: dict[int, tuple[str, int]] = {}
        self._lock = threading.Lock()
        self._queues: dict[int, collections.deque[bytes]] = {}
        self._blocked: frozenset[int] = frozenset()
        self._stopped = False
        self._started = False
        # Loop-thread-only state (never touched off-loop after start()).
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._writers: dict[int, asyncio.StreamWriter] = {}
        self._wake_events: dict[int, asyncio.Event] = {}
        self._workers: dict[int, asyncio.Task] = {}
        self._conn_tasks: set[asyncio.Task] = set()
        self._down_until: dict[int, float] = {}
        self._down_streak: dict[int, int] = {}
        # Stats (always written under self._lock) — the /healthz source.
        self._open = 0
        self._dialed = 0
        self._accepted = 0
        self._retries = 0
        self._sent = 0
        self._delivered = 0
        self._send_failed = 0
        self._backpressure_dropped = 0
        self._partition_refused = 0
        self._fault_dropped = 0
        self._inflight = 0
        # Per-peer cumulative payload bytes (frame minus the src header) —
        # stats-dict material like queue_depth, never telemetry labels.
        self._tx_bytes: dict[int, int] = {}
        self._rx_bytes: dict[int, int] = {}
        self._c_sent = telemetry.counter("transport.messages", transport="aio", event="sent")
        self._c_bytes = telemetry.counter("transport.bytes", transport="aio", event="sent")
        self._c_fail = telemetry.counter("transport.messages", transport="aio", event="send_failed")
        self._c_deliver = telemetry.counter("transport.messages", transport="aio", event="delivered")
        self._c_bytes_deliver = telemetry.counter("transport.bytes", transport="aio", event="delivered")
        self._c_reject = telemetry.counter("transport.messages", transport="aio", event="rejected")
        self._c_retry = telemetry.counter("transport.messages", transport="aio", event="retry")
        self._c_partition = telemetry.counter("transport.messages", transport="aio", event="partitioned")
        self._c_fault_drop = telemetry.counter("transport.messages", transport="aio", event="fault_dropped")
        self._c_dup = telemetry.counter("transport.messages", transport="aio", event="duplicated")
        self._c_backpressure = telemetry.counter("transport.backpressure_dropped", transport="aio")
        self._c_dial = telemetry.counter("transport.connections", transport="aio", event="dialed")
        self._c_accept = telemetry.counter("transport.connections", transport="aio", event="accepted")
        self._g_open = telemetry.gauge("transport.connections_open", transport="aio")

    # ---- lifecycle ----------------------------------------------------------

    def add_peer(self, peer_id: int, host: str, port: int) -> None:
        with self._lock:
            self.peers[peer_id] = (host, port)

    def start(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name=f"aio-transport-{self.my_id}",
            daemon=True,
        )
        self._thread.start()
        fut = asyncio.run_coroutine_threadsafe(self._start_server(), self._loop)
        fut.result(timeout=10.0)

    async def _start_server(self) -> None:
        self._server = await asyncio.start_server(
            self._serve_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]  # resolve port 0

    def stop(self) -> None:
        """Drain queues (bounded), then tear everything down. Idempotent."""
        with self._lock:
            already = self._stopped
            self._stopped = True
            started = self._started
        if already or not started or self._loop is None:
            return
        deadline = time.monotonic() + self.drain_timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                pending = sum(len(q) for q in self._queues.values())
                pending += self._inflight
            if pending == 0:
                break
            time.sleep(0.01)
        fut = asyncio.run_coroutine_threadsafe(self._shutdown(), self._loop)
        try:
            fut.result(timeout=10.0)
        except Exception:  # noqa: BLE001 - teardown is best-effort, bounded
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        self._loop.close()

    async def _shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for task in sorted(self._workers.values(), key=lambda t: t.get_name()):
            task.cancel()
        for task in sorted(self._conn_tasks, key=lambda t: t.get_name()):
            task.cancel()
        for peer in sorted(self._writers):
            self._close_writer(self._writers[peer])
        self._writers.clear()
        await asyncio.sleep(0)  # let cancellations propagate

    @staticmethod
    def _close_writer(writer: asyncio.StreamWriter) -> None:
        try:
            writer.close()
        except Exception:  # noqa: BLE001 - teardown must not raise
            pass

    # ---- server side --------------------------------------------------------

    async def _serve_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        if task is not None:  # track for cancellation at shutdown
            self._conn_tasks.add(task)
            task.add_done_callback(self._conn_tasks.discard)
        with self._lock:
            self._accepted += 1
            self._open += 1
            self._g_open.set(self._open)
        self._c_accept.inc()
        try:
            await self._read_frames(reader)
        except asyncio.CancelledError:
            pass  # shutdown: fall through to the close
        finally:
            self._close_writer(writer)
            with self._lock:
                self._open -= 1
                self._g_open.set(self._open)

    async def _read_frames(self, reader: asyncio.StreamReader) -> None:
        """Deliver frames until EOF — serves both legacy one-shot senders
        and pooled peers, and doubles as the EOF watch on dialed
        connections."""
        while True:
            frame = await recv_frame_async(reader)
            if frame is None:
                return
            if len(frame) < _LEN.size:
                self._c_reject.inc()
                return
            (src,) = _LEN.unpack(frame[: _LEN.size])
            with self._lock:
                if src in self._blocked:
                    self._partition_refused += 1
                    cut = True
                else:
                    self._delivered += 1
                    self._rx_bytes[src] = (
                        self._rx_bytes.get(src, 0) + len(frame) - _LEN.size
                    )
                    cut = False
            if cut:
                self._c_partition.inc()
                continue
            self._c_deliver.inc()
            self._c_bytes_deliver.inc(len(frame) - _LEN.size)
            self.handler(src, frame[_LEN.size :])

    # ---- client side --------------------------------------------------------

    def send(self, dst: int, data: bytes) -> bool:
        """Enqueue one frame for ``dst``; never blocks.

        True means accepted into the peer's bounded queue (delivery is
        asynchronous and may still fail — the protocol's quorum/retry
        machinery owns that). False means refused here: unknown peer,
        blocked by a partition, queue at its high-water mark (the frame is
        dropped-newest and counted), or transport stopped.
        """
        loop = self._loop
        with self._lock:
            if self._stopped or not self._started or loop is None:
                return False
            if dst not in self.peers:
                self._send_failed += 1
                refusal = "fail"
            elif dst in self._blocked:
                self._partition_refused += 1
                refusal = "partition"
            else:
                q = self._queues.get(dst)
                if q is None:
                    q = collections.deque()
                    self._queues[dst] = q
                if len(q) >= self.high_water:
                    self._backpressure_dropped += 1
                    refusal = "backpressure"
                else:
                    q.append(data)
                    refusal = None
        if refusal == "fail":
            self._c_fail.inc()
            return False
        if refusal == "partition":
            self._c_partition.inc()
            return False
        if refusal == "backpressure":
            self._c_backpressure.inc()
            return False
        try:
            loop.call_soon_threadsafe(self._wake, dst)
        except RuntimeError:  # loop torn down between the check and the call
            return False
        return True

    def _wake(self, dst: int) -> None:
        ev = self._wake_events.get(dst)
        if ev is None:
            ev = asyncio.Event()
            self._wake_events[dst] = ev
            task = self._loop.create_task(self._peer_worker(dst))
            task.set_name(f"aio-worker-{self.my_id}-{dst}")
            self._workers[dst] = task
        ev.set()

    async def _peer_worker(self, dst: int) -> None:
        ev = self._wake_events[dst]
        while True:
            await ev.wait()
            ev.clear()
            while True:
                with self._lock:
                    q = self._queues.get(dst)
                    if not q:
                        break
                    data = q.popleft()
                    self._inflight += 1
                try:
                    await self._dispatch(dst, data)
                finally:
                    with self._lock:
                        self._inflight -= 1

    async def _dispatch(self, dst: int, data: bytes) -> None:
        """Apply the chaos-plane frame fate, then transmit each copy."""
        copies = 1
        if self.fault_filter is not None:
            copies = int(self.fault_filter(dst, data))
        if copies <= 0:
            with self._lock:
                self._fault_dropped += 1
            self._c_fault_drop.inc()
            return
        if copies > 1:
            self._c_dup.inc(copies - 1)
        for _ in range(copies):
            await self._transmit(dst, data)

    async def _transmit(self, dst: int, data: bytes) -> None:
        frame = _LEN.pack(self.my_id) + data
        for attempt in range(2):  # one reconnect after a stale pooled writer
            writer = await self._ensure_conn(dst)
            if writer is None:
                with self._lock:
                    self._send_failed += 1
                self._c_fail.inc()
                return
            try:
                await send_frame_async(writer, frame)
                with self._lock:
                    self._sent += 1
                    self._tx_bytes[dst] = self._tx_bytes.get(dst, 0) + len(data)
                self._c_sent.inc()
                self._c_bytes.inc(len(data))
                return
            except (ConnectionError, OSError):
                self._invalidate(dst)
                if attempt == 0:
                    self._c_retry.inc()
                    with self._lock:
                        self._retries += 1
        with self._lock:
            self._send_failed += 1
        self._c_fail.inc()

    async def _ensure_conn(self, dst: int) -> Optional[asyncio.StreamWriter]:
        writer = self._writers.get(dst)
        if writer is not None and not writer.is_closing():
            return writer
        with self._lock:
            addr = self.peers.get(dst)
            blocked = dst in self._blocked
        if addr is None or blocked:
            return None
        now = self._loop.time()
        if now < self._down_until.get(dst, 0.0):
            return None  # fail fast inside the down window
        backoff = self.dial_backoff_s
        for attempt in range(self.dial_retries + 1):
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(addr[0], addr[1]),
                    timeout=self.dial_timeout_s,
                )
                self._writers[dst] = writer
                self._down_until.pop(dst, None)
                self._down_streak.pop(dst, None)
                with self._lock:
                    self._dialed += 1
                    self._open += 1
                    self._g_open.set(self._open)
                self._c_dial.inc()
                task = self._loop.create_task(self._watch_conn(dst, reader, writer))
                task.set_name(f"aio-watch-{self.my_id}-{dst}")
                self._conn_tasks.add(task)
                task.add_done_callback(self._conn_tasks.discard)
                return writer
            except (ConnectionError, OSError, asyncio.TimeoutError):
                if attempt == self.dial_retries:
                    break
                self._c_retry.inc()
                with self._lock:
                    self._retries += 1
                # Deterministic jitter, keyed like the legacy sender: no
                # global RNG in replay-adjacent code.
                h = hashlib.sha256(
                    f"{self.my_id}|{dst}|{attempt}".encode()
                ).digest()
                await asyncio.sleep(backoff * (1.0 + h[0] / 255.0 * 0.5))
                backoff *= 2.0
        # Unreachable: open the fail-fast window, growing with the streak.
        streak = self._down_streak.get(dst, 0) + 1
        self._down_streak[dst] = streak
        window = min(self.dial_backoff_s * (2.0**streak), 2.0)
        self._down_until[dst] = self._loop.time() + window
        return None

    async def _watch_conn(
        self, dst: int, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        """Reader task on a dialed connection: delivers any frames the peer
        sends back on it and, crucially, notices EOF (a legacy peer closes
        after one frame) so the pool entry is invalidated promptly."""
        try:
            await self._read_frames(reader)
        finally:
            if self._writers.get(dst) is writer:
                del self._writers[dst]
            self._close_writer(writer)
            with self._lock:
                self._open -= 1
                self._g_open.set(self._open)

    def _invalidate(self, dst: int) -> None:
        writer = self._writers.pop(dst, None)
        if writer is not None:
            self._close_writer(writer)

    # ---- chaos plane --------------------------------------------------------

    def set_blocked(self, peer_ids) -> None:
        """Partition face: refuse sends to and frames from ``peer_ids`` and
        tear down any pooled connections to them — the cut is a real
        connection close, not a silent filter."""
        with self._lock:
            self._blocked = frozenset(peer_ids)
            blocked = sorted(self._blocked)
        if self._loop is not None and not self._loop.is_closed():
            self._loop.call_soon_threadsafe(self._cut_blocked, blocked)

    def _cut_blocked(self, blocked: list[int]) -> None:
        for peer in blocked:
            self._invalidate(peer)

    # ---- observability ------------------------------------------------------

    def transport_stats(self) -> dict[str, Any]:
        """JSON-ready snapshot for the orchestrator's ``/healthz`` transport
        block. Per-peer queue depths live here (a stats dict), never as
        telemetry labels — peer ids are unbounded identity values."""
        with self._lock:
            return {
                "transport": "aio",
                "open_connections": self._open,
                "dialed": self._dialed,
                "accepted": self._accepted,
                "retries": self._retries,
                "sent": self._sent,
                "delivered": self._delivered,
                "send_failed": self._send_failed,
                "backpressure_dropped": self._backpressure_dropped,
                "partition_refused": self._partition_refused,
                "fault_dropped": self._fault_dropped,
                "high_water": self.high_water,
                "blocked_peers": sorted(self._blocked),
                "tx_bytes": sum(self._tx_bytes.values()),
                "rx_bytes": sum(self._rx_bytes.values()),
                "tx_bytes_by_peer": {
                    str(p): b for p, b in sorted(self._tx_bytes.items())
                },
                "rx_bytes_by_peer": {
                    str(p): b for p, b in sorted(self._rx_bytes.items())
                },
                "queue_depth": {
                    str(p): len(q) for p, q in sorted(self._queues.items())
                },
            }
