"""PKI and signatures.

Capability parity with reference ``utils/crypto.py``: per-peer ECDSA P-256 /
SHA-256 keypairs (reference ``utils/crypto.py:42-48``), a ``KeyServer``
registry standing in for a PKI (reference ``utils/crypto.py:7-40`` — an
in-process trusted directory; ours is thread-safe and keyed by peer id), and
sign/verify (reference ``utils/crypto.py:50-101``).

Deliberate differences (documented): signatures cover a canonical SHA-256
digest of the update pytree rather than pickled bytes (the reference signs
``pickle.dumps`` output, ``utils/broadcast.py:19-21``, which is neither
canonical nor safe to deserialize from the network), and there is no
``verify_signature_2``-style ``return True`` stub (reference
``utils/crypto.py:61-62``).

Dependency gate: when ``cryptography`` is not installed the module falls
back to HMAC-SHA256 "keypairs" — the private and public halves share one
random 256-bit secret, sign is an HMAC tag, verify is a constant-time tag
compare. This preserves every protocol property the simulation exercises
(unforgeability without the key material, wrong-key rejection, canonical
digests, KeyServer substitution guard) but is SYMMETRIC — anyone holding
the "public" half can also sign — so it is simulation-only and the
serialized form carries a distinct ``P2PDL HMAC`` PEM marker that a real
PKI would never accept. ``HAVE_CRYPTOGRAPHY`` reports which backend is
live.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os
import threading

import numpy as np

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec

    HAVE_CRYPTOGRAPHY = True
except ImportError:  # pragma: no cover - exercised only on bare images
    HAVE_CRYPTOGRAPHY = False


_HMAC_PEM_HEADER = b"-----BEGIN P2PDL HMAC-SHA256 KEY-----\n"
_HMAC_PEM_FOOTER = b"\n-----END P2PDL HMAC-SHA256 KEY-----\n"


class _HmacPublicKey:
    """Fallback 'public' key: shares the signer's secret (symmetric MAC)."""

    __slots__ = ("_secret",)

    def __init__(self, secret: bytes) -> None:
        self._secret = secret

    def _tag(self, data: bytes) -> bytes:
        return _hmac.new(self._secret, data, hashlib.sha256).digest()


class _HmacPrivateKey:
    """Fallback private key: HMAC-SHA256 over a random 256-bit secret."""

    __slots__ = ("_secret",)

    def __init__(self, secret: bytes | None = None) -> None:
        # p2plint: disable=determinism-entropy -- sanctioned: signing-key generation; keys are identity, not replayed state
        self._secret = secret if secret is not None else os.urandom(32)

    def sign(self, data: bytes) -> bytes:
        return _hmac.new(self._secret, data, hashlib.sha256).digest()

    def public_key(self) -> _HmacPublicKey:
        return _HmacPublicKey(self._secret)


def generate_key_pair():
    """ECDSA keypair on SECP256R1 (reference ``utils/crypto.py:42-48``);
    HMAC fallback when ``cryptography`` is unavailable (see module doc)."""
    if not HAVE_CRYPTOGRAPHY:
        private_key = _HmacPrivateKey()
        return private_key, private_key.public_key()
    private_key = ec.generate_private_key(ec.SECP256R1())
    return private_key, private_key.public_key()


def sign_data(private_key, data: bytes) -> bytes:
    """ECDSA/SHA-256 signature over ``data`` (reference ``utils/crypto.py:50-59``)."""
    if isinstance(private_key, _HmacPrivateKey):
        return private_key.sign(data)
    return private_key.sign(data, ec.ECDSA(hashes.SHA256()))


def verify_signature(public_key, signature: bytes, data: bytes) -> bool:
    """True iff ``signature`` is valid for ``data`` (reference
    ``utils/crypto.py:64-101``, minus the KeyServer lookup — see
    :meth:`KeyServer.verify`)."""
    if isinstance(public_key, _HmacPublicKey):
        return _hmac.compare_digest(public_key._tag(data), signature)
    try:
        public_key.verify(signature, data, ec.ECDSA(hashes.SHA256()))
        return True
    except InvalidSignature:
        return False


def digest_update(update) -> bytes:
    """Canonical SHA-256 digest of an update pytree.

    Hashes each leaf's path, shape, dtype, and raw little-endian bytes in
    sorted-path order — a stable serialization, unlike pickle. This is the
    only device->host transfer authentication requires (32-byte output).
    """
    import jax

    h = hashlib.sha256()
    leaves = jax.tree_util.tree_flatten_with_path(update)[0]
    for path, leaf in leaves:
        arr = np.asarray(leaf)
        h.update(jax.tree_util.keystr(path).encode())
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.digest()


def make_segment_digester(segments):
    """Per-row hasher over VARIABLE-WIDTH byte segments.

    ``segments`` is ``[(header_bytes, nbytes), ...]``: each row is a
    concatenation of fixed (but per-segment different) widths, and the
    digest interleaves each segment's header with its bytes — the framing
    both the dense digest pack (:func:`make_row_digester`, whose segments
    are ``row_shape x dtype.itemsize``) and the compressed pack (segments
    are ``ops.delta_codec`` wire widths, headers carry the codec
    parameters) reduce to. Headers and offsets are precomputed once; per
    row only SHA-256 runs (which releases the GIL on large buffers, so
    rows thread-pool well).
    """
    spans: list[tuple[bytes, int, int]] = []
    offset = 0
    for header, nbytes in segments:
        spans.append((bytes(header), offset, offset + nbytes))
        offset += nbytes
    total = offset

    def hash_row(row) -> bytes:
        view = memoryview(np.ascontiguousarray(row)).cast("B")
        if len(view) != total:
            raise ValueError(
                f"packed row has {len(view)} bytes, layout expects {total}"
            )
        h = hashlib.sha256()
        for header, start, end in spans:
            h.update(header)
            h.update(view[start:end])
        return h.digest()

    hash_row.total_bytes = total
    return hash_row


def make_row_digester(leaf_meta):
    """Per-row hasher for the single-transfer digest path, bit-compatible
    with :func:`digest_update`.

    ``leaf_meta`` is ``[(keystr, row_shape, dtype_str, nbytes), ...]`` in
    ``tree_flatten_with_path`` order — one entry per leaf of the update
    tree, describing a single trainer's slice (the peer axis removed).
    The returned ``hash_row(row)`` takes one packed ``[total_bytes]``
    uint8 buffer (that trainer's leaf slices concatenated in meta order,
    each in C-contiguous little-endian layout, exactly what
    ``parallel.round.build_digest_pack_fn`` produces) and interleaves the
    canonical per-leaf header bytes — keystr + str(shape) + str(dtype) —
    with the corresponding byte segments, so the digest is bitwise equal
    to ``digest_update`` of that trainer's slice tree. A specialization of
    :func:`make_segment_digester` to dense (shape x itemsize) widths.
    """
    return make_segment_digester(
        (
            key.encode() + str(tuple(row_shape)).encode() + dtype_str.encode(),
            nbytes,
        )
        for key, row_shape, dtype_str, nbytes in leaf_meta
    )


def public_key_pem(public_key) -> bytes:
    if isinstance(public_key, _HmacPublicKey):
        return _HMAC_PEM_HEADER + public_key._secret.hex().encode() + _HMAC_PEM_FOOTER
    return public_key.public_bytes(
        serialization.Encoding.PEM, serialization.PublicFormat.SubjectPublicKeyInfo
    )


def public_key_from_pem(pem: bytes):
    if pem.startswith(_HMAC_PEM_HEADER):
        body = pem[len(_HMAC_PEM_HEADER) : -len(_HMAC_PEM_FOOTER)]
        return _HmacPublicKey(bytes.fromhex(body.decode()))
    return serialization.load_pem_public_key(pem)


class KeyServer:
    """Trusted public-key directory keyed by peer id.

    The reference's ``KeyServer`` is an unlocked in-process dict keyed by
    ``(addr, port)`` (reference ``utils/crypto.py:7-40``) mutated from
    concurrent threads; this one is thread-safe, stores PEM (so it works
    across process boundaries), and refuses re-registration with a different
    key (key-substitution guard).
    """

    def __init__(self) -> None:
        self._keys: dict[int, bytes] = {}
        # Deserialized-key cache: verify() runs per BRB message (O(n^2) per
        # round) and must not re-parse PEM every time.
        self._cache: dict[int, object] = {}
        self._lock = threading.Lock()

    def register_key(self, peer_id: int, public_key) -> None:
        pem = public_key_pem(public_key)
        with self._lock:
            existing = self._keys.get(peer_id)
            if existing is not None and existing != pem:
                raise ValueError(f"peer {peer_id} already registered with a different key")
            self._keys[peer_id] = pem
            self._cache[peer_id] = public_key

    def get_key(self, peer_id: int):
        with self._lock:
            key = self._cache.get(peer_id)
            if key is not None:
                return key
            pem = self._keys.get(peer_id)
        if pem is None:
            raise KeyError(f"no key registered for peer {peer_id}")
        key = public_key_from_pem(pem)
        with self._lock:
            self._cache[peer_id] = key
        return key

    def has_key(self, peer_id: int) -> bool:
        """True iff ``peer_id`` is a registered peer — the membership test
        protocol validators use to bound the sender universe."""
        with self._lock:
            return peer_id in self._keys

    def verify(self, peer_id: int, signature: bytes, data: bytes) -> bool:
        """Verify ``data`` against peer ``peer_id``'s registered key
        (reference ``utils/crypto.py:64-101`` folds this lookup into
        ``verify_signature``)."""
        try:
            key = self.get_key(peer_id)
        except KeyError:
            return False
        return verify_signature(key, signature, data)

    def __len__(self) -> int:
        with self._lock:
            return len(self._keys)
