"""Host-side trust plane: PKI, signatures, Byzantine Reliable Broadcast.

The data plane (model math) runs on-device as XLA collectives; this package
is the control/trust plane that the reference conflates with it (reference
``node/node.py`` carries weights and protocol messages in the same pickled
TCP stream, SURVEY §1). Signatures operate on SHA-256 digests of canonically
serialized updates, so only 32 bytes ever cross the host boundary per
authentication, and the device pipeline never blocks on crypto.
"""

from p2pdl_tpu.protocol.crypto import (
    KeyServer,
    digest_update,
    generate_key_pair,
    sign_data,
    verify_signature,
)
from p2pdl_tpu.protocol.brb import BRBConfig, BRBInstance, BRBMessage, Broadcaster

__all__ = [
    "KeyServer",
    "digest_update",
    "generate_key_pair",
    "sign_data",
    "verify_signature",
    "BRBConfig",
    "BRBInstance",
    "BRBMessage",
    "Broadcaster",
]
