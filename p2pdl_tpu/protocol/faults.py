"""Deterministic chaos plane: declarative fault plans, a seeded injector,
and the heartbeat/suspicion failure detector.

The reference has no failure handling at all — one silent peer stalls its
round forever (reference ``node/node.py:73``; the ``utils/waiting.py``
timeout is inoperative, SURVEY §2 #13). This module is the other half of
surviving that: PR 1's telemetry *counts* failures, the chaos plane
*injects* them on purpose and the failure detector lets rounds degrade
gracefully instead of timing out.

Design constraints:

- **Declarative**: a :class:`FaultPlan` is a frozen value object (JSON
  round-trippable) listing per-round crash-stop / crash-recover schedules,
  message drop/corrupt/delay/duplicate/reorder rates, and network
  partitions with heal times. Named scenarios (:func:`scenario`) build
  plans sized to a config.
- **Deterministic**: every probabilistic decision is a pure function of
  ``(plan.seed, round, draw-counter, src, dst)`` via SHA-256 — no
  wall-clock, no global RNG state — so a re-run with the same seed
  replays the exact same fault schedule and the driver's RoundRecord
  stream is bit-identical (the acceptance bar for every robustness claim).
- **Transport-applied**: the injector installs hooks on the extended
  :class:`~p2pdl_tpu.protocol.transport.InMemoryHub` (drop/corrupt/delay/
  duplicate/reorder + partition sets); crashes additionally silence a
  peer's heartbeats so the detector's live-membership view converges.

Scope note (see ROADMAP): the chaos plane models *omission* faults
(crashes, loss, partitions, reordering) and bit corruption. Byzantine
*equivocation* — a peer lying consistently — stays with the trust plane's
``_TrustPlane.lie_digests`` / ``broadcast_equivocating`` hooks; both
compose in one experiment.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import json
import os
from typing import Optional

from p2pdl_tpu.utils import flight, telemetry


@dataclasses.dataclass(frozen=True)
class CrashSpec:
    """Crash-stop (``recover_round=None``) or crash-recover schedule for one
    peer: dark from ``at_round`` (inclusive) until ``recover_round``
    (exclusive). A dark peer's messages are dropped in both directions and
    its heartbeats go unanswered."""

    peer: int
    at_round: int
    recover_round: Optional[int] = None

    def __post_init__(self) -> None:
        if self.peer < 0:
            raise ValueError(f"crash peer must be >= 0, got {self.peer}")
        if self.at_round < 0:
            raise ValueError(f"at_round must be >= 0, got {self.at_round}")
        if self.recover_round is not None and self.recover_round <= self.at_round:
            raise ValueError(
                f"recover_round ({self.recover_round}) must be after "
                f"at_round ({self.at_round})"
            )


@dataclasses.dataclass(frozen=True)
class PartitionSpec:
    """Network partition active on rounds ``[at_round, heal_round)``: a
    message is cut iff src and dst sit in *different* listed groups (peers
    absent from every group are unrestricted — partial partitions are a
    thing)."""

    groups: tuple[tuple[int, ...], ...]
    at_round: int
    heal_round: int

    def __post_init__(self) -> None:
        if len(self.groups) < 2:
            raise ValueError("a partition needs at least 2 groups")
        seen: set[int] = set()
        for g in self.groups:
            for p in g:
                if p in seen:
                    raise ValueError(f"peer {p} appears in two partition groups")
                seen.add(p)
        if self.heal_round <= self.at_round:
            raise ValueError(
                f"heal_round ({self.heal_round}) must be after "
                f"at_round ({self.at_round})"
            )


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Seeded, declarative fault schedule for one experiment."""

    name: str = "custom"
    seed: int = 0
    crashes: tuple[CrashSpec, ...] = ()
    partitions: tuple[PartitionSpec, ...] = ()
    drop_rate: float = 0.0
    corrupt_rate: float = 0.0
    delay_rate: float = 0.0
    max_delay_ticks: int = 3  # delay draws land uniformly in [1, this]
    duplicate_rate: float = 0.0
    reorder_rate: float = 0.0
    # Per-leg heartbeat loss (ping + pong are two independent draws);
    # None = reuse drop_rate, so the detector sees the same network the
    # protocol does.
    heartbeat_loss_rate: Optional[float] = None

    def __post_init__(self) -> None:
        for field in (
            "drop_rate", "corrupt_rate", "delay_rate",
            "duplicate_rate", "reorder_rate",
        ):
            v = getattr(self, field)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{field} must be in [0, 1], got {v}")
        if self.heartbeat_loss_rate is not None and not (
            0.0 <= self.heartbeat_loss_rate <= 1.0
        ):
            raise ValueError(
                f"heartbeat_loss_rate must be in [0, 1], got "
                f"{self.heartbeat_loss_rate}"
            )
        if self.max_delay_ticks < 1:
            raise ValueError(
                f"max_delay_ticks must be >= 1, got {self.max_delay_ticks}"
            )
        # Normalize list inputs (JSON round-trip) to tuples so the plan
        # stays hashable/frozen.
        if not isinstance(self.crashes, tuple):
            object.__setattr__(self, "crashes", tuple(self.crashes))
        if not isinstance(self.partitions, tuple):
            object.__setattr__(self, "partitions", tuple(self.partitions))

    @property
    def hb_loss(self) -> float:
        return (
            self.drop_rate
            if self.heartbeat_loss_rate is None
            else self.heartbeat_loss_rate
        )

    def is_omission_only(self) -> bool:
        """True when every configured fault is an *omission* — crashes,
        message drops, partitions, heartbeat loss — and nothing mutates
        content or ordering (corrupt/delay/duplicate/reorder all zero).

        Omission-only plans have a key closure property: with no hub
        installed, their entire effect on a run is the membership schedule
        (``FaultInjector.begin_round`` + ``heartbeat_ok``), which is a
        pure function of ``(plan, round)`` — precomputable for a whole
        block of rounds without running any of them. ``run_fused`` leans
        on exactly this to compose fused device blocks with chaos."""
        return (
            self.corrupt_rate == 0.0
            and self.delay_rate == 0.0
            and self.duplicate_rate == 0.0
            and self.reorder_rate == 0.0
        )

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        d = dict(d)
        d["crashes"] = tuple(
            c if isinstance(c, CrashSpec) else CrashSpec(**c)
            for c in d.get("crashes", ())
        )
        d["partitions"] = tuple(
            p
            if isinstance(p, PartitionSpec)
            else PartitionSpec(
                groups=tuple(tuple(g) for g in p["groups"]),
                at_round=p["at_round"],
                heal_round=p["heal_round"],
            )
            for p in d.get("partitions", ())
        )
        return cls(**d)

    @classmethod
    def from_json(cls, s: str) -> "FaultPlan":
        return cls.from_dict(json.loads(s))


SCENARIOS = (
    "baseline",
    "lossy",
    "partition_heal",
    "crash_drop_partition",
    "crash_churn",
)


def scenario(
    name: str, num_peers: int, rounds: int, f: int = 1, seed: int = 0
) -> FaultPlan:
    """Build a named fault plan sized to ``(num_peers, rounds, f)``.

    - ``baseline``: no faults (the control arm).
    - ``lossy``: a bad network — drops, corruption, delays, duplicates,
      reordering — but no process faults.
    - ``partition_heal``: one mid-experiment split that heals a round later.
    - ``crash_drop_partition``: the acceptance scenario — crash-stop ``f``
      peers mid-experiment + 10% message drop + one partition/heal.
    - ``crash_churn``: crash-recover churn (a peer leaves and returns) on a
      lightly lossy network.
    """
    if num_peers < 2:
        raise ValueError(f"scenarios need >= 2 peers, got {num_peers}")
    crash_round = max(1, rounds // 4)
    part_round = max(crash_round + 1, rounds // 2)
    heal_round = part_round + 1
    # Crash the top peer ids: deterministic, and at small scale they stay
    # clear of the low ids tests like to pin as trainers.
    crash_ids = tuple(num_peers - 1 - i for i in range(f))
    # Partition: split off the two highest non-crashed-adjacent peers so a
    # quorum-capable majority side always exists (n - f - 2 > 3f holds for
    # every config the trust plane accepts at these sizes).
    minority = tuple(sorted(crash_ids) + [min(crash_ids) - 1])
    majority = tuple(p for p in range(num_peers) if p not in minority)
    if name == "baseline":
        return FaultPlan(name=name, seed=seed)
    if name == "lossy":
        return FaultPlan(
            name=name, seed=seed, drop_rate=0.05, corrupt_rate=0.01,
            delay_rate=0.2, max_delay_ticks=3, duplicate_rate=0.05,
            reorder_rate=0.1,
        )
    if name == "partition_heal":
        return FaultPlan(
            name=name, seed=seed,
            partitions=(
                PartitionSpec(
                    groups=(majority, minority),
                    at_round=part_round, heal_round=heal_round,
                ),
            ),
        )
    if name == "crash_drop_partition":
        return FaultPlan(
            name=name, seed=seed, drop_rate=0.10,
            crashes=tuple(CrashSpec(peer=p, at_round=crash_round) for p in crash_ids),
            partitions=(
                PartitionSpec(
                    groups=(majority, minority),
                    at_round=part_round, heal_round=heal_round,
                ),
            ),
        )
    if name == "crash_churn":
        churn = tuple(
            CrashSpec(
                peer=p, at_round=crash_round,
                recover_round=min(rounds, crash_round + 2),
            )
            for p in crash_ids
        )
        return FaultPlan(name=name, seed=seed, drop_rate=0.02, crashes=churn)
    raise ValueError(f"unknown scenario {name!r}; known: {', '.join(SCENARIOS)}")


def resolve_plan(
    spec, num_peers: int, rounds: int, f: int = 1, seed: int = 0
) -> FaultPlan:
    """Resolve a plan spec: a FaultPlan passes through; a dict builds one; a
    string is a scenario name, inline JSON (``{...}``), or a JSON file path."""
    if isinstance(spec, FaultPlan):
        return spec
    if isinstance(spec, dict):
        return FaultPlan.from_dict(spec)
    if isinstance(spec, str):
        if spec in SCENARIOS:
            return scenario(spec, num_peers, rounds, f=f, seed=seed)
        if spec.lstrip().startswith("{"):
            return FaultPlan.from_json(spec)
        if os.path.exists(spec):
            with open(spec) as fh:
                return FaultPlan.from_json(fh.read())
        raise ValueError(
            f"fault plan {spec!r} is neither a known scenario "
            f"({', '.join(SCENARIOS)}), inline JSON, nor an existing file"
        )
    raise TypeError(f"cannot resolve a fault plan from {type(spec).__name__}")


class FailureDetector:
    """Heartbeat/suspicion table -> live membership view.

    Each round every peer is probed (ping + pong through the fault model);
    ``suspicion_threshold`` *consecutive* misses mark a peer suspected —
    excluded from trainer sampling and from the BRB live-quorum set — and
    one successful heartbeat clears it (crash-recover peers re-join). This
    is the "failure-suspicion table" the config's selection notes
    anticipated: observational runtime state, deliberately not
    checkpointed (a resumed experiment starts with a clean slate, like any
    real failure detector).

    Partition note: the view is the *aggregate* over all observers — in a
    partitioned network every side still hosts live peers, so partitions
    degrade delivery (and show up as BRB failures) without evicting
    members; only crashes and sustained loss do.
    """

    def __init__(self, num_peers: int, suspicion_threshold: int = 2) -> None:
        if suspicion_threshold < 1:
            raise ValueError(
                f"suspicion_threshold must be >= 1, got {suspicion_threshold}"
            )
        self.num_peers = num_peers
        self.suspicion_threshold = suspicion_threshold
        self.misses = [0] * num_peers
        self.suspected: set[int] = set()

    def observe(
        self, round_idx: int, responded: set[int]
    ) -> tuple[list[int], list[int]]:
        """Fold one round of heartbeat outcomes into the table; returns
        ``(newly_suspected, recovered)`` (both sorted)."""
        newly: list[int] = []
        recovered: list[int] = []
        for p in range(self.num_peers):
            if p in responded:
                self.misses[p] = 0
                if p in self.suspected:
                    self.suspected.discard(p)
                    recovered.append(p)
                    flight.record("unsuspect", round=round_idx, peer=p)
            else:
                self.misses[p] += 1
                if (
                    self.misses[p] >= self.suspicion_threshold
                    and p not in self.suspected
                ):
                    self.suspected.add(p)
                    newly.append(p)
                    flight.record(
                        "suspect", round=round_idx, peer=p, misses=self.misses[p]
                    )
        return newly, recovered

    def live(self) -> list[int]:
        return [p for p in range(self.num_peers) if p not in self.suspected]


class FaultInjector:
    """Applies a :class:`FaultPlan` to an experiment, deterministically.

    Per round the driver calls :meth:`begin_round` (advances crash/partition
    state, returns the round's fault *events*) and :meth:`apply_round`
    (pushes the active partition onto the hub). The message-fate hooks
    installed by :meth:`install` draw from a counter-keyed SHA-256 PRF, so
    identical traffic sees identical faults across runs.
    """

    def __init__(self, plan: FaultPlan, num_peers: int) -> None:
        for c in plan.crashes:
            if c.peer >= num_peers:
                raise ValueError(
                    f"crash peer {c.peer} out of range for {num_peers} peers"
                )
        for part in plan.partitions:
            for g in part.groups:
                for p in g:
                    if p >= num_peers:
                        raise ValueError(
                            f"partition peer {p} out of range for "
                            f"{num_peers} peers"
                        )
        self.plan = plan
        self.num_peers = num_peers
        self.crashed: set[int] = set()
        self.partition: Optional[tuple[tuple[int, ...], ...]] = None
        self.injected: collections.Counter = collections.Counter()  # cumulative
        self.round_injected: collections.Counter = collections.Counter()
        self._round = -1
        self._draws = 0

    # -- deterministic PRF ---------------------------------------------
    def _u(self, *key) -> float:
        """Uniform in [0, 1) as a pure function of (plan.seed, key)."""
        h = hashlib.sha256(
            ("fault|%d|" % self.plan.seed + "|".join(str(k) for k in key)).encode()
        ).digest()
        return int.from_bytes(h[:8], "big") / 2.0**64

    def _count(self, kind: str) -> None:
        self.injected[kind] += 1
        self.round_injected[kind] += 1
        telemetry.counter("chaos.faults", type=kind).inc()

    # -- round lifecycle ------------------------------------------------
    def begin_round(self, round_idx: int) -> list[dict]:
        """Advance crash/partition state to ``round_idx``; returns this
        round's fault events (crash/recover/partition/heal) and resets the
        per-round injected-message counter."""
        self._round = round_idx
        self._draws = 0
        self.round_injected = collections.Counter()
        events: list[dict] = []
        for c in self.plan.crashes:
            if c.at_round == round_idx:
                self.crashed.add(c.peer)
                events.append({"event": "crash", "peer": c.peer})
                self._count("crash")
            if c.recover_round == round_idx:
                self.crashed.discard(c.peer)
                events.append({"event": "recover", "peer": c.peer})
                self._count("recover")
        active = None
        for part in self.plan.partitions:
            if part.at_round == round_idx:
                events.append(
                    {"event": "partition", "groups": [list(g) for g in part.groups]}
                )
                self._count("partition")
            if part.heal_round == round_idx:
                events.append({"event": "heal"})
                self._count("heal")
            if part.at_round <= round_idx < part.heal_round:
                active = part.groups
        self.partition = active
        for ev in events:
            flight.record("fault", round=round_idx, **ev)
        return events

    def apply_round(self, hub) -> None:
        """Push the current partition state onto the hub (None = no hub, the
        fault plan still drives membership through heartbeats)."""
        if hub is None:
            return
        if self.partition is not None:
            hub.set_partition(self.partition)
        else:
            hub.clear_partition()

    def install(self, hub) -> None:
        """Install the message-fate hooks on an InMemoryHub."""
        hub.drop = self._drop
        if self.plan.corrupt_rate > 0.0:
            hub.corrupt = self._corrupt
        hub.delay = self._delay
        hub.duplicate = self._duplicate
        hub.reorder = self._reorder

    # -- message fates (InMemoryHub hook signatures) --------------------
    def _drop(self, src: int, dst: int, data: bytes) -> bool:
        if src in self.crashed or dst in self.crashed:
            self._count("crash_drop")
            return True
        if self.plan.drop_rate <= 0.0:
            return False
        self._draws += 1
        if self._u(self._round, "drop", self._draws, src, dst) < self.plan.drop_rate:
            self._count("drop")
            return True
        return False

    def _corrupt(self, src: int, dst: int, data: bytes) -> bytes:
        if self.plan.corrupt_rate <= 0.0 or not data:
            return data
        self._draws += 1
        if self._u(self._round, "corrupt", self._draws, src, dst) >= self.plan.corrupt_rate:
            return data
        self._count("corrupt")
        pos = int(self._u(self._round, "cpos", self._draws, src, dst) * len(data))
        flipped = bytearray(data)
        flipped[pos] ^= 0xFF
        return bytes(flipped)

    def _delay(self, src: int, dst: int, data: bytes) -> int:
        if self.plan.delay_rate <= 0.0:
            return 0
        self._draws += 1
        if self._u(self._round, "delay", self._draws, src, dst) >= self.plan.delay_rate:
            return 0
        self._count("delay")
        ticks = 1 + int(
            self._u(self._round, "dticks", self._draws, src, dst)
            * self.plan.max_delay_ticks
        )
        return min(ticks, self.plan.max_delay_ticks)

    def _duplicate(self, src: int, dst: int, data: bytes) -> bool:
        if self.plan.duplicate_rate <= 0.0:
            return False
        self._draws += 1
        if self._u(self._round, "dup", self._draws, src, dst) < self.plan.duplicate_rate:
            self._count("duplicate")
            return True
        return False

    def _reorder(self, src: int, dst: int, data: bytes) -> bool:
        if self.plan.reorder_rate <= 0.0:
            return False
        self._draws += 1
        if self._u(self._round, "reorder", self._draws, src, dst) < self.plan.reorder_rate:
            self._count("reorder")
            return True
        return False

    # -- frame-boundary fates (real-transport chaos) --------------------
    def frame_fate(
        self, round_idx: int, src: int, dst: int, route_seq: int, size: int = 0
    ) -> dict:
        """Deterministic fate for the ``route_seq``-th frame ``src -> dst``
        of ``round_idx``, decided at the transport's frame boundary.

        Unlike the hub hooks (which draw from a *global* per-round counter
        and are therefore a function of total traffic order), this is keyed
        purely on ``(seed, round, src, dst, route_seq)`` — routes draw
        independently, so the schedule is identical whether the frames
        cross one in-memory mesh or N real TCP processes interleaving
        arbitrarily. Returns ``{"drop", "copies", "delay_ticks",
        "corrupt_pos"}``; the caller transmits ``copies`` copies (0 when
        dropped), holds delayed frames for ``delay_ticks`` delivery
        epochs, and XOR-flips the byte at ``corrupt_pos`` when not None.
        Crashed endpoints drop everything, both directions, like the hub
        path."""
        if src in self.crashed or dst in self.crashed:
            self._count("crash_drop")
            return {"drop": True, "copies": 0, "delay_ticks": 0, "corrupt_pos": None}
        key = (round_idx, "frame", src, dst, route_seq)
        if (
            self.plan.drop_rate > 0.0
            and self._u(*key, "drop") < self.plan.drop_rate
        ):
            self._count("drop")
            return {"drop": True, "copies": 0, "delay_ticks": 0, "corrupt_pos": None}
        copies = 1
        if (
            self.plan.duplicate_rate > 0.0
            and self._u(*key, "dup") < self.plan.duplicate_rate
        ):
            self._count("duplicate")
            copies = 2
        delay_ticks = 0
        if (
            self.plan.delay_rate > 0.0
            and self._u(*key, "delay") < self.plan.delay_rate
        ):
            self._count("delay")
            delay_ticks = min(
                1 + int(self._u(*key, "dticks") * self.plan.max_delay_ticks),
                self.plan.max_delay_ticks,
            )
        corrupt_pos = None
        if (
            self.plan.corrupt_rate > 0.0
            and size > 0
            and self._u(*key, "corrupt") < self.plan.corrupt_rate
        ):
            self._count("corrupt")
            corrupt_pos = int(self._u(*key, "cpos") * size)
        return {
            "drop": False,
            "copies": copies,
            "delay_ticks": delay_ticks,
            "corrupt_pos": corrupt_pos,
        }

    def frame_filter(self, my_id: int):
        """Build an ``AsyncTCPTransport.fault_filter`` for host ``my_id``:
        per-destination frame counters feed :meth:`frame_fate`, and the
        returned copy count (0 = drop) is applied on the *real* connection.
        Delay/corrupt fates are not applied at this layer — wall-clock
        delay is nondeterministic by nature; the lockstep runner holds and
        mutates frames itself where replay-exactness is claimed."""
        counters: collections.Counter = collections.Counter()

        def fate(dst: int, data: bytes) -> int:
            seq = counters[dst]
            counters[dst] += 1
            f = self.frame_fate(self._round, my_id, dst, seq)
            return 0 if f["drop"] else f["copies"]

        return fate

    def cut(self, src: int, dst: int) -> bool:
        """Does the active partition cut ``src -> dst``? (Same semantics as
        ``InMemoryHub._cut``: only cross-group pairs are cut; peers in no
        group are unrestricted.)"""
        if self.partition is None:
            return False
        src_g = dst_g = None
        for i, g in enumerate(self.partition):
            if src in g:
                src_g = i
            if dst in g:
                dst_g = i
        return src_g is not None and dst_g is not None and src_g != dst_g

    def partition_peers(self, my_id: int) -> frozenset[int]:
        """Peers unreachable from ``my_id`` under the active partition — the
        set a real transport passes to ``set_blocked`` so the cut closes
        actual connections."""
        if self.partition is None:
            return frozenset()
        mine = None
        for i, g in enumerate(self.partition):
            if my_id in g:
                mine = i
        if mine is None:
            return frozenset()
        return frozenset(
            p
            for i, g in enumerate(self.partition)
            if i != mine
            for p in g
        )

    # -- heartbeats -----------------------------------------------------
    def heartbeat_ok(self, round_idx: int, peer: int) -> bool:
        """Did ``peer``'s heartbeat land this round? Crashed peers never
        answer; otherwise the ping and the pong each survive the per-leg
        loss rate. Keyed directly on (round, peer) — independent of hub
        traffic — so the membership schedule is a closed function of the
        plan."""
        if peer in self.crashed:
            return False
        rate = self.plan.hb_loss
        if rate <= 0.0:
            return True
        return (
            self._u(round_idx, "hb", peer, 0) >= rate
            and self._u(round_idx, "hb", peer, 1) >= rate
        )
