"""Gauge-driven overlap autotuner: deterministic hill-climb over the
driver's overlap knobs.

The overlap levers landed as hand-picked constants — ``pipeline_depth``
(depth-k deferred readbacks) and ``rounds_per_call`` (fused scan-block
length) — while the performance plane already measures their effect every
round: ``driver.rounds_per_sec``, ``driver.overlap_efficiency``,
``driver.inflight_rounds``, ``driver.mfu``, and the recompile sentinel.
This module closes the loop: a small controller that reads ONLY recorded
per-round observations (round durations from the RoundRecord stream;
gauge readings ride along for attribution) and walks one knob along a
fixed ladder of candidate values, turning the constants into measured
optima per model/backend.

Determinism contract (policed by p2plint's replay-scope rules — this
file lives in ``parallel/``): the controller is a pure function of its
observation sequence. No wall clock, no entropy, no set iteration — two
runs fed identical observation streams produce identical knob
trajectories (test-pinned in ``tests/test_autotune.py``). Wall-clock
VALUES do flow in as observations (that is the point: the knob converges
to the measured optimum), but the DECISION RULE stays replayable.

Recompile accounting stays attributable: every distinct
``rounds_per_call`` the tuner visits adds at most one compiled scan-block
shape, so the driver recomputes the sentinel's expected-compile budget
from ``fused_block_sizes()`` over the sizes already seen plus the
remaining schedule — retuning must never surface as a recompile anomaly
(test-pinned: sentinel quiet across retune events). The ladder being
finite is what makes that budget finite.
"""

from __future__ import annotations

import math
from typing import Any, Optional

# Candidate rungs per knob. Power-of-two spacing: each rung is at most one
# new compiled program shape (rounds_per_call) or one window size
# (pipeline_depth), and the throughput response is near-monotone in log
# space — exactly what a +-1-rung hill climb handles. The configured
# start value is spliced in if it is not already a rung.
_LADDERS: dict[str, tuple[int, ...]] = {
    "pipeline_depth": (1, 2, 4, 8),
    "rounds_per_call": (1, 2, 4, 8, 16, 32),
}


class HillClimb:
    """±1-rung hill climb on a fixed value ladder (higher score = better).

    Feed scores via :meth:`observe`; every ``window`` observations one
    :meth:`step` consumes them: the window mean becomes the current rung's
    score and the controller either records its incumbent's baseline,
    accepts a probe (beats the incumbent by ``rel_margin`` relative — the
    deadband that keeps run-to-run timing noise from flapping the knob),
    or rejects it and returns to the incumbent. A rejected direction is
    abandoned; when both directions (or the ladder edges) are exhausted
    the climb SETTLES and holds the incumbent for the rest of the run.
    Exploration is therefore bounded by the rungs actually visited, never
    the run length.
    """

    def __init__(
        self,
        name: str,
        ladder: tuple[int, ...],
        start: int,
        window: int = 4,
        rel_margin: float = 0.02,
    ) -> None:
        self.name = str(name)
        self.ladder = tuple(sorted(set(list(ladder) + [int(start)])))
        self.window = max(1, int(window))
        self.rel_margin = float(rel_margin)
        self.idx = self.ladder.index(int(start))
        self.best_idx = self.idx
        self.best_score: Optional[float] = None
        self.settled = False
        self.retunes = 0
        self._scores: list[float] = []
        self._dir = 1
        self._tried_up = False
        self._tried_down = False
        self.trajectory: list[int] = [self.current]
        self.events: list[dict[str, Any]] = []

    @property
    def current(self) -> int:
        return self.ladder[self.idx]

    def observe(self, score: float) -> None:
        s = float(score)
        if not self.settled and math.isfinite(s):
            self._scores.append(s)

    def ready(self) -> bool:
        return (not self.settled) and len(self._scores) >= self.window

    def _exhausted(self, d: int) -> bool:
        if d > 0:
            return self._tried_up or self.best_idx == len(self.ladder) - 1
        return self._tried_down or self.best_idx == 0

    def _next_probe(self) -> None:
        """From the incumbent, move onto the next unexplored neighbor rung
        — or settle when there is none."""
        for d in (self._dir, -self._dir):
            if not self._exhausted(d):
                self._dir = d
                self.idx = self.best_idx + d
                return
        self.idx = self.best_idx
        self.settled = True
        self.events.append({"event": "settled", "value": self.current})

    def step(self) -> int:
        """Consume a full observation window and advance one climb step;
        returns the knob value to use next (unchanged while the window is
        still filling or after settling)."""
        if not self.ready():
            return self.current
        s = sum(self._scores) / len(self._scores)
        self._scores = []
        self.retunes += 1
        if self.best_score is None or self.idx == self.best_idx:
            # Measure the incumbent, then go probe a neighbor.
            self.best_score = s
            self.events.append(
                {"event": "baseline", "value": self.current, "score": s}
            )
            self._next_probe()
        elif s > self.best_score * (1.0 + self.rel_margin):
            # Probe wins: it becomes the incumbent. Keep climbing the same
            # way; the rung behind is the old incumbent, already measured
            # worse, so that direction stays closed.
            self.events.append(
                {"event": "accept", "value": self.current, "score": s}
            )
            self.best_idx = self.idx
            self.best_score = s
            if self._dir > 0:
                self._tried_down = True
            else:
                self._tried_up = True
            self._next_probe()
        else:
            self.events.append(
                {"event": "reject", "value": self.current, "score": s}
            )
            if self._dir > 0:
                self._tried_up = True
            else:
                self._tried_down = True
            self._dir = -self._dir
            self._next_probe()
        self.trajectory.append(self.current)
        return self.current


class OverlapAutotuner:
    """Driver-facing wrapper: one :class:`HillClimb` on one overlap knob,
    scored by measured round throughput (``1 / duration_s``).

    Gauge readings (``overlap_efficiency``, ``inflight_rounds``, ``mfu``)
    are recorded for the perf summary — attribution, not decision inputs,
    so the decision rule remains a pure function of the duration stream
    and the trajectory is reproducible from the RoundRecord stream alone.
    """

    def __init__(
        self,
        knob: str,
        start: int,
        window: int = 4,
        rel_margin: float = 0.02,
        ladder: tuple[int, ...] | None = None,
    ) -> None:
        if ladder is None:
            if knob not in _LADDERS:
                raise ValueError(
                    f"unknown autotune knob {knob!r}; known: "
                    f"{sorted(_LADDERS)}"
                )
            ladder = _LADDERS[knob]
        self.knob = str(knob)
        self.climb = HillClimb(
            knob, tuple(ladder), start, window=window, rel_margin=rel_margin
        )
        self._last_aux: dict[str, float] = {}

    @property
    def current(self) -> int:
        return self.climb.current

    @property
    def settled(self) -> bool:
        return self.climb.settled

    def observe(
        self,
        duration_s: Optional[float],
        overlap_efficiency: Optional[float] = None,
        inflight: Optional[float] = None,
        mfu: Optional[float] = None,
    ) -> None:
        """Record one round's observations. ``duration_s`` comes from the
        RoundRecord (the score); the rest are gauge reads kept for
        :meth:`summary`."""
        if duration_s is not None and duration_s > 0:
            self.climb.observe(1.0 / float(duration_s))
        for k, v in (
            ("overlap_efficiency", overlap_efficiency),
            ("inflight_rounds", inflight),
            ("mfu", mfu),
        ):
            if v is not None:
                self._last_aux[k] = float(v)

    def ready(self) -> bool:
        return self.climb.ready()

    def propose(self) -> int:
        """Advance the climb if a full window is pending; returns the knob
        value the driver should use from here on."""
        return self.climb.step()

    def summary(self) -> dict[str, Any]:
        """Perf-summary block: chosen knob value, retune/settle state, the
        full value trajectory, and the last gauge readings seen."""
        out: dict[str, Any] = {
            "knob": self.knob,
            "chosen_" + self.knob: self.current,
            "retunes": self.climb.retunes,
            "settled": self.climb.settled,
            "trajectory": list(self.climb.trajectory),
            "events": list(self.climb.events),
        }
        out.update(self._last_aux)
        return out
