"""SPMD peer-axis execution core.

Where the reference runs N peers as N threads exchanging pickled TCP messages
(reference ``node/node.py:81-112``, ``main.py:24-36``), this package puts the
peer axis on the device mesh: per-peer state (data shards, PRNG keys,
optimizer state) is sharded over a ``jax.sharding.Mesh`` axis, the global
model is stored once (see ``peer_state`` for the layout rationale), local
training is a vmapped ``lax.scan`` under one ``jit``, and every exchange is
an XLA collective over ICI.
"""

from p2pdl_tpu.parallel.mesh import make_mesh, peer_sharding, peers_per_device
from p2pdl_tpu.parallel.peer_state import (
    PeerState,
    global_params,
    init_peer_state,
    params_layout,
    shard_state,
)
from p2pdl_tpu.parallel.round import (
    build_compressed_pack_fn,
    build_digest_pack_fn,
    build_eval_fn,
    build_multi_round_fn,
    build_per_peer_eval_fn,
    build_personalized_eval_fn,
    build_round_fn,
    build_gossip_trust_round_fns,
    build_trust_round_fns,
)

__all__ = [
    "make_mesh",
    "peer_sharding",
    "peers_per_device",
    "PeerState",
    "init_peer_state",
    "shard_state",
    "global_params",
    "params_layout",
    "build_compressed_pack_fn",
    "build_digest_pack_fn",
    "build_round_fn",
    "build_multi_round_fn",
    "build_gossip_trust_round_fns",
    "build_trust_round_fns",
    "build_eval_fn",
    "build_per_peer_eval_fn",
    "build_personalized_eval_fn",
]
