"""Peer-stacked training state.

The reference's per-node state (model + SGD optimizer + loss constructed in
``Node.__init__``, reference ``node/node.py:22-31``) becomes one pytree,
built under ``jit`` with per-peer PRNG keys.

Two parameter layouts, chosen by the aggregation topology:

- **sync** (fedavg / robust reducers / secure_fedavg): the global model is
  stored ONCE (no peer dimension). Peers' parameters are provably identical
  at every round boundary — synchronized init plus a uniform server update —
  so peer-stacking them would store (and stream through HBM every round)
  ``num_peers`` copies of the same bytes. Per-peer copies exist only
  transiently inside the compiled round while local SGD diverges them.
  This is the key deviation from the reference's layout, where every node
  holds its own full model replica (reference ``node/node.py:22-29``) and
  every round moves all of them.
- **peer** (gossip): truly decentralized — peers' models genuinely differ
  across rounds, so every array leaf leads with ``num_peers``.

Per-peer optimizer state is kept in both layouts (each node owns its
optimizer for the experiment's lifetime, reference ``node/node.py:30``;
with plain SGD the state is empty and costs nothing).

Deliberate deviation (documented, per SURVEY §7): the reference gives every
node an *independent random init* and still averages deltas across them
(reference ``main.py:25``, ``aggregator/aggregation.py:36-38``) — averaging
deltas between unaligned parameter spaces. We synchronize the initial
parameters across peers (standard FedAvg), keeping per-peer keys for data
order and any peer-local stochasticity.
"""

from __future__ import annotations

from typing import Any

import flax
import jax
import jax.numpy as jnp
import optax

from p2pdl_tpu.config import Config
from p2pdl_tpu.models import get_model, init_params, model_input_spec
from p2pdl_tpu.parallel.mesh import peer_sharding, replicated_sharding


@flax.struct.dataclass
class PeerState:
    """All mutable experiment state.

    ``params``: global pytree (sync layout) or ``[P, ...]``-stacked (peer
    layout). ``opt_state``/``rng`` always lead with ``num_peers``;
    ``round_idx`` is a replicated scalar.
    """

    params: Any
    opt_state: Any
    rng: jax.Array  # [P] peer PRNG keys (uint32 typed key array)
    round_idx: jax.Array  # scalar int32, replicated
    # Server momentum buffer (FedAvgM): params-shaped float32 pytree when
    # cfg.server_momentum > 0, None otherwise (None keeps the pytree
    # structure — and every momentum-off code path — bit-identical to the
    # pre-FedAvgM layout).
    server_m: Any = None
    # Second FedOpt buffer (cfg.server_opt in ("adam", "yogi")): the
    # adaptive variance accumulator v, params-shaped float32. None
    # otherwise.
    server_v: Any = None
    # SCAFFOLD control variates (cfg.scaffold): ``scaffold_c`` is the
    # server's params-shaped float32 pytree (replicated), ``scaffold_ci``
    # the [P, ...]-stacked per-peer variates (peer-sharded). None when off.
    scaffold_c: Any = None
    scaffold_ci: Any = None
    # Error-feedback residual (cfg.compress != "none"): [P, ...]-stacked
    # float32 unsent remainders, peer-sharded. None when off.
    compress_err: Any = None


def params_layout(cfg: Config) -> str:
    """``"peer"`` (stacked) for gossip, ``"sync"`` (single copy) otherwise."""
    return "peer" if cfg.aggregator == "gossip" else "sync"


def make_optimizer(cfg: Config) -> optax.GradientTransformation:
    """Local optimizer (reference hard-codes SGD lr=0.01, ``node/node.py:30``;
    we add momentum, Adam, and weight decay as config knobs)."""
    if cfg.optimizer == "adam":
        if cfg.weight_decay > 0.0:
            return optax.adamw(cfg.lr, weight_decay=cfg.weight_decay)
        return optax.adam(cfg.lr)
    sgd = (
        optax.sgd(cfg.lr, momentum=cfg.momentum)
        if cfg.momentum > 0.0
        else optax.sgd(cfg.lr)
    )
    if cfg.weight_decay > 0.0:
        # L2 into the update: grad + wd * p, before any momentum.
        return optax.chain(optax.add_decayed_weights(cfg.weight_decay), sgd)
    return sgd


def build_model(
    cfg: Config,
    seq_axis: str | None = None,
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    pp_axis: str | None = None,
):
    """Build the configured model. ``seq_axis`` / ``tp_axis`` / ``ep_axis`` /
    ``pp_axis`` name the mesh axes the token sequence / heads+MLP-hidden /
    MoE experts / trunk depth are sharded over (only inside ``shard_map``);
    the default ``None`` is the dense twin — same logical param pytree, so
    init and eval share one model while the compiled round runs the parallel
    one. (With ``cfg.pp_shards > 1`` the dense twin still uses the
    scan-blocks stacked layout so the pytrees match.)"""
    kwargs: dict[str, Any] = {}
    if cfg.model in ("char_lstm", "char_gpt"):
        from p2pdl_tpu.data.synthetic import SHAKESPEARE_VOCAB_SIZE

        kwargs["vocab_size"] = SHAKESPEARE_VOCAB_SIZE
    if cfg.model == "char_gpt":
        kwargs["attn_impl"] = cfg.attn_impl
        kwargs["max_len"] = cfg.seq_len  # exactly-sized pos-embed table
    if cfg.model == "vit_tiny":
        kwargs["attn_impl"] = cfg.attn_impl
        kwargs["pool"] = cfg.vit_pool
        kwargs["heads"] = cfg.vit_heads
        kwargs["depth"] = cfg.vit_depth
        if cfg.moe_experts > 0:
            kwargs["moe_experts"] = cfg.moe_experts
            kwargs["moe_every"] = cfg.moe_every
            kwargs["moe_capacity_factor"] = cfg.moe_capacity_factor
        if seq_axis is not None:
            kwargs["seq_axis"] = seq_axis
            kwargs["seq_impl"] = cfg.seq_impl
        if tp_axis is not None:
            kwargs["tp_axis"] = tp_axis
            kwargs["tp_shards"] = cfg.tp_shards
        if ep_axis is not None:
            kwargs["ep_axis"] = ep_axis
            kwargs["ep_shards"] = cfg.ep_shards
        if cfg.uses_scan_blocks:
            kwargs["scan_blocks"] = True
            kwargs["pp_microbatches"] = cfg.effective_pp_microbatches
            if pp_axis is not None:
                kwargs["pp_axis"] = pp_axis
                kwargs["pp_shards"] = cfg.pp_shards
    return get_model(cfg.model, **kwargs)


def init_peer_state(cfg: Config, key: jax.Array | None = None) -> PeerState:
    """Initialize synchronized params + per-peer keys (pure; jit-safe)."""
    if key is None:
        key = jax.random.PRNGKey(cfg.seed)
    model = build_model(cfg)
    input_shape, in_dtype = model_input_spec(cfg.model, cfg.dataset, cfg.seq_len)
    init_key, peer_key = jax.random.split(key)
    params = init_params(model, input_shape, in_dtype, init_key)
    params = jax.tree.map(
        lambda p: p.astype(cfg.param_dtype)
        if jnp.issubdtype(p.dtype, jnp.floating)
        else p,
        params,
    )
    opt_state = make_optimizer(cfg).init(params)

    def stack(leaf):
        return jnp.broadcast_to(leaf[None], (cfg.num_peers, *leaf.shape))

    if params_layout(cfg) == "peer":
        params = jax.tree.map(stack, params)
    server_m = server_v = None
    if cfg.server_momentum > 0.0 or cfg.server_opt != "sgd":
        # Float32 regardless of param dtype: the buffer accumulates small
        # aggregates across many rounds.
        server_m = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
    if cfg.server_opt in ("adam", "yogi"):
        server_v = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
    scaffold_c = scaffold_ci = None
    if cfg.scaffold:
        scaffold_c = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        scaffold_ci = jax.tree.map(
            lambda p: jnp.zeros((cfg.num_peers, *p.shape), jnp.float32), params
        )
    compress_err = None
    if cfg.compress == "topk":  # qsgd is unbiased — no residual state
        compress_err = jax.tree.map(
            lambda p: jnp.zeros((cfg.num_peers, *p.shape), jnp.float32), params
        )
    return PeerState(
        params=params,
        opt_state=jax.tree.map(stack, opt_state),
        rng=jax.random.split(peer_key, cfg.num_peers),
        round_idx=jnp.zeros((), jnp.int32),
        server_m=server_m,
        server_v=server_v,
        scaffold_c=scaffold_c,
        scaffold_ci=scaffold_ci,
        compress_err=compress_err,
    )


def shard_state(state: PeerState, cfg: Config, mesh) -> PeerState:
    """Place a ``PeerState`` on the mesh with the layout-correct shardings.

    Under tensor / expert parallelism the sync-layout params get PER-LEAF
    placements (column/row kernels split over the tp axis,
    ``ops.tp.param_specs``; expert-stacked leaves split over the ep axis,
    ``ops.moe.param_specs``) — the leaves keep their full logical shapes;
    only bytes move."""
    from jax.sharding import NamedSharding

    ps = peer_sharding(mesh)
    rs = replicated_sharding(mesh)
    layout = params_layout(cfg)
    opt_shardings = jax.tree.map(
        lambda l: ps if getattr(l, "ndim", 0) >= 1 else rs, state.opt_state
    )
    # Derived-stack placement for peer-stacked params-shaped families
    # (optimizer traces, SCAFFOLD c_i, compression residuals): plain
    # peer-stacked by default, peer axis + the matching param's spec per
    # leaf under model parallelism.
    stack_shardings = lambda tree: jax.tree.map(lambda _: ps, tree)  # noqa: E731
    if (cfg.tp_shards > 1 or cfg.ep_shards > 1 or cfg.pp_shards > 1) and layout == "sync":
        from p2pdl_tpu.ops.placement import derived_tree_specs
        from p2pdl_tpu.parallel.mesh import PEER_AXIS

        if cfg.tp_shards > 1:
            from p2pdl_tpu.ops import tp as _placer
        elif cfg.ep_shards > 1:
            from p2pdl_tpu.ops import moe as _placer
        else:
            from p2pdl_tpu.ops import pipeline as _placer

        param_specs = _placer.param_specs(state.params)
        is_spec = lambda x: isinstance(x, jax.sharding.PartitionSpec)  # noqa: E731
        param_shardings = jax.tree.map(
            lambda spec: NamedSharding(mesh, spec), param_specs, is_leaf=is_spec
        )

        def stack_shardings(tree):  # noqa: F811
            return jax.tree.map(
                lambda spec: NamedSharding(mesh, spec),
                derived_tree_specs(tree, param_specs, PEER_AXIS),
                is_leaf=is_spec,
            )

        opt_shardings = stack_shardings(state.opt_state)
    else:
        param_shardings = jax.tree.map(
            lambda _: ps if layout == "peer" else rs, state.params
        )
    shardings = PeerState(
        params=param_shardings,
        opt_state=opt_shardings,
        rng=ps,
        round_idx=rs,
        # The momentum buffer mirrors the params placement leaf-for-leaf
        # (same shapes, same model-parallel splits).
        server_m=None if state.server_m is None else param_shardings,
        server_v=None if state.server_v is None else param_shardings,
        # SCAFFOLD: c mirrors the params placement (replicated across
        # peers, model-axis-sharded under tp/ep/pp); the c_i and residual
        # stacks place like the optimizer state.
        scaffold_c=None if state.scaffold_c is None else param_shardings,
        scaffold_ci=None if state.scaffold_ci is None else stack_shardings(state.scaffold_ci),
        compress_err=None if state.compress_err is None else stack_shardings(state.compress_err),
    )
    return jax.device_put(state, shardings)


def global_params(state: PeerState, cfg: Config) -> Any:
    """The synchronized global model: the single stored copy (sync layout)
    or peer 0's slice (peer layout, where "global" is per-peer)."""
    if params_layout(cfg) == "sync":
        return state.params
    return jax.tree.map(lambda l: l[0], state.params)


def params_bytes(params: Any) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
