"""Peer-stacked training state.

The reference's per-node state (model + SGD optimizer + loss constructed in
``Node.__init__``, reference ``node/node.py:22-31``) becomes one pytree with
a leading peer dimension, built under ``jit`` with per-peer PRNG keys.

Deliberate deviation (documented, per SURVEY §7): the reference gives every
node an *independent random init* and still averages deltas across them
(reference ``main.py:25``, ``aggregator/aggregation.py:36-38``) — averaging
deltas between unaligned parameter spaces. We synchronize the initial
parameters across peers (standard FedAvg), keeping per-peer keys for data
order and any peer-local stochasticity.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax
import jax
import jax.numpy as jnp
import optax

from p2pdl_tpu.config import Config
from p2pdl_tpu.models import get_model, init_params, model_input_spec


@flax.struct.dataclass
class PeerState:
    """All mutable experiment state; every array leaf leads with ``num_peers``
    except ``round_idx``."""

    params: Any  # pytree, leaves [P, ...]
    opt_state: Any  # pytree, leaves [P, ...]
    rng: jax.Array  # [P] peer PRNG keys (uint32 typed key array)
    round_idx: jax.Array  # scalar int32, replicated


def make_optimizer(cfg: Config) -> optax.GradientTransformation:
    """Local-SGD optimizer (reference uses SGD lr=0.01, ``node/node.py:30``)."""
    if cfg.momentum > 0.0:
        return optax.sgd(cfg.lr, momentum=cfg.momentum)
    return optax.sgd(cfg.lr)


def build_model(cfg: Config):
    kwargs: dict[str, Any] = {}
    if cfg.model == "char_lstm":
        from p2pdl_tpu.data.synthetic import SHAKESPEARE_VOCAB_SIZE

        kwargs["vocab_size"] = SHAKESPEARE_VOCAB_SIZE
    return get_model(cfg.model, **kwargs)


def init_peer_state(cfg: Config, key: jax.Array | None = None) -> PeerState:
    """Initialize synchronized params + per-peer keys (pure; jit-safe)."""
    if key is None:
        key = jax.random.PRNGKey(cfg.seed)
    model = build_model(cfg)
    input_shape, in_dtype = model_input_spec(cfg.model, cfg.dataset, cfg.seq_len)
    init_key, peer_key = jax.random.split(key)
    params = init_params(model, input_shape, in_dtype, init_key)
    params = jax.tree.map(
        lambda p: p.astype(cfg.param_dtype)
        if jnp.issubdtype(p.dtype, jnp.floating)
        else p,
        params,
    )
    opt_state = make_optimizer(cfg).init(params)

    def stack(leaf):
        return jnp.broadcast_to(leaf[None], (cfg.num_peers, *leaf.shape))

    return PeerState(
        params=jax.tree.map(stack, params),
        opt_state=jax.tree.map(stack, opt_state),
        rng=jax.random.split(peer_key, cfg.num_peers),
        round_idx=jnp.zeros((), jnp.int32),
    )


def params_bytes(params: Any) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
