"""The federated round as one compiled SPMD program.

This is the TPU-native replacement for the reference's entire data plane —
the trainer threads (reference ``main.py:72-80``), the per-batch train loop
with its host sync every step (reference ``training/train.py:7-17``), the
delta computation (reference ``node/node.py:272-282``), the pickled-TCP
update fan-out (reference ``node/node.py:289-297``), FedAvg-on-deltas with
server learning rate (reference ``aggregator/aggregation.py:15-38``), and the
global-model broadcast (reference ``aggregator/aggregation.py:66-77``) — as a
single ``jit``-compiled ``shard_map`` over the peer mesh axis:

- local training = ``vmap`` (peers-per-device) of a ``lax.scan`` over epochs
  and batches: zero host round-trips inside a round;
- update exchange = one XLA collective: a masked ``psum`` for FedAvg (no
  materialized per-peer copies), or a tiled ``all_gather`` feeding the robust
  reducers (Krum needs all updates visible);
- global sync = the replicated aggregate applied uniformly, replacing the
  reference's nondeterministic last-writer-wins broadcast (SURVEY §3.4) with
  a deterministic update — a documented, deliberate fix.

Bandwidth architecture (the perf ceiling is HBM traffic, not FLOPs): in the
sync layout the global params live in ONE copy (see ``peer_state``), so the
cross-round working set is megabytes, not ``num_peers`` × model. Per-peer
parameter copies are materialized only transiently inside the round while
local SGD diverges peers. When a round is a *single* plain-SGD step per
trainer (no momentum, no attack, no BRB commitments needed), FedAvg-on-deltas
is algebraically one pooled-minibatch gradient step —
``mean_t(-lr·g_t) = -lr·∇ mean_t(loss_t)`` — so the round compiles to one
big batched forward/backward on the MXU with a single ``psum``, never
materializing per-peer deltas at all (the ``_fast_sync_body`` path; exactness
is asserted by ``tests/test_round.py::test_fast_path_matches_general``).

Deliberate semantic deviations from the reference, all documented:
shared initial params (vs. unaligned per-node inits, reference ``main.py:25``),
deterministic global sync (vs. last-writer-wins), and a held-out eval split
(vs. train-shard eval, reference ``evaluation/evaluation.py:10``).
"""

from __future__ import annotations

import math
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from p2pdl_tpu.config import Config
from p2pdl_tpu.ops import aggregators, sharded_aggregators
from p2pdl_tpu.ops.attacks import apply_attack, poison_labels
from p2pdl_tpu.ops.gossip import exp_mix, ring_mix
from p2pdl_tpu.ops.secure_agg import apply_masks, residual_mask_sum
from p2pdl_tpu.parallel.mesh import (
    EP_AXIS,
    PEER_AXIS,
    PP_AXIS,
    SEQ_AXIS,
    TP_AXIS,
    peers_per_device,
)
from p2pdl_tpu.parallel.peer_state import (
    PeerState,
    build_model,
    global_params,
    init_peer_state,
    make_optimizer,
    params_layout,
)
from p2pdl_tpu.utils import telemetry


def _mesh_axes_for(
    cfg: Config, mesh: Mesh
) -> tuple[str | None, str | None, str | None, str | None]:
    """(seq_axis, tp_axis, ep_axis, pp_axis) for this config, validated
    against the mesh."""
    seq_axis = SEQ_AXIS if cfg.seq_shards > 1 else None
    tp_axis = TP_AXIS if cfg.tp_shards > 1 else None
    ep_axis = EP_AXIS if cfg.ep_shards > 1 else None
    pp_axis = PP_AXIS if cfg.pp_shards > 1 else None
    for axis, knob in (
        (seq_axis, "seq_shards"),
        (tp_axis, "tp_shards"),
        (ep_axis, "ep_shards"),
        (pp_axis, "pp_shards"),
    ):
        if axis is not None and axis not in mesh.shape:
            raise ValueError(
                f"cfg.{knob}={getattr(cfg, knob)} needs a (peers x {axis}) "
                f"mesh; build it with make_mesh({knob}=...)"
            )
    return seq_axis, tp_axis, ep_axis, pp_axis


def _model_parallel_specs(cfg: Config, kind: str):
    """(params_spec, opt_spec, extra_specs) per-leaf PartitionSpec trees
    for a model-parallel layout (one abstract init trace shared by all):

    - params: full logical shapes; ``kind`` selects the placer — "tp"
      (column/row kernels, ``ops.tp``), "ep" (expert-stacked leaves,
      ``ops.moe``), "pp" (depth-stacked block leaves, ``ops.pipeline``);
    - optimizer state: momentum traces mirror the param tree, so each
      trace leaf is its param's spec with the peer axis prefixed
      (``ops.placement.derived_tree_specs``);
    - ``extra_specs``: same derivation for the other peer-stacked
      params-shaped state families (SCAFFOLD ``c_i``, compression
      residuals), present iff the config enables them."""
    from p2pdl_tpu.ops.placement import derived_tree_specs

    if kind == "tp":
        from p2pdl_tpu.ops import tp as placer
    elif kind == "ep":
        from p2pdl_tpu.ops import moe as placer
    else:
        from p2pdl_tpu.ops import pipeline as placer

    abstract = jax.eval_shape(lambda: init_peer_state(cfg))
    params_spec = placer.param_specs(abstract.params)
    opt_spec = derived_tree_specs(abstract.opt_state, params_spec, PEER_AXIS)
    extra_specs = {}
    if abstract.scaffold_ci is not None:
        extra_specs["scaffold_ci"] = derived_tree_specs(
            abstract.scaffold_ci, params_spec, PEER_AXIS
        )
    if abstract.compress_err is not None:
        extra_specs["compress_err"] = derived_tree_specs(
            abstract.compress_err, params_spec, PEER_AXIS
        )
    return params_spec, opt_spec, extra_specs


def make_forward_fn(
    model: Any, compute_dtype: jnp.dtype, param_transform: Callable | None = None
) -> Callable:
    """``(params, x) -> float32 logits`` with the mixed-precision policy:
    params/float inputs cast to the compute dtype (bfloat16 by default) so
    matmuls hit the MXU, logits returned in float32. Shared by training and
    eval so their numerics cannot diverge. ``param_transform`` applies a
    pure view transform before the forward (tensor parallelism pre-scales
    row-parallel biases by 1/tp — ``ops.tp``); gradients flow through it,
    which is exactly what makes the stored (untransformed) params' update
    come out dense-equivalent."""

    def forward(params, x):
        if param_transform is not None:
            params = param_transform(params)
        cparams = jax.tree.map(lambda p: p.astype(compute_dtype), params)
        if jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(compute_dtype)
        return model.apply({"params": cparams}, x).astype(jnp.float32)

    return forward


def make_loss_fn(
    model: Any, compute_dtype: jnp.dtype, param_transform: Callable | None = None
) -> Callable:
    """Mean CE loss (reference wires ``CrossEntropyLoss`` at
    ``node/node.py:31``). Handles both ``[B, C]`` logits with ``[B]`` labels
    and sequence-model ``[B, T, C]`` logits with ``[B, T]`` targets."""
    forward = make_forward_fn(model, compute_dtype, param_transform)

    def loss_fn(params, x, y):
        logits = forward(params, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    return loss_fn


def _param_transform(cfg: Config) -> Callable | None:
    """The TP bias-view transform when tensor parallelism is on."""
    if cfg.tp_shards <= 1:
        return None
    from p2pdl_tpu.ops import tp

    factor = 1.0 / cfg.tp_shards
    return lambda p: tp.scale_row_parallel_biases(p, factor)


def make_local_train(
    cfg: Config,
    model: Any,
    opt: optax.GradientTransformation,
    seq_axis: str | None = None,
    ep_axis: str | None = None,
) -> Callable:
    """One peer's full local-training phase (``cfg.local_epochs`` epochs of
    minibatch SGD, reshuffled per epoch) as a pure function — the jittable
    equivalent of reference ``training/train.py:3-26``.

    Under sequence parallelism (the model's ``seq_axis`` set) no explicit
    gradient collective appears here: params stay seq-INVARIANT, so the
    vma machinery inserts the ``psum`` over the seq axis exactly at the
    invariant->varying boundary — each shard's token-block contribution is
    summed once, and layers computing in the already-invariant region after
    the pooling ``pmean`` are not double-counted. (``seq_axis`` is accepted
    for signature symmetry; the psum is implicit.)

    Under expert parallelism (``ep_axis`` set) each shard trains on ITS
    ``batch_size / ep_shards`` slice of every batch (tokens reach their
    expert's owner by all_to_all inside the model) and the local loss is
    pre-scaled by ``1 / ep_shards``: non-expert params stay ep-invariant,
    so the implicit psum of their grads over the ep axis then reconstructs
    exactly the global-batch mean; expert params are ep-varying and their
    grads arrive complete through the all_to_all transpose. The reported
    loss is the scaled local mean — callers psum it over the ep axis to
    recover the true batch loss (``_local_train_phase`` does)."""
    del seq_axis  # implicit via vma typing; see docstring
    loss_fn = make_loss_fn(model, jnp.dtype(cfg.compute_dtype), _param_transform(cfg))
    if ep_axis is not None:
        inner = loss_fn
        ep_shards = cfg.ep_shards
        b_local = cfg.batch_size // ep_shards

        def loss_fn(params, xb, yb):  # noqa: F811 - deliberate wrap
            start = lax.axis_index(ep_axis) * b_local
            xs = lax.dynamic_slice_in_dim(xb, start, b_local, axis=0)
            ys = lax.dynamic_slice_in_dim(yb, start, b_local, axis=0)
            return inner(params, xs, ys) / ep_shards

    if cfg.remat:
        loss_fn = jax.checkpoint(loss_fn)
    grad_fn = jax.value_and_grad(loss_fn)
    mu = cfg.fedprox_mu
    s = cfg.samples_per_peer
    nb = cfg.batches_per_epoch
    b = cfg.batch_size
    # With exactly one full-shard batch per epoch, the shuffle only permutes
    # rows *within* the batch — the mean gradient is permutation-invariant —
    # so the gather (a full copy of x per step) is skipped. (Under expert
    # parallelism rows map to ep shards positionally, so the permutation is
    # no longer a no-op and the gather stays.)
    shuffle = not (nb == 1 and nb * b == s and ep_axis is None)

    def local_train(params, opt_state, key, x, y, grad_bias=None, tau=None):
        # FedProx (Li et al., MLSys 2020): add (mu/2)||w - w_anchor||^2 to
        # every local step's objective, anchored at THIS round's incoming
        # params — bounds local drift over multi-step training on skewed
        # shards. The prox gradient is zero at the anchor, so single-step
        # rounds are bit-identical to FedAvg (test-asserted) and the
        # pooled-gradient fast path stays exact. The REPORTED loss stays
        # the data loss (the reference's progress metric), not data+prox.
        if mu > 0.0:
            anchor = params

            def prox_grad(p, xb, yb):
                def total(q):
                    data = loss_fn(q, xb, yb)
                    drift = sum(
                        jnp.sum(
                            (l.astype(jnp.float32) - a.astype(jnp.float32)) ** 2
                        )
                        for l, a in zip(jax.tree.leaves(q), jax.tree.leaves(anchor))
                    )
                    return data + 0.5 * mu * drift, data

                (_, data), grads = jax.value_and_grad(total, has_aux=True)(p)
                return data, grads

            step_grad = prox_grad
        else:
            step_grad = grad_fn

        def epoch(carry, inp):
            ekey, e_idx = inp

            def batch_step(carry, batch):
                params, opt_state = carry
                xb, yb = batch
                loss, grads = step_grad(params, xb, yb)
                if grad_bias is not None:
                    # SCAFFOLD control-variate correction c - c_i, constant
                    # across this round's local steps.
                    grads = jax.tree.map(
                        lambda g, b: g + b.astype(g.dtype), grads, grad_bias
                    )
                updates, opt_state = opt.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss

            if shuffle:
                perm = jax.random.permutation(ekey, s)[: nb * b].reshape(nb, b)
                batches = (x[perm], y[perm])
            else:
                batches = (x[None], y[None])
            new_carry, losses = lax.scan(batch_step, carry, batches)
            loss = jnp.mean(losses)
            if tau is not None:
                # Straggler simulation: epochs past this peer's tau_i are
                # computed (static shapes) but their updates are FROZEN —
                # the peer's delta and loss are exactly a tau_i-epoch run's.
                live = e_idx < tau
                new_carry = jax.tree.map(
                    lambda n, o: jnp.where(live, n, o), new_carry, carry
                )
                loss = jnp.where(live, loss, 0.0)
            return new_carry, loss

        keys = jax.random.split(key, cfg.local_epochs)
        (params, opt_state), epoch_losses = lax.scan(
            epoch, (params, opt_state), (keys, jnp.arange(cfg.local_epochs))
        )
        if tau is not None:
            return params, opt_state, jnp.sum(epoch_losses) / tau.astype(jnp.float32)
        return params, opt_state, jnp.mean(epoch_losses)

    return local_train


def _aggregate(cfg: Config, deltas_trainers: Any) -> Any:
    """Dispatch to the configured reducer over ``[T, ...]`` stacked deltas.
    ``cfg.pallas_aggregators`` routes the distance-based reducers through
    the fused kernels where trusted (``ops.pallas_aggregators``); the flag
    is a no-op for the coordinate-wise ones."""
    pallas = cfg.pallas_aggregators
    if cfg.aggregator == "krum":
        return aggregators.krum(deltas_trainers, cfg.byzantine_f, pallas=pallas)
    if cfg.aggregator == "multi_krum":
        return aggregators.multi_krum(
            deltas_trainers, cfg.byzantine_f, cfg.multi_krum_m, pallas=pallas
        )
    if cfg.aggregator == "trimmed_mean":
        return aggregators.trimmed_mean(deltas_trainers, cfg.trimmed_mean_beta)
    if cfg.aggregator == "median":
        return aggregators.median(deltas_trainers)
    if cfg.aggregator == "geometric_median":
        return aggregators.geometric_median(deltas_trainers)
    if cfg.aggregator == "centered_clip":
        return aggregators.centered_clip(
            deltas_trainers, cfg.cclip_tau, cfg.cclip_iters, pallas=pallas
        )
    if cfg.aggregator == "bulyan":
        return aggregators.bulyan(deltas_trainers, cfg.byzantine_f, pallas=pallas)
    raise ValueError(f"no gathered-reducer for {cfg.aggregator!r}")


def _aggregate_blockwise(cfg: Config, delta: Any, trainer_idx) -> Any:
    """Dispatch to the blockwise (streamed) reducer over local ``[L, ...]``
    delta blocks inside ``shard_map`` (``ops.sharded_aggregators``).
    ``cfg.pallas_aggregators`` routes the Gram accumulation through the
    fused kernel where trusted; coordinate-wise reducers are unaffected."""
    pallas = cfg.pallas_aggregators
    if cfg.aggregator == "krum":
        return sharded_aggregators.krum_sharded(
            delta, trainer_idx, cfg.byzantine_f, pallas=pallas
        )
    if cfg.aggregator == "multi_krum":
        return sharded_aggregators.multi_krum_sharded(
            delta, trainer_idx, cfg.byzantine_f, cfg.multi_krum_m, pallas=pallas
        )
    if cfg.aggregator == "trimmed_mean":
        return sharded_aggregators.trimmed_mean_sharded(
            delta, trainer_idx, cfg.trimmed_mean_beta
        )
    if cfg.aggregator == "median":
        return sharded_aggregators.median_sharded(delta, trainer_idx)
    if cfg.aggregator == "geometric_median":
        return sharded_aggregators.geometric_median_sharded(
            delta, trainer_idx, pallas=pallas
        )
    if cfg.aggregator == "centered_clip":
        return sharded_aggregators.centered_clip_sharded(
            delta, trainer_idx, cfg.cclip_tau, cfg.cclip_iters, pallas=pallas
        )
    if cfg.aggregator == "bulyan":
        return sharded_aggregators.bulyan_sharded(
            delta, trainer_idx, cfg.byzantine_f, pallas=pallas
        )
    raise ValueError(f"no blockwise reducer for {cfg.aggregator!r}")


def _use_fast_sync_path(cfg: Config, attack: str) -> bool:
    """The pooled-gradient round is exact iff local training is one plain-SGD
    step (delta = -lr·grad, linear in the gradient), nothing perturbs
    per-peer deltas (no attack, no per-peer masking semantics to simulate),
    and nothing downstream needs them (no BRB commitments). ``remat`` routes
    to the general path, whose local trainer honors ``jax.checkpoint`` — the
    fast path pools every trainer's batch into one forward/backward, which is
    exactly the memory shape a remat request is trying to avoid."""
    return (
        cfg.aggregator == "fedavg"
        and attack == "none"
        and not cfg.brb_enabled
        and not cfg.remat
        and cfg.seq_shards == 1
        and cfg.tp_shards == 1
        and cfg.ep_shards == 1
        and cfg.pp_shards == 1
        and cfg.optimizer == "sgd"
        and cfg.dp_clip == 0.0  # per-peer clipping needs per-peer deltas
        and not cfg.scaffold  # per-peer control variates need per-peer deltas
        and cfg.compress == "none"  # both compressors act on per-peer deltas
        and not cfg.fednova  # per-peer delta normalization
        and cfg.hetero_min_epochs == 0  # per-peer epoch masking
        and cfg.momentum == 0.0
        and cfg.weight_decay == 0.0
        and cfg.local_epochs == 1
        and cfg.batches_per_epoch == 1
        and cfg.samples_per_peer == cfg.batch_size
    )


# Memo for builder-resolved ECDH seed matrices: the derivation is pure in
# (num_peers, seed) but costs O(P^2/2) host-side ECDH (~1 min at P=1024);
# without the cache every builder call (and every bench retry) would re-pay
# it. Entries are treated as immutable — the driver's rotating matrix never
# flows through here (it injects its own copy).
_SEED_MATRIX_CACHE: dict[tuple[int, int], Any] = {}


def _resolve_pair_seeds(cfg: Config, pair_seeds):
    """The key-derivation mode follows ``cfg.secure_agg_keys``, not whether
    the caller happened to plumb a matrix: with the default "ecdh" and no
    injected seeds, build the keyring here (from ``cfg.seed``, so every
    builder derives the identical matrix) — otherwise a direct
    ``build_round_fn`` caller would silently get the legacy shared-key
    masks the config says are for A/B benchmarking only. The driver still
    injects its own matrix so rotation state stays with its keyring."""
    if (
        pair_seeds is None
        and cfg.aggregator == "secure_fedavg"
        and cfg.secure_agg_keys == "ecdh"
    ):
        key = (cfg.num_peers, cfg.seed)
        pair_seeds = _SEED_MATRIX_CACHE.get(key)
        if pair_seeds is None:
            from p2pdl_tpu.protocol.secure_keys import SecureAggKeyring

            pair_seeds = SecureAggKeyring(cfg.num_peers, seed=cfg.seed).seed_matrix()
            _SEED_MATRIX_CACHE[key] = pair_seeds
    return pair_seeds


def _apply_server_update(cfg: Config, old_params, new_params, m, v):
    """ONE dispatch for the stateful server-optimizer step — shared by the
    sequential round, the fused scan body, and the BRB-gated agg_fn, so
    the three paths cannot drift (their mutual equivalence is
    test-asserted). Returns ``(params, m, v)`` unchanged when no stateful
    server optimizer is configured."""
    if cfg.server_opt in ("adam", "yogi"):
        return _apply_server_opt(cfg, old_params, new_params, m, v)
    if cfg.server_momentum > 0.0:
        new_params, m = _apply_server_momentum(cfg, old_params, new_params, m)
    return new_params, m, v


def _apply_server_momentum(cfg: Config, old_params, new_params, m):
    """FedAvgM (Hsu et al. 2019) applied OUTSIDE the shard-mapped body.

    Every sync body's server update is exactly ``p' = p + server_lr·agg``,
    so the aggregate reconstructs as ``(p' - p)/server_lr`` from the
    round-level replicated arrays — no body signature or spec changes for
    any of the fast/general/chunked paths. Then ``m' = beta·m + agg`` and
    ``p'' = p' + server_lr·beta·m  (= p + server_lr·m')``. All float32;
    the reconstruction costs ~1 ulp of division rounding per round vs an
    in-body implementation (the fused scan uses this same helper inside
    its carry, and the fused==sequential test bounds the agreement).
    """
    s = jnp.float32(cfg.server_lr)
    beta = jnp.float32(cfg.server_momentum)
    new_m = jax.tree.map(
        lambda mm, po, pn: beta * mm
        + (pn.astype(jnp.float32) - po.astype(jnp.float32)) / s,
        m,
        old_params,
        new_params,
    )
    out_p = jax.tree.map(
        lambda pn, mm: (pn.astype(jnp.float32) + s * beta * mm).astype(pn.dtype),
        new_params,
        m,
    )
    return out_p, new_m


def _apply_server_opt(cfg: Config, old_params, new_params, m, v):
    """FedAdam / FedYogi (Reddi et al., ICLR 2021, Alg. 2 — no bias
    correction) applied the same outside-the-body way as
    :func:`_apply_server_momentum`: the aggregate reconstructs as
    ``(p' - p)/server_lr`` from the body's plain update, then the
    adaptive step REPLACES it::

        m' = b1*m + (1-b1)*agg
        v' = b2*v + (1-b2)*agg^2                    (adam)
        v' = v - (1-b2)*agg^2*sign(v - agg^2)       (yogi)
        p  = p_old + server_lr * m' / (sqrt(v') + eps)

    Returns ``(params_out, m', v')`` — all buffer math float32.
    """
    s = jnp.float32(cfg.server_lr)
    b1 = jnp.float32(cfg.server_beta1)
    b2 = jnp.float32(cfg.server_beta2)
    eps = jnp.float32(cfg.server_eps)
    agg = jax.tree.map(
        lambda po, pn: (pn.astype(jnp.float32) - po.astype(jnp.float32)) / s,
        old_params,
        new_params,
    )
    new_m = jax.tree.map(lambda mm, g: b1 * mm + (1.0 - b1) * g, m, agg)
    if cfg.server_opt == "yogi":
        new_v = jax.tree.map(
            lambda vv, g: vv - (1.0 - b2) * g * g * jnp.sign(vv - g * g), v, agg
        )
    else:
        new_v = jax.tree.map(lambda vv, g: b2 * vv + (1.0 - b2) * g * g, v, agg)
    out_p = jax.tree.map(
        lambda po, mm, vv: (
            po.astype(jnp.float32) + s * mm / (jnp.sqrt(vv) + eps)
        ).astype(po.dtype),
        old_params,
        new_m,
        new_v,
    )
    return out_p, new_m, new_v


def _epoch_counts(cfg: Config, peer_ids, round_idx):
    """Per-peer local epoch counts ``tau_i`` for the straggler simulation
    (``cfg.hetero_min_epochs``): uniform over
    ``[hetero_min_epochs, local_epochs]``, keyed on (seed, GLOBAL peer id,
    round) — deterministic and layout-invariant, so every execution mode
    (vmap width, peer_chunk, fused rounds) sees the identical straggler
    schedule and chunked == general holds exactly. ``None`` when the
    simulation is off (homogeneous ``local_epochs``)."""
    if cfg.hetero_min_epochs == 0:
        return None
    key = jax.random.fold_in(
        jax.random.PRNGKey(cfg.seed ^ 0x48455401), round_idx  # "HET"
    )
    return jax.vmap(
        lambda pid: jax.random.randint(
            jax.random.fold_in(key, pid), (),
            cfg.hetero_min_epochs, cfg.local_epochs + 1,
        )
    )(peer_ids)


def _local_steps(cfg: Config, peer_ids, round_idx):
    """``a_i`` — each peer's local STEP count this round (tau_i x batches
    per epoch), the FedNova normalizer. ``[L]`` float32."""
    tau = _epoch_counts(cfg, peer_ids, round_idx)
    if tau is None:
        tau = jnp.full(peer_ids.shape, cfg.local_epochs, jnp.int32)
    return (tau * cfg.batches_per_epoch).astype(jnp.float32)


def _fednova_normalize(delta, a, lead: int):
    """Divide each of the leading ``lead`` stacked updates by its step
    count ``a`` (``[lead]`` float32) — FedNova's per-trainer d_i =
    delta_i / a_i. Shared by the general and chunked bodies so the two
    cannot drift (their equivalence is test-asserted)."""
    return jax.tree.map(
        lambda d: (
            d.astype(jnp.float32) / a.reshape((lead,) + (1,) * (d.ndim - 1))
        ).astype(d.dtype),
        delta,
    )


def _fednova_tau_eff(is_trainer, a):
    """``tau_eff = mean(a_i over live trainers)`` — the FedNova rescale of
    the normalized mean. Cross-device: psums over the peer axis."""
    live = jnp.maximum(
        lax.psum(jnp.sum(is_trainer.astype(jnp.float32)), PEER_AXIS), 1.0
    )
    return lax.psum(jnp.sum(jnp.where(is_trainer, a, 0.0)), PEER_AXIS) / live


def _fednova_rescale(agg, tau_eff):
    return jax.tree.map(
        lambda x: (x.astype(jnp.float32) * tau_eff).astype(x.dtype), agg
    )


def _num_classes(cfg: Config) -> int:
    """Label-space size for data poisoning (ops.attacks.poison_labels) —
    sourced from the SAME constants the data layer builds labels with
    (data/federated.py), so a future dataset with a different class count
    cannot silently desynchronize the flip range. Shakespeare labels are
    next-char ids over the synthetic vocab (flipping them is still a
    faithful wrong-data corruption for the char LM)."""
    if cfg.dataset == "shakespeare":
        from p2pdl_tpu.data.synthetic import SHAKESPEARE_VOCAB_SIZE

        return SHAKESPEARE_VOCAB_SIZE
    from p2pdl_tpu.data.federated import NUM_CLASSES

    return NUM_CLASSES


def _dp_sharded_tree(params_spec, axis):
    """Per-leaf bool tree from a model-parallel params spec tree: which
    leaves are SPLIT over ``axis`` (their delta slices need a psum to
    complete the DP clip norm, and per-shard noise keys); replicated
    leaves are full copies and enter the norm once."""
    return jax.tree.map(
        lambda s: axis in s, params_spec, is_leaf=lambda x: isinstance(x, P)
    )


def build_round_fn(
    cfg: Config, mesh: Mesh, attack: str = "none", pair_seeds=None
) -> Callable:
    """Compile the fused round: ``(state, x, y, trainer_idx, byz_gate,
    mask_key) -> (state', metrics)``.

    ``trainer_idx``: ``[T]`` global peer ids of this round's trainers (the
    host round driver samples roles, mirroring reference ``main.py:52-54``).
    For ``fedavg``/``secure_fedavg``, entries may be ``-1`` (vacant slot):
    participation can shrink — e.g. after peer failures or BRB delivery
    failures — without a recompile, and the aggregate normalizes by the live
    trainer count. The gathered robust reducers (krum/trimmed-mean/median)
    need their full ``[T]`` update matrix, so they reject vacancy at the
    driver level. ``byz_gate``: ``[P]`` 1.0 for adversarial peers.
    ``mask_key``: PRNG key for secure-aggregation masks / noise attacks.

    For sync layouts with the trust plane on, the driver uses
    :func:`build_trust_round_fns` instead, so the BRB outcome can gate the
    aggregate *between* the two compiled phases. The fused round still
    serves gossip with BRB (observational trust: the mix is in-band, so
    ``metrics["delta"]`` exposes per-peer deltas for digest broadcast).

    The input ``state`` is donated: the round overwrites it in place, so the
    caller must use the returned state (all call sites thread it through).
    """
    pair_seeds = _resolve_pair_seeds(cfg, pair_seeds)
    seq_axis, tp_axis, ep_axis, pp_axis = _mesh_axes_for(cfg, mesh)
    model = build_model(
        cfg, seq_axis=seq_axis, tp_axis=tp_axis, ep_axis=ep_axis, pp_axis=pp_axis
    )
    opt = make_optimizer(cfg)
    l_per_dev = peers_per_device(cfg.num_peers, mesh)
    # Per-leaf model-parallel placement, computed ONCE (params: column/row
    # kernels over tp / expert stacks over ep / depth stacks over pp;
    # optimizer state mirrors the params — what makes momentum compose
    # with the sharded axes). Also the single derivation site for the DP
    # sharded-leaf classification.
    mp_kind = "tp" if tp_axis else ("ep" if ep_axis else ("pp" if pp_axis else None))
    mp_specs = _model_parallel_specs(cfg, mp_kind) if mp_kind else None
    mp_axis = tp_axis or ep_axis or pp_axis
    mp_sharded = _dp_sharded_tree(mp_specs[0], mp_axis) if mp_axis else None
    emit_delta = False
    if params_layout(cfg) == "peer":
        emit_delta = cfg.brb_enabled
        body = _gossip_body(cfg, mesh, attack, model, opt, l_per_dev, emit_delta)
        params_spec = P(PEER_AXIS)
    elif cfg.peer_chunk > 0:
        # Explicit request to stream the peer stack (memory over speed).
        body = _chunked_sync_body(cfg, attack, model, opt, l_per_dev, pair_seeds=pair_seeds)
        params_spec = P()
    elif _use_fast_sync_path(cfg, attack):
        body = _fast_sync_body(cfg, model, l_per_dev)
        params_spec = P()
    else:
        body = _general_sync_body(
            cfg, attack, model, opt, l_per_dev,
            seq_axis=seq_axis, ep_axis=ep_axis, pair_seeds=pair_seeds,
            mp_axis=mp_axis, mp_sharded=mp_sharded,
        )
        params_spec = P()
    sp = P(PEER_AXIS)
    sr = P()
    opt_spec = sp
    if mp_specs is not None:
        params_spec, opt_spec = mp_specs[:2]
    # Per-round state-family stacks place like the optimizer state: peer
    # axis + the matching param's spec per leaf under model parallelism,
    # plain peer-stacked otherwise. The SCAFFOLD server c mirrors the
    # params placement itself (replicated across peers, sharded across
    # any model axis exactly as the params are).
    mp_extra = mp_specs[2] if mp_specs is not None else {}
    ci_spec = mp_extra.get("scaffold_ci", sp)
    err_spec = mp_extra.get("compress_err", sp)

    # Inputs [P, S, ...]: under sequence parallelism the third dimension
    # (image height for ViT — the stride-aligned patch stem makes row blocks
    # independent) is additionally sharded over the seq axis.
    x_spec = P(PEER_AXIS, None, SEQ_AXIS) if seq_axis is not None else sp
    if cfg.scaffold:
        # (params, opt, c, ci, rng, x, y, tid, byz, round, key) ->
        # (params, opt, losses, c, ci).
        smapped = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(params_spec, opt_spec, params_spec, ci_spec, sp, x_spec, sp, sr, sr, sr, sr),
            out_specs=(params_spec, opt_spec, sp, params_spec, ci_spec),
        )
    elif cfg.compress == "topk":
        # (params, opt, err, rng, x, y, tid, byz, round, key) ->
        # (params, opt, losses, err). The residual stack shards like the
        # optimizer state. (qsgd is stateless and rides the plain branch.)
        smapped = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(params_spec, opt_spec, err_spec, sp, x_spec, sp, sr, sr, sr, sr),
            out_specs=(params_spec, opt_spec, sp, err_spec),
        )
    else:
        smapped = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(params_spec, opt_spec, sp, x_spec, sp, sr, sr, sr, sr),
            out_specs=(params_spec, opt_spec, sp) + ((sp,) if emit_delta else ()),
        )

    def round_fn(state: PeerState, x, y, trainer_idx, byz_gate, mask_key):
        if cfg.scaffold:
            new_params, new_opt, losses, new_c, new_ci = smapped(
                state.params,
                state.opt_state,
                state.scaffold_c,
                state.scaffold_ci,
                state.rng,
                x,
                y,
                trainer_idx,
                byz_gate,
                state.round_idx,
                mask_key,
            )
            out = (new_params, new_opt, losses)
            scaffold_c, scaffold_ci = new_c, new_ci
            compress_err = state.compress_err
        elif cfg.compress == "topk":
            new_params, new_opt, losses, compress_err = smapped(
                state.params,
                state.opt_state,
                state.compress_err,
                state.rng,
                x,
                y,
                trainer_idx,
                byz_gate,
                state.round_idx,
                mask_key,
            )
            out = (new_params, new_opt, losses)
            scaffold_c, scaffold_ci = state.scaffold_c, state.scaffold_ci
        else:
            out = smapped(
                state.params,
                state.opt_state,
                state.rng,
                x,
                y,
                trainer_idx,
                byz_gate,
                state.round_idx,
                mask_key,
            )
            scaffold_c, scaffold_ci = state.scaffold_c, state.scaffold_ci
            compress_err = state.compress_err
        new_params, new_opt, losses = out[:3]
        metrics = {"train_loss": losses}
        if emit_delta:
            metrics["delta"] = out[3]
        new_params, server_m, server_v = _apply_server_update(
            cfg, state.params, new_params, state.server_m, state.server_v
        )
        new_state = PeerState(
            params=new_params,
            opt_state=new_opt,
            rng=state.rng,
            round_idx=state.round_idx + 1,
            server_m=server_m,
            server_v=server_v,
            scaffold_c=scaffold_c,
            scaffold_ci=scaffold_ci,
            compress_err=compress_err,
        )
        return new_state, metrics

    # Donate the state: without it every round copies the full working set
    # (for gossip, num_peers × model) through HBM just to preserve a buffer
    # no caller reads again.
    # traced(): each dispatch (trace/compile on first call, async enqueue
    # after) shows as a "dispatch.*" span when event tracing is on; the
    # wrapper's ``program_name`` ("round") keys the driver's recompile
    # sentinel and cost-model registries.
    return telemetry.traced(
        "dispatch.round", jax.jit(round_fn, donate_argnums=(0,))
    )


def fused_block_sizes(
    rounds: int, rounds_per_call: int, start: int = 0
) -> tuple[int, ...]:
    """Distinct scan-block lengths ``run_fused`` will dispatch from
    ``start``: the trainer matrix is ``[block, T]``, so each distinct block
    length is one LEGITIMATE compile of the multi_round program (the tail
    block is shorter unless ``rounds_per_call`` divides the remaining
    rounds). The recompile sentinel's ``expected`` for ``multi_round`` is
    the length of this tuple — anything beyond it is an anomaly."""
    return tuple(
        sorted(
            {
                min(rounds_per_call, rounds - r0)
                for r0 in range(start, rounds, rounds_per_call)
            }
        )
    )


def build_multi_round_fn(
    cfg: Config, mesh: Mesh, attack: str = "none", pair_seeds=None
) -> Callable:
    """Compile R rounds as ONE device program: ``(state, x, y, trainer_mat
    [R, T], byz_gate [P] or [R, P], base_key) -> (state',
    {"train_loss": [R, P]})``.

    A ``lax.scan`` over rounds inside the same ``shard_map`` — the
    round-loop boundary costs zero host round-trips, so configs whose
    per-round work is small (the 8/128-peer stages, gossip rings) stop being
    dispatch-bound. Role sampling stays on the host (``trainer_mat`` row per
    round, same sampler as the sequential driver); per-round mask/attack
    keys derive on device by folding ``base_key`` with the round index, and
    the per-peer PRNG path is identical to the sequential round (the body
    folds each peer key with the absolute round index), so R fused rounds
    equal R sequential rounds exactly (test-asserted).

    The trust plane needs the host between training and aggregation, so
    fusion requires ``brb_enabled=False``. SCAFFOLD control variates and
    the EF compression residual ride the same scan carry as the server
    momentum/FedOpt buffers (their bodies already emit the updated state
    per round; the fused==sequential equivalence tests cover both).
    """
    if cfg.brb_enabled:
        raise ValueError("fused rounds cannot host the BRB trust plane between phases")
    pair_seeds = _resolve_pair_seeds(cfg, pair_seeds)
    seq_axis, tp_axis, ep_axis, pp_axis = _mesh_axes_for(cfg, mesh)
    model = build_model(
        cfg, seq_axis=seq_axis, tp_axis=tp_axis, ep_axis=ep_axis, pp_axis=pp_axis
    )
    opt = make_optimizer(cfg)
    l_per_dev = peers_per_device(cfg.num_peers, mesh)
    # One derivation site for model-parallel placement + the DP
    # sharded-leaf classification (same structure as build_round_fn).
    mp_kind = "tp" if tp_axis else ("ep" if ep_axis else ("pp" if pp_axis else None))
    mp_specs = _model_parallel_specs(cfg, mp_kind) if mp_kind else None
    mp_axis = tp_axis or ep_axis or pp_axis
    mp_sharded = _dp_sharded_tree(mp_specs[0], mp_axis) if mp_axis else None
    if params_layout(cfg) == "peer":
        body = _gossip_body(cfg, mesh, attack, model, opt, l_per_dev, emit_delta=False)
        params_spec = P(PEER_AXIS)
    elif cfg.peer_chunk > 0:
        body = _chunked_sync_body(cfg, attack, model, opt, l_per_dev, pair_seeds=pair_seeds)
        params_spec = P()
    elif _use_fast_sync_path(cfg, attack):
        body = _fast_sync_body(cfg, model, l_per_dev)
        params_spec = P()
    else:
        body = _general_sync_body(
            cfg, attack, model, opt, l_per_dev,
            seq_axis=seq_axis, ep_axis=ep_axis, pair_seeds=pair_seeds,
            mp_axis=mp_axis, mp_sharded=mp_sharded,
        )
        params_spec = P()
    sp = P(PEER_AXIS)
    sr = P()
    opt_spec = sp
    if mp_specs is not None:
        params_spec, opt_spec = mp_specs[:2]

    def multi_body(
        params, opt_state, server_m, server_v, extras, rng, x, y, trainer_mat, byz_gate, round0, base_key
    ):
        def step(carry, inputs):
            params, opt_state, server_m, server_v, extras = carry
            trainer_idx, gate_row, r = inputs
            # Absolute round index — identical mask/attack keys to the
            # sequential driver's fold_in(base, round_idx).
            mask_key = jax.random.fold_in(base_key, round0 + r)
            outs = body(
                params, opt_state, *extras, rng, x, y, trainer_idx, gate_row, round0 + r, mask_key
            )
            new_p, new_opt, losses = outs[:3]
            # SCAFFOLD: (c, ci); compression: (err,) — the bodies emit the
            # updated state after the losses, in the same order they take it.
            extras = tuple(outs[3:])
            # Same dispatch as the sequential round — the buffers ride the
            # scan carry (replicated P() values inside shard_map, so the
            # math is identical).
            new_p, server_m, server_v = _apply_server_update(
                cfg, params, new_p, server_m, server_v
            )
            return (new_p, new_opt, server_m, server_v, extras), losses

        rounds = trainer_mat.shape[0]
        # The per-round host decisions ride the scan xs as schedule arrays:
        # trainer rows [R, T] and byz-gate rows [R, P] — the device program
        # consumes one row per round, so per-round gating composes with
        # fusion with zero host round-trips.
        (params, opt_state, server_m, server_v, extras), losses = lax.scan(
            step,
            (params, opt_state, server_m, server_v, extras),
            (trainer_mat, byz_gate, jnp.arange(rounds)),
        )
        return params, opt_state, server_m, server_v, extras, losses  # losses: [R, L]

    x_spec = P(PEER_AXIS, None, SEQ_AXIS) if seq_axis is not None else sp
    # Buffer off => None (zero pytree leaves): a per-leaf model-parallel
    # spec TREE cannot prefix-broadcast over None, so the slot must
    # degrade to a bare P() spec; on, it mirrors the params placement
    # leaf-for-leaf.
    has_m = cfg.server_momentum > 0.0 or cfg.server_opt != "sgd"
    m_spec = params_spec if has_m else P()
    v_spec = params_spec if cfg.server_opt in ("adam", "yogi") else P()
    # Extra per-round state rides the scan carry next to the server buffers.
    # ONE list of (PeerState field, spec) pairs drives the spec, the packing,
    # and the state rebuild below — the bodies emit these fields after the
    # losses in this same order. The server's c mirrors the params placement
    # (replicated across peers, model-axis-sharded under tp/ep/pp); the
    # per-peer stacks (c_i, err) place like the optimizer state.
    mp_extra = mp_specs[2] if mp_specs is not None else {}
    if cfg.scaffold:
        extra_fields = (
            ("scaffold_c", params_spec),
            ("scaffold_ci", mp_extra.get("scaffold_ci", sp)),
        )
    elif cfg.compress == "topk":
        extra_fields = (("compress_err", mp_extra.get("compress_err", sp)),)
    else:
        extra_fields = ()
    extras_spec = tuple(s for _, s in extra_fields)
    smapped = jax.shard_map(
        multi_body,
        mesh=mesh,
        in_specs=(params_spec, opt_spec, m_spec, v_spec, extras_spec, sp, x_spec, sp, sr, sr, sr, sr),
        out_specs=(params_spec, opt_spec, m_spec, v_spec, extras_spec, P(None, PEER_AXIS)),
    )

    def multi_round_fn(state: PeerState, x, y, trainer_mat, byz_gate, base_key):
        # Accept either a static [P] gate (broadcast to every round of the
        # block) or a precomputed [R, P] per-round schedule; either way the
        # scan consumes one gate row per round.
        if byz_gate.ndim == 1:
            byz_gate = jnp.broadcast_to(
                byz_gate, (trainer_mat.shape[0],) + byz_gate.shape
            )
        extras = tuple(getattr(state, f) for f, _ in extra_fields)
        new_params, new_opt, server_m, server_v, extras, losses = smapped(
            state.params,
            state.opt_state,
            state.server_m,
            state.server_v,
            extras,
            state.rng,
            x,
            y,
            trainer_mat,
            byz_gate,
            state.round_idx,
            base_key,
        )
        carried = {f: v for (f, _), v in zip(extra_fields, extras)}
        new_state = PeerState(
            params=new_params,
            opt_state=new_opt,
            rng=state.rng,
            round_idx=state.round_idx + trainer_mat.shape[0],
            server_m=server_m,
            server_v=server_v,
            scaffold_c=carried.get("scaffold_c", state.scaffold_c),
            scaffold_ci=carried.get("scaffold_ci", state.scaffold_ci),
            compress_err=carried.get("compress_err", state.compress_err),
        )
        return new_state, {"train_loss": losses}

    return telemetry.traced(
        "dispatch.multi_round", jax.jit(multi_round_fn, donate_argnums=(0,))
    )


def build_trust_round_fns(
    cfg: Config, mesh: Mesh, attack: str = "none", pair_seeds=None
) -> tuple[Callable, Callable]:
    """The BRB-gated round: local training and aggregation as two compiled
    programs with the host trust plane deciding between them which trainers'
    updates the aggregate admits.

    This is the reference's core security semantic — a tester accumulates
    exactly the updates it received and signature-verified (reference
    ``node/node.py:130-145`` feeds ``received_models``;
    ``aggregator/aggregation.py:8-28`` consumes them) — realized SPMD-style:

    - ``train_fn(state, x, y, byz_gate, mask_key) -> (delta, new_opt,
      losses)``: every peer's local SGD; per-peer deltas stay on device.
    - The driver digests each live trainer's delta
      (``crypto.digest_update``), BRB-broadcasts the digests, and replaces
      undelivered/unverified trainers with ``-1`` in the trainer vector.
    - ``agg_fn(state, delta, new_opt, trainer_idx, mask_key, masked_idx=None)
      -> state'``: masked aggregation over the *gated* trainer vector +
      server update. A gated-out trainer contributes nothing to this round's
      aggregate (and its optimizer state does not advance, exactly as if
      never sampled). Under secure_fedavg the driver passes ``masked_idx``
      (the pre-gate trainer vector) so the orphaned pairwise masks a
      gated-out trainer left in its surviving partners' deltas are cancelled
      by ``residual_mask_sum`` — the Bonawitz dropout-recovery semantic.

    Gating applies to the mean family (fedavg/secure_fedavg, via ``-1``
    vacancy). The gathered robust reducers take their full update matrix —
    they are content-robust in-band by construction (tolerate f Byzantine
    updates) — so for them delivery failures remain observational (next-round
    sampling exclusion), which the driver handles.

    Gossip (peer layout) has no admit step — the mix is in-band — so it uses
    the fused round; requesting the split pipeline for it is an error.
    """
    if params_layout(cfg) == "peer":
        raise ValueError("gossip has no gated aggregate; use build_round_fn")
    pair_seeds = _resolve_pair_seeds(cfg, pair_seeds)
    model = build_model(cfg)
    opt = make_optimizer(cfg)
    l_per_dev = peers_per_device(cfg.num_peers, mesh)
    train = _local_train_phase(cfg, attack, model, opt, l_per_dev)
    # Runtime seeds: key rotation after dropout recovery swaps the matrix
    # without recompiling the aggregate. The resolved matrix doubles as the
    # default `seeds` argument, so callers that never rotate (multihost
    # workers, tests) need not thread it through.
    runtime_seeds = pair_seeds is not None
    default_seeds = jnp.asarray(pair_seeds) if runtime_seeds else None
    agg = _aggregate_phase(cfg, l_per_dev, gated=True, runtime_seeds=runtime_seeds)
    sp = P(PEER_AXIS)
    sr = P()
    train_smapped = jax.shard_map(
        train,
        mesh=mesh,
        in_specs=(sr, sp, sp, sp, sp, sr, sr, sr),
        out_specs=(sp, sp, sp),
    )
    agg_smapped = jax.shard_map(
        agg,
        mesh=mesh,
        in_specs=(sr, sp, sp, sp, sr, sr, sr, sr) + ((sr,) if runtime_seeds else ()),
        out_specs=(sr, sp),
    )

    def train_fn(state: PeerState, x, y, byz_gate, mask_key):
        return train_smapped(
            state.params,
            state.opt_state,
            state.rng,
            x,
            y,
            byz_gate,
            state.round_idx,
            mask_key,
        )

    def agg_fn(state: PeerState, delta, new_opt, trainer_idx, mask_key, masked_idx=None, seeds=None):
        # ``masked_idx``: the PRE-gate trainer vector the deltas were masked
        # against (driver passes it under secure_fedavg so orphaned masks of
        # gated-out trainers get cancelled); defaults to the gated vector
        # for callers without mid-round dropout (no residual exists then).
        # ``seeds``: the CURRENT ECDH seed matrix (rotation-aware) when the
        # phase was built with one.
        if masked_idx is None:
            masked_idx = trainer_idx
        if seeds is None:
            seeds = default_seeds
        extra = (seeds,) if runtime_seeds else ()
        new_params, kept_opt = agg_smapped(
            state.params, state.opt_state, new_opt, delta, trainer_idx,
            masked_idx, mask_key, state.round_idx, *extra,
        )
        # Stateful server optimizers compose with the trust plane: the
        # FedAvgM/FedOpt step applies to the GATED aggregate (what the
        # verdict admitted), reconstructed from (p' - p)/server_lr on the
        # replicated arrays — identical helpers to the fused round, so
        # all-verify gated rounds match it exactly (tested).
        new_params, server_m, server_v = _apply_server_update(
            cfg, state.params, new_params, state.server_m, state.server_v
        )
        # A fully-vacated round (every trainer crashed or gated out — the
        # chaos plane's worst case) must be a TRUE no-op: the masked sum is
        # zero, but a stateful server optimizer would still decay momentum /
        # advance Adam moments on that zero delta. Carry params and server
        # state over unchanged; round_idx still advances.
        vacant = jnp.all(trainer_idx < 0)

        def keep(old, new):
            return jax.tree.map(lambda o, n: jnp.where(vacant, o, n), old, new)

        new_params = keep(state.params, new_params)
        if server_m is not None:
            server_m = keep(state.server_m, server_m)
        if server_v is not None:
            server_v = keep(state.server_v, server_v)
        return PeerState(
            params=new_params,
            opt_state=kept_opt,
            rng=state.rng,
            round_idx=state.round_idx + 1,
            server_m=server_m,
            server_v=server_v,
        )

    # agg_fn consumes the round's transients (deltas + trained opt state) and
    # the previous state — donate all three; train_fn's inputs are all read
    # again by agg_fn, so it donates nothing.
    return (
        telemetry.traced("dispatch.train", jax.jit(train_fn)),
        telemetry.traced(
            "dispatch.agg", jax.jit(agg_fn, donate_argnums=(0, 1, 2))
        ),
    )


def build_digest_pack_fn(delta) -> tuple[Callable, Callable]:
    """Single-transfer digesting: pack every trainer's update bytes into
    ONE device buffer so the trust plane's digest step costs exactly one
    ``jax.device_get`` per round.

    ``delta`` is an example peer-stacked update tree (leaves ``[P, ...]``,
    concrete or abstract) fixing the layout. Returns ``(pack_fn,
    hash_row)``:

    - ``pack_fn(delta, trainer_idx)``: jitted; for each leaf (in
      ``tree_flatten_with_path`` order, the canonical ``digest_update``
      order) gathers the ``[T]`` trainer rows, bitcasts to bytes, and
      concatenates into a ``[T, total_bytes]`` uint8 buffer. All shapes
      are static — varying trainer ids and ``-1`` vacancy padding never
      retrigger XLA compilation after the first call. Vacant (``-1``)
      slots are clamped to row 0 on device; the caller discards those
      rows on the host.
    - ``hash_row(row)``: host-side SHA-256 over one fetched row
      interleaved with the canonical per-leaf headers
      (``crypto.make_row_digester``) — bit-identical to
      ``crypto.digest_update`` of that trainer's slice tree.

    The byte layout relies on ``lax.bitcast_convert_type(x, uint8)``
    emitting least-significant-byte-first along the new minor axis, which
    matches ``np.ndarray.tobytes()`` on the little-endian hosts and TPUs
    this runs on (asserted bit-for-bit by the digest-equivalence test).
    """
    from jax.tree_util import keystr, tree_flatten_with_path

    from p2pdl_tpu.protocol.crypto import make_row_digester

    leaves = tree_flatten_with_path(delta)[0]
    if not leaves:
        raise ValueError("cannot build a digest pack for an empty update tree")
    num_peers = int(leaves[0][1].shape[0])
    meta = []
    for path, leaf in leaves:
        row_shape = tuple(int(s) for s in leaf.shape[1:])
        dtype = jnp.dtype(leaf.dtype)
        nbytes = math.prod(row_shape) * dtype.itemsize
        meta.append((keystr(path), row_shape, str(dtype), nbytes))
    hash_row = make_row_digester(meta)

    def pack(delta, trainer_idx):
        # Clamp instead of letting a traced -1 wrap: the gathered bytes for
        # a vacant slot are deterministic garbage (row 0) the host skips.
        idx = jnp.clip(trainer_idx, 0, num_peers - 1)
        rows = []
        for _, leaf in tree_flatten_with_path(delta)[0]:
            g = jnp.take(leaf, idx, axis=0)
            flat = g.reshape((g.shape[0], -1))
            b = lax.bitcast_convert_type(flat, jnp.uint8)
            if b.ndim == 3:  # itemsize > 1 adds a trailing byte axis
                b = b.reshape((flat.shape[0], -1))
            rows.append(b)
        return jnp.concatenate(rows, axis=1)

    return telemetry.traced("dispatch.digest_pack", jax.jit(pack)), hash_row


def build_compressed_pack_fn(
    delta, mode: str, ratio: float
) -> tuple[Callable, Callable]:
    """Compressed sibling of :func:`build_digest_pack_fn`: one
    ``[T, compressed_bytes]`` uint8 buffer per round, quantized/sparsified
    on device per the ``ops.delta_codec`` wire layout.

    Same discipline as the dense pack — exactly one ``jax.device_get`` per
    round downstream, all shapes static (``mode``/``ratio`` are baked into
    the program; per-leaf ``k`` comes from the layout), and the vacancy
    clamp (``-1`` -> row 0) so shrunken rounds never recompile. Returns
    ``(pack_fn, hash_row)`` shaped exactly like the dense pair so the
    driver swaps them interchangeably:

    - ``pack_fn(delta, trainer_idx)``: jitted; per leaf gathers the ``[T]``
      trainer rows, encodes them (int8 quantize routed through the fused
      Pallas kernel when ``ops.pallas_codec.use_fused()`` — Mosaic on TPU,
      XLA encoder elsewhere, interpreter under the test hook), and
      concatenates the wire segments.
    - ``hash_row(row)``: host-side SHA-256 over one fetched COMPRESSED row
      (``crypto.make_segment_digester`` over the layout's per-leaf
      headers+widths) — the digest BRB signs is over the bytes the wire
      ships, so ``agg_admit`` lineage and ``cli audit`` hold unchanged.

    The returned ``pack_fn`` carries the ``CodecLayout`` as ``.layout``
    (the receiver side and the byte accounting both need it).
    """
    from p2pdl_tpu.ops import delta_codec, pallas_codec
    from p2pdl_tpu.protocol.crypto import make_segment_digester

    layout = delta_codec.layout_from_tree(delta, mode, ratio)
    leaves = jax.tree_util.tree_flatten_with_path(delta)[0]
    num_peers = int(leaves[0][1].shape[0])
    hash_row = make_segment_digester(layout.digest_segments())

    def pack(delta, trainer_idx):
        idx = jnp.clip(trainer_idx, 0, num_peers - 1)
        segs = []
        for leaf_codec, (_, leaf) in zip(layout.leaves, jax.tree_util.tree_flatten_with_path(delta)[0]):
            g = jnp.take(leaf, idx, axis=0)
            flat = g.reshape((g.shape[0], -1))
            if mode == "int8" and pallas_codec.use_fused():
                segs.append(pallas_codec.fused_encode_int8(flat))
            else:
                segs.append(delta_codec.encode_jax(flat, mode, k=leaf_codec.k))
        return jnp.concatenate(segs, axis=1)

    pack_fn = telemetry.traced("dispatch.compressed_pack", jax.jit(pack))  # p2plint: disable=donation-discipline -- sanctioned: pack reads a delta the aggregate phase still consumes; donation would free live buffers
    pack_fn.layout = layout
    return pack_fn, hash_row


def build_gossip_trust_round_fns(
    cfg: Config, mesh: Mesh, attack: str = "none"
) -> tuple[Callable, Callable]:
    """The BRB-gated gossip round: train and mix as two compiled programs
    with the trust verdict deciding the mixing weights between them.

    Round 3 ran gossip BRB observationally — an equivocator's corrupted
    params still mixed into its neighbors in the round where it cheated,
    with exclusion arriving one round late. Here the mix itself is gated
    (the reference's aggregate-only-verified semantic, reference
    ``node/node.py:130-145``, applied to the in-band mix):

    - ``train_fn(state, x, y, byz_gate, mask_key) -> (attacked, new_opt,
      losses, delta)``: every peer trains and (if Byzantine) corrupts; its
      post-update params stay peer-local on device, its delta is digested
      and BRB-broadcast by the host.
    - ``mix_fn(state, attacked, new_opt, verdict) -> state'``: the
      graph mix with an UNVERIFIED peer's weight zeroed in every
      neighbor's row (mass reverting to self) — its params provably never
      enter any honest peer's round-r mix (test-asserted). ``verdict``:
      ``[P]`` 1.0 = delivered + digest-verified.
    """
    if params_layout(cfg) != "peer":
        raise ValueError("gossip trust round requires the peer params layout")
    model = build_model(cfg)
    opt = make_optimizer(cfg)
    l_per_dev = peers_per_device(cfg.num_peers, mesh)
    local_train = make_local_train(cfg, model, opt)
    sp = P(PEER_AXIS)
    sr = P()

    def train_phase(params, opt_state, rng, x, y, byz_gate, round_idx, mask_key):
        dev = lax.axis_index(PEER_AXIS)
        local_ids = dev * l_per_dev + jnp.arange(l_per_dev)
        round_keys = jax.vmap(lambda k: jax.random.fold_in(k, round_idx))(rng)
        gate = byz_gate[local_ids]
        y = poison_labels(attack, y, gate, _num_classes(cfg))
        tau = _epoch_counts(cfg, local_ids, round_idx)
        new_params, new_opt, losses = jax.vmap(
            local_train,
            in_axes=(0, 0, 0, 0, 0, None, 0 if tau is not None else None),
        )(params, opt_state, round_keys, x, y, None, tau)
        delta = jax.tree.map(lambda n, p: n - p, new_params, params)
        delta = apply_attack(
            attack, delta, gate, mask_key,
            axis_name=PEER_AXIS, peer_ids=local_ids,
        )
        attacked = jax.tree.map(lambda p, d: p + d, params, delta)
        return attacked, new_opt, losses, delta

    def mix_phase(attacked, verdict, round_idx):
        dev = lax.axis_index(PEER_AXIS)
        local_ids = dev * l_per_dev + jnp.arange(l_per_dev)
        vm = verdict[local_ids]
        return (
            exp_mix(attacked, round_idx, mask=vm)
            if cfg.gossip_graph == "exponential"
            else ring_mix(attacked, mask=vm)
        )

    train_smapped = jax.shard_map(
        train_phase,
        mesh=mesh,
        in_specs=(sp, sp, sp, sp, sp, sr, sr, sr),
        out_specs=(sp, sp, sp, sp),
    )
    mix_smapped = jax.shard_map(
        mix_phase, mesh=mesh, in_specs=(sp, sr, sr), out_specs=sp
    )

    def train_fn(state: PeerState, x, y, byz_gate, mask_key):
        return train_smapped(
            state.params, state.opt_state, state.rng, x, y,
            byz_gate, state.round_idx, mask_key,
        )

    def mix_fn(state: PeerState, attacked, new_opt, verdict):
        mixed = mix_smapped(attacked, verdict, state.round_idx)
        return PeerState(
            params=mixed,
            opt_state=new_opt,
            rng=state.rng,
            round_idx=state.round_idx + 1,
        )

    # mix_fn consumes the round transients and the previous state.
    return (
        telemetry.traced("dispatch.train", jax.jit(train_fn)),
        telemetry.traced(
            "dispatch.mix", jax.jit(mix_fn, donate_argnums=(0, 1, 2))
        ),
    )


def _gossip_body(cfg, mesh, attack, model, opt, l_per_dev, emit_delta=False):
    """Decentralized averaging (D-PSGD): peer-stacked params; every peer
    trains, then mixes parameters with its graph neighbors (``cfg.
    gossip_graph``: static ring or round-cycled exponential strides) — no
    roles, no global sync. Byzantine peers mix their corrupted params into
    the graph. With ``emit_delta`` (trust plane on) the per-peer deltas are
    returned so the host can digest-broadcast them."""
    local_train = make_local_train(cfg, model, opt)

    def body(params, opt_state, rng, x, y, trainer_idx, byz_gate, round_idx, mask_key):
        dev = lax.axis_index(PEER_AXIS)
        local_ids = dev * l_per_dev + jnp.arange(l_per_dev)
        round_keys = jax.vmap(lambda k: jax.random.fold_in(k, round_idx))(rng)
        gate = byz_gate[local_ids]
        y = poison_labels(attack, y, gate, _num_classes(cfg))
        tau = _epoch_counts(cfg, local_ids, round_idx)
        new_params, new_opt, losses = jax.vmap(
            local_train,
            in_axes=(0, 0, 0, 0, 0, None, 0 if tau is not None else None),
        )(params, opt_state, round_keys, x, y, None, tau)
        delta = jax.tree.map(lambda n, p: n - p, new_params, params)
        delta = apply_attack(
            attack, delta, gate, mask_key,
            axis_name=PEER_AXIS, peer_ids=local_ids,
        )
        attacked = jax.tree.map(lambda p, d: p + d, params, delta)
        mixed = (
            exp_mix(attacked, round_idx)
            if cfg.gossip_graph == "exponential"
            else ring_mix(attacked)
        )
        if emit_delta:
            return mixed, new_opt, losses, delta
        return mixed, new_opt, losses

    return body


def _fast_sync_body(cfg, model, l_per_dev):
    """Single-local-step plain-SGD FedAvg as one pooled gradient step.

    ``mean over trainers of (-lr·∇loss_peer) = -lr·∇(mean over trainers of
    loss_peer)``, and the server update ``p += server_lr·mean(delta)``
    becomes ``p -= server_lr·lr·∇(pooled loss)``: one batched
    forward/backward over every trainer's full shard with a single ``psum``
    of gradients — arithmetic intensity ∝ total pooled batch instead of one
    peer's batch, and no ``[P, ...]`` delta materialization."""
    loss_fn = make_loss_fn(model, jnp.dtype(cfg.compute_dtype))

    def body(params, opt_state, rng, x, y, trainer_idx, byz_gate, round_idx, mask_key):
        dev = lax.axis_index(PEER_AXIS)
        local_ids = dev * l_per_dev + jnp.arange(l_per_dev)
        gate = jnp.isin(local_ids, trainer_idx).astype(jnp.float32)
        # Live trainer count (vacant -1 slots match no local id), so shrunken
        # participation normalizes correctly.
        count = jnp.maximum(lax.psum(jnp.sum(gate), PEER_AXIS), 1.0)

        def pooled_loss(p):
            losses = jax.vmap(lambda xp, yp: loss_fn(p, xp, yp))(x, y)  # [L]
            return jnp.sum(losses * gate) / count, losses

        # pvary: differentiate w.r.t. a device-VARYING view of the replicated
        # params. Grad of a varying loss w.r.t. an invariant value would make
        # JAX insert an implicit psum in the backward pass (the transpose of
        # the replicated->varying broadcast), and the explicit psum below
        # would then double-count by the device count.
        grads, losses = jax.grad(pooled_loss, has_aux=True)(
            jax.lax.pcast(params, PEER_AXIS, to="varying")
        )
        grads = jax.tree.map(lambda g: lax.psum(g, PEER_AXIS), grads)
        new_p = jax.tree.map(
            lambda p, g: p - (cfg.server_lr * cfg.lr) * g.astype(p.dtype), params, grads
        )
        return new_p, opt_state, losses

    return body


def _local_train_phase(
    cfg, attack, model, opt, l_per_dev, seq_axis=None, ep_axis=None, with_bias=False
):
    """Phase fragment (inside ``shard_map``): every peer's local SGD from the
    replicated global params, returning the (possibly attacked) per-peer
    deltas — the round up to the point where the reference's trainer ships
    its update (reference ``node/node.py:265-297``).

    ``with_bias=True`` (SCAFFOLD): the phase takes a per-peer gradient-bias
    pytree (``[L, ...]`` leaves, the ``c - c_i`` correction) vmapped into
    every local step."""
    local_train = make_local_train(cfg, model, opt, seq_axis=seq_axis, ep_axis=ep_axis)

    def phase(params, opt_state, rng, x, y, byz_gate, round_idx, mask_key, grad_bias=None):
        dev = lax.axis_index(PEER_AXIS)
        local_ids = dev * l_per_dev + jnp.arange(l_per_dev)
        round_keys = jax.vmap(lambda k: jax.random.fold_in(k, round_idx))(rng)
        # pvary over the PEER axis only: grad w.r.t. an invariant value under
        # shard_map gets an implicit psum inserted (transpose of the
        # replicated->varying broadcast), which would silently turn per-peer
        # local gradients into the global sum. Along the SEQ axis that
        # implicit psum is exactly the desired semantics (sum the shards'
        # token-block gradient contributions), so params stay seq-invariant.
        # Likewise along the EP axis for the non-expert leaves (the expert
        # leaves enter ep-varying via their P(ep) placement and stay so).
        pvaried = jax.lax.pcast(params, PEER_AXIS, to="varying")
        # Data-space poisoning happens BEFORE training (a label-flipper's
        # optimizer is honest; its data is not) — model-space corruptions
        # apply to the delta after.
        y = poison_labels(attack, y, byz_gate[local_ids], _num_classes(cfg))
        tau = _epoch_counts(cfg, local_ids, round_idx)
        new_params, new_opt, losses = jax.vmap(
            local_train,
            in_axes=(
                None, 0, 0, 0, 0, 0 if with_bias else None,
                0 if tau is not None else None,
            ),
        )(pvaried, opt_state, round_keys, x, y, grad_bias, tau)

        if ep_axis is not None:
            # local_train reports its 1/ep-scaled shard-slice loss mean;
            # the sum over ep shards is the true batch loss.
            losses = lax.psum(losses, ep_axis)
        delta = jax.tree.map(lambda n, p: n - p[None], new_params, pvaried)
        gate = byz_gate[local_ids]
        delta = apply_attack(
            attack, delta, gate, mask_key,
            axis_name=PEER_AXIS, peer_ids=local_ids,
        )
        return delta, new_opt, losses

    return phase


def _dp_noise_tree(cfg, agg, mask_key, dp_axis=None, dp_sharded=None):
    """Gaussian mechanism on the clipped mean: std = z * C / T_cfg (the
    fixed DP denominator). The key derives from the replicated mask_key,
    so every device adds the IDENTICAL draw and peers stay in lockstep —
    which also makes the chunked and general bodies' noisy rounds
    bit-equal (shared helper, same per-leaf key schedule). Under a
    model-parallel layout (``dp_axis``), sharded leaves fold the shard
    index in so equal-shaped slices draw INDEPENDENT noise (correlated
    slice noise would have off-spec covariance after the logical concat);
    replicated leaves keep the shared key — they must stay bit-identical
    across shards. Noise adds in float32 and casts ONCE afterwards:
    casting the noise to a low-precision leaf dtype BEFORE the add would
    quantize it to the leaf's ulp grid (a discretized Gaussian breaks the
    continuous-mechanism RDP bound); quantizing the already-noised sum is
    data-independent post-processing, which preserves DP."""
    noise_key = jax.random.fold_in(mask_key, 0x6D70)  # "dp"
    std = cfg.dp_noise_multiplier * cfg.dp_clip / cfg.trainers_per_round
    leaves, treedef = jax.tree_util.tree_flatten(agg)
    keys = list(jax.random.split(noise_key, len(leaves)))
    if dp_axis is not None:
        ax = lax.axis_index(dp_axis)
        keys = [
            jax.random.fold_in(k, ax) if s else k
            for k, s in zip(keys, jax.tree.leaves(dp_sharded))
        ]
    return jax.tree_util.tree_unflatten(
        treedef,
        [
            (
                l.astype(jnp.float32)
                + std * jax.random.normal(k, l.shape, jnp.float32)
            ).astype(l.dtype)
            for l, k in zip(leaves, keys)
        ],
    )


def _dp_clip_scale(cfg, sq):
    """``min(1, C / ||delta||)`` per peer from the summed squares ``sq``."""
    return jnp.minimum(1.0, cfg.dp_clip / jnp.maximum(jnp.sqrt(sq), 1e-12))


def _aggregate_phase(
    cfg, l_per_dev, pair_seeds=None, gated=False, runtime_seeds=False,
    dp_axis=None, dp_sharded=None,
):
    """Phase fragment (inside ``shard_map``): admit the trainer-gated deltas
    into the aggregate, apply one deterministic server update, and advance
    only trainers' optimizer state — the reference's tester-side
    accumulate/average/apply (reference ``aggregator/aggregation.py:15-38``).

    Secure aggregation keys on ``pair_seeds`` when given (the ECDH-derived
    ``[P, P, 2]`` matrix from ``protocol/secure_keys``, baked in as a
    compile-time constant) and otherwise on the legacy shared ``mask_key``.
    With ``gated=True`` (the BRB trust pipeline) masks pair over the
    PRE-gate trainer vector ``masked_idx`` — what each trainer knew when it
    shipped its masked update — and the orphaned masks a gated-out trainer
    leaves in its surviving partners' deltas are cancelled by subtracting
    ``residual_mask_sum`` (the Shamir dropout-recovery flow, reference-less:
    the reference has no masking at all).

    ``runtime_seeds=True`` (the gated driver path) takes the seed matrix as
    a trailing RUNTIME argument instead of a baked constant, so key ROTATION
    after a dropout-recovery event (``SecureAggKeyring.rotate``) swaps in
    fresh seeds without recompiling.

    ``dp_axis``/``dp_sharded`` (a mesh-axis name + a per-leaf bool tree,
    set when DP composes with a model-parallel layout): each device holds
    only a SLICE of a peer's update for the sharded leaves, so the clip
    norm is completed by a ``psum`` of those leaves' partial squares over
    the model axis (replicated leaves contribute once — a blind psum
    would overcount them ``shards``-fold and under-clip nothing but
    OVER-count sensitivity), and the noise key folds in the shard index
    for sharded leaves only, so equal-shaped slices draw independent
    noise while replicated leaves stay bit-identical across shards (the
    shard_map vma check enforces the latter)."""
    const = None if runtime_seeds else (
        jnp.asarray(pair_seeds) if pair_seeds is not None else None
    )

    def core(params, opt_state, new_opt, delta, trainer_idx, masked_idx, mask_key, round_idx, *seeds_arg):
        seeds_const = seeds_arg[0] if runtime_seeds else const
        dev = lax.axis_index(PEER_AXIS)
        local_ids = dev * l_per_dev + jnp.arange(l_per_dev)
        is_trainer = jnp.isin(local_ids, trainer_idx)

        if cfg.delta_compression != "none":
            # Compressed wire semantics: what aggregation consumes is the
            # codec ROUNDTRIP of each peer's raw delta — bit-identical to
            # decode(encode(row)) of the bytes build_compressed_pack_fn
            # ships and BRB signs ("what is signed is what is shipped").
            # Row-wise per peer, so it composes with the peer sharding;
            # applied before any other delta transform (Config validation
            # forbids the combinations that would reorder it).
            from p2pdl_tpu.ops import delta_codec as _codec

            def _roundtrip(d):
                flat = d.reshape(l_per_dev, -1)
                k = (
                    _codec.topk_count(flat.shape[1], cfg.compress_ratio)
                    if cfg.delta_compression == "topk"
                    else None
                )
                return _codec.roundtrip_jax(flat, cfg.delta_compression, k).reshape(
                    d.shape
                )

            delta = jax.tree.map(_roundtrip, delta)

        tau_eff = None
        if cfg.fednova:
            # FedNova (Wang et al. 2020): each trainer SHIPS its
            # step-normalized delta d_i = delta_i / a_i (so masking/
            # robust semantics see the normalized update), and the mean
            # is rescaled by tau_eff = mean(a_i over live trainers) after
            # aggregation. Homogeneous work: a_i constant => exactly
            # FedAvg (test-asserted).
            a = _local_steps(cfg, local_ids, round_idx)  # [L]
            delta = _fednova_normalize(delta, a, l_per_dev)
            tau_eff = _fednova_tau_eff(is_trainer, a)

        if cfg.dp_clip > 0.0:
            # DP-FedAvg clipping (McMahan et al. 2018): bound each peer's
            # L2 contribution BEFORE masking and aggregation — on the raw
            # delta, exactly what a DP client would ship (composes with
            # secure aggregation: clip locally, then mask).
            def leaf_sq(d):
                return jnp.sum(
                    d.astype(jnp.float32).reshape(l_per_dev, -1) ** 2, axis=1
                )

            if dp_axis is None:
                sq = sum(leaf_sq(d) for d in jax.tree.leaves(delta))
            else:
                # Model-parallel layout: complete the global per-peer L2
                # over the model axis (sharded leaves hold slices);
                # replicated leaves enter once, outside the psum.
                zero = jnp.zeros((l_per_dev,), jnp.float32)
                flags = jax.tree.leaves(dp_sharded)
                parts = jax.tree.leaves(delta)
                sh = sum((leaf_sq(d) for d, s in zip(parts, flags) if s), zero)
                rep = sum((leaf_sq(d) for d, s in zip(parts, flags) if not s), zero)
                sq = lax.psum(sh, dp_axis) + rep
            clip_scale = _dp_clip_scale(cfg, sq)  # [L]
            delta = jax.tree.map(
                lambda d: (
                    d.astype(jnp.float32)
                    * clip_scale.reshape((l_per_dev,) + (1,) * (d.ndim - 1))
                ).astype(d.dtype),
                delta,
            )

        if cfg.aggregator == "secure_fedavg":
            # Every PRE-gate trainer masked before the gate fell; gated-out
            # trainers' (masked) deltas are excluded wholesale by the
            # is_trainer weights below.
            is_masked = jnp.isin(local_ids, masked_idx)
            delta = jax.vmap(
                lambda d, pid, it: apply_masks(
                    d, mask_key, pid, masked_idx, it,
                    neighbors=cfg.secure_agg_neighbors,
                    pair_seeds=seeds_const, round_idx=round_idx,
                )
            )(delta, local_ids, is_masked)

        if cfg.aggregator in ("fedavg", "secure_fedavg"):
            if cfg.dp_clip > 0.0:
                # FIXED denominator (McMahan et al. 2018's qW): dividing by
                # the live count would make the denominator itself
                # data-dependent and one trainer's influence up to 2C/T —
                # silently doubling the privacy spend the accountant
                # certifies. With sum/T_cfg the sensitivity is exactly
                # C/T_cfg. (A vacancy-shrunken DP round underweights — the
                # standard DP-FL tradeoff.)
                count = jnp.float32(cfg.trainers_per_round)
            else:
                count = jnp.maximum(
                    lax.psum(jnp.sum(is_trainer.astype(jnp.float32)), PEER_AXIS), 1.0
                )

            # Masked-psum fast path: never materializes per-peer copies.
            def leaf(d):
                w = is_trainer.astype(d.dtype).reshape((l_per_dev,) + (1,) * (d.ndim - 1))
                return lax.psum(jnp.sum(d * w, axis=0), PEER_AXIS) / count.astype(d.dtype)

            agg = jax.tree.map(leaf, delta)
            if gated and cfg.aggregator == "secure_fedavg":
                # lax.cond on the replicated drop predicate: the residual is
                # a sequential scan-of-scans of O(T x partners) model-sized
                # PRF draws — provably zero (and pure waste) in the common
                # no-dropout round, so don't execute it there.
                def with_resid(a):
                    resid = residual_mask_sum(
                        a, masked_idx, trainer_idx,
                        neighbors=cfg.secure_agg_neighbors,
                        base_key=mask_key, pair_seeds=seeds_const, round_idx=round_idx,
                    )
                    return jax.tree.map(
                        lambda x, r: x - r.astype(x.dtype) / count.astype(x.dtype),
                        a, resid,
                    )

                agg = lax.cond(
                    jnp.any(masked_idx != trainer_idx),
                    with_resid,
                    lambda a: a,
                    agg,
                )
            if tau_eff is not None:
                agg = _fednova_rescale(agg, tau_eff)
        elif cfg.robust_impl == "blockwise":
            # Stream the peer axis through feature blocks: O(P x block)
            # transient instead of O(P x model) per device (SURVEY §7 hard
            # part (b)) — the 1024-peer-capable path. Results are already
            # replicated (masked-psum extraction / psum-selected vector).
            agg = _aggregate_blockwise(cfg, delta, trainer_idx)
        else:
            # Robust reducers need every trainer's update visible everywhere.
            all_d = jax.tree.map(
                lambda d: lax.all_gather(d, PEER_AXIS, axis=0, tiled=True), delta
            )
            agg = _aggregate(cfg, jax.tree.map(lambda d: d[trainer_idx], all_d))
            # The reducer's result is bitwise identical on every device, but
            # the vma type system can't infer that through argsort/gather —
            # materialize it as replicated by psum-selecting device 0's copy.
            agg = jax.tree.map(
                lambda a: lax.psum(jnp.where(dev == 0, a, jnp.zeros_like(a)), PEER_AXIS),
                agg,
            )

        if cfg.dp_noise_multiplier > 0.0:
            agg = _dp_noise_tree(cfg, agg, mask_key, dp_axis, dp_sharded)

        # Server update (reference applies 0.1 * avg_delta in place,
        # ``aggregator/aggregation.py:36-38``); peers stay in lockstep.
        new_p = jax.tree.map(
            lambda p, a: p + cfg.server_lr * a.astype(p.dtype), params, agg
        )

        # Only this round's trainers actually trained in the reference
        # (non-trainers idle, ``main.py:72-80``): their optimizer state
        # (momentum, if enabled) must not advance. The optimizer is per-peer
        # for the experiment's lifetime (reference ``node/node.py:30``).
        # Under BRB gating this also rolls back excluded trainers' optimizer
        # advance — a gated-out trainer is treated exactly as never sampled.
        def keep_trainers(n, o):
            m = is_trainer.reshape((l_per_dev,) + (1,) * (n.ndim - 1))
            return jnp.where(m, n, o)

        new_opt = jax.tree.map(keep_trainers, new_opt, opt_state)
        return new_p, new_opt

    if gated:
        return core

    def phase(params, opt_state, new_opt, delta, trainer_idx, mask_key, round_idx):
        # Non-gated callers: nobody drops between masking and aggregation,
        # so masked == gated and no residual exists.
        return core(
            params, opt_state, new_opt, delta, trainer_idx, trainer_idx,
            mask_key, round_idx,
        )

    return phase


def _chunked_sync_body(cfg, attack, model, opt, l_per_dev, pair_seeds=None):
    """Role-based round streaming the PEER-STACK axis through fixed-size
    chunks, with the masked-sum aggregation FUSED into the chunk loop.

    The general body transiently materializes every local peer's diverged
    params and delta — O(peers_per_device x model) HBM. At 1024 vmapped
    peers x ViT-Tiny that is ~22 GB and does not fit one chip. Here a
    ``lax.scan`` trains ``cfg.peer_chunk`` peers at a time and folds each
    chunk's trainer-gated (and, for secure_fedavg, masked) delta sum into a
    single model-sized accumulator, so peak transient memory is
    O(peer_chunk x model) regardless of the peer count — the peer-axis
    analogue of gradient accumulation, and the same streaming idea as the
    blockwise robust reducers (SURVEY §7 hard part (b)).

    Only the mean family (fedavg / secure_fedavg) can fuse its aggregation
    into a running sum; plain SGD only (no per-peer optimizer state to
    advance), both enforced by Config validation. Results equal the
    unchunked general body exactly for deterministic attacks and (by
    per-global-peer-id draw keys) the "noise" attack (test-asserted).

    The adaptive collusions (ALIE, IPM) stream too: their envelopes
    (``mean_h - z * std_h`` / ``-eps * mean_h``) need the honest
    population's moments, which no single chunk sees — but every attacker
    submits the SAME envelope value, and the mean family only consumes the
    trainer-gated SUM. So the scan accumulates honest raw moments
    (``sum x``, plus ``sum x^2`` for ALIE, honest count) alongside the
    fold, zeroes Byzantine trainers' contributions inside it, and adds
    ``n_byz_trainers x envelope`` once after the cross-device psum — one
    training pass, O(model) extra transient, exact up to the raw-vs-centered
    variance rounding (test-asserted vs the unchunked body).
    """
    local_train = make_local_train(cfg, model, opt)
    seeds_const = jnp.asarray(pair_seeds) if pair_seeds is not None else None
    chunk = cfg.peer_chunk
    if l_per_dev % chunk != 0:
        raise ValueError(
            f"peer_chunk ({chunk}) must divide peers-per-device ({l_per_dev})"
        )
    adaptive = attack in ("alie", "ipm")
    alie = attack == "alie"
    n_chunks = l_per_dev // chunk
    # SCAFFOLD constants (option II): same derivation as the general body.
    inv_klr = 1.0 / (cfg.local_epochs * cfg.batches_per_epoch * cfg.lr)
    n_total = float(cfg.num_peers)
    if adaptive and (cfg.compress != "none" or cfg.scaffold or cfg.fednova):
        # The adaptive envelope lands ONCE post-scan, but compression's
        # residual / scaffold's c_i are per-peer state the envelope peers
        # would also have to update — per-attacker bookkeeping the
        # streamed fold deliberately avoids. The unchunked general body
        # handles these combinations (the attack runs in-band there).
        raise ValueError(
            f"peer_chunk with attack={attack!r} does not compose with "
            f"compression/scaffold/fednova (adaptive envelopes land post-scan; "
            f"use the unchunked body for this combination)"
        )

    def _stream_body(params, opt_state, rng, x, y, trainer_idx, byz_gate, round_idx, mask_key, err=None, sc_c=None, sc_ci=None):
        dev = lax.axis_index(PEER_AXIS)
        local_ids = dev * l_per_dev + jnp.arange(l_per_dev)
        round_keys = jax.vmap(lambda k: jax.random.fold_in(k, round_idx))(rng)
        pvaried = jax.lax.pcast(params, PEER_AXIS, to="varying")
        is_trainer_all = jnp.isin(local_ids, trainer_idx)
        if cfg.dp_clip > 0.0:
            # FIXED DP denominator (same rationale as the general body:
            # a data-dependent count would double the certified spend).
            count = jnp.float32(cfg.trainers_per_round)
        else:
            count = jnp.maximum(
                lax.psum(jnp.sum(is_trainer_all.astype(jnp.float32)), PEER_AXIS), 1.0
            )

        def to_chunks(leaf):
            return leaf.reshape((n_chunks, chunk) + leaf.shape[1:])

        # Per-peer state families stream WITH the data: residual / c_i
        # chunks enter each scan step and the refreshed slices come back
        # as stacked scan outputs (reshaped to [L, ...] below).
        extras_in = ()
        if cfg.compress == "topk":
            extras_in = (jax.tree.map(to_chunks, err),)
        elif cfg.scaffold:
            extras_in = (jax.tree.map(to_chunks, sc_ci),)
        tau_all = _epoch_counts(cfg, local_ids, round_idx)
        tau_eff = None
        if cfg.fednova:
            tau_eff = _fednova_tau_eff(
                is_trainer_all, _local_steps(cfg, local_ids, round_idx)
            )
        chunked = jax.tree.map(
            to_chunks, (opt_state, round_keys, x, y, local_ids, byz_gate[local_ids])
        ) + ((to_chunks(tau_all),) if tau_all is not None else ()) + extras_in

        def chunk_step(carry, inputs):
            acc, moments, dci_acc = carry
            opt_c, keys_c, x_c, y_c, ids_c, gate_c, *rest, cidx = inputs
            if tau_all is not None:
                tau_c, *extras_c = rest
            else:
                tau_c, extras_c = None, rest
            y_c = poison_labels(attack, y_c, gate_c, _num_classes(cfg))
            tau_ax = 0 if tau_c is not None else None
            if cfg.scaffold:
                (ci_c,) = extras_c
                bias_c = jax.tree.map(lambda c, ci: c[None] - ci, sc_c, ci_c)
                new_params, _, losses = jax.vmap(
                    local_train, in_axes=(None, 0, 0, 0, 0, 0, tau_ax)
                )(pvaried, opt_c, keys_c, x_c, y_c, bias_c, tau_c)
            else:
                new_params, _, losses = jax.vmap(
                    local_train, in_axes=(None, 0, 0, 0, 0, None, tau_ax)
                )(pvaried, opt_c, keys_c, x_c, y_c, None, tau_c)
            delta = jax.tree.map(lambda n, p: n - p[None], new_params, pvaried)
            is_trainer = jnp.isin(ids_c, trainer_idx)
            if adaptive:
                # Stream the honest raw moments; zero Byzantine trainers'
                # own contributions (their envelope lands post-psum). IPM
                # needs the mean only — no second-moment tree.
                s1, s2, n_h, n_bt = moments
                honest = (1.0 - gate_c).astype(jnp.float32)

                def h_of(l):
                    return honest.reshape((chunk,) + (1,) * (l.ndim - 1)).astype(l.dtype)

                s1 = jax.tree.map(
                    lambda a, l: a + jnp.sum(l * h_of(l), axis=0), s1, delta
                )
                if alie:
                    s2 = jax.tree.map(
                        lambda a, l: a + jnp.sum(l * l * h_of(l), axis=0), s2, delta
                    )
                moments = (
                    s1, s2,
                    n_h + jnp.sum(honest),
                    n_bt + jnp.sum(gate_c * is_trainer.astype(gate_c.dtype)),
                )
                delta = jax.tree.map(lambda l: l * h_of(l), delta)
            else:
                delta = apply_attack(
                    attack, delta, gate_c, mask_key, peer_ids=ids_c
                )

            def keep_trainers_c(n, o):
                m = is_trainer.reshape((chunk,) + (1,) * (n.ndim - 1))
                return jnp.where(m, n, o)

            ys_extra = ()
            if cfg.compress == "topk":
                # EF top-k per peer inside the chunk (post-attack, the
                # general body's order); only trainers refresh their
                # residual slice, and the SPARSIFIED delta is what folds.
                from p2pdl_tpu.ops.compression import topk_ef

                (err_c,) = extras_c
                sent, new_err_c = topk_ef(delta, err_c, cfg.compress_ratio)
                new_err_c = jax.tree.map(keep_trainers_c, new_err_c, err_c)
                delta = sent
                ys_extra = (new_err_c,)
            elif cfg.scaffold:
                # Option-II c_i refresh from the POST-attack delta, same
                # as the general body; the server-c numerator accumulates
                # across chunks and lands after the scan.
                gate_f = is_trainer.astype(jnp.float32)

                def dci_of(c, d):
                    return -c[None] - d.astype(jnp.float32) * inv_klr

                dci = jax.tree.map(dci_of, sc_c, delta)
                new_ci_c = jax.tree.map(
                    lambda ci, dc: ci
                    + gate_f.reshape((chunk,) + (1,) * (dc.ndim - 1)) * dc,
                    ci_c, dci,
                )
                dci_acc = jax.tree.map(
                    lambda a, dc: a
                    + jnp.sum(
                        gate_f.reshape((chunk,) + (1,) * (dc.ndim - 1)) * dc,
                        axis=0,
                    ),
                    dci_acc, dci,
                )
                ys_extra = (new_ci_c,)
            elif cfg.compress == "qsgd":
                # Stateless unbiased quantization per chunk; draws keyed on
                # the chunk's GLOBAL peer ids, so chunked == general.
                from p2pdl_tpu.ops.compression import qsgd

                delta = qsgd(
                    delta, cfg.qsgd_levels,
                    jax.random.fold_in(mask_key, 0x7173), ids_c,
                )
            if cfg.fednova:
                # Step-normalization AFTER the compressor, matching the
                # general path (compress in-body, fednova in the agg
                # phase) so chunked == general exactly. a_i comes from the
                # tau chunk already streaming through the scan (or the
                # static homogeneous count).
                if tau_c is not None:
                    a_c = (tau_c * cfg.batches_per_epoch).astype(jnp.float32)
                else:
                    a_c = jnp.full(
                        (chunk,),
                        cfg.local_epochs * cfg.batches_per_epoch,
                        jnp.float32,
                    )
                delta = _fednova_normalize(delta, a_c, chunk)
            if cfg.dp_clip > 0.0:
                # Per-peer L2 clip INSIDE the chunk — same order as the
                # general body (post-attack, pre-masking), so chunked DP
                # rounds equal unchunked ones bit-for-bit. Adaptive
                # envelopes are clipped once post-scan (below).
                sq = sum(
                    jnp.sum(d.astype(jnp.float32).reshape(chunk, -1) ** 2, axis=1)
                    for d in jax.tree.leaves(delta)
                )
                scale = _dp_clip_scale(cfg, sq)  # [chunk]
                delta = jax.tree.map(
                    lambda d: (
                        d.astype(jnp.float32)
                        * scale.reshape((chunk,) + (1,) * (d.ndim - 1))
                    ).astype(d.dtype),
                    delta,
                )
            if cfg.aggregator == "secure_fedavg":
                delta = jax.vmap(
                    lambda d, pid, it: apply_masks(
                        d, mask_key, pid, trainer_idx, it,
                        neighbors=cfg.secure_agg_neighbors,
                        pair_seeds=seeds_const, round_idx=round_idx,
                    )
                )(delta, ids_c, is_trainer)

            def fold(a, d):
                w = is_trainer.astype(d.dtype).reshape(
                    (chunk,) + (1,) * (d.ndim - 1)
                )
                return a + jnp.sum(d * w, axis=0)

            return (jax.tree.map(fold, acc, delta), moments, dci_acc), (
                losses, *ys_extra
            )

        acc0 = jax.tree.map(jnp.zeros_like, pvaried)
        # Moment accumulators only exist under the adaptive attacks —
        # otherwise the scan carry would haul dead model-sized trees
        # through every chunk (IPM carries the first moment only).
        # Scalar accumulators must start peer-VARYING (they sum the
        # peer-varying gate), or the scan carry types mismatch.
        zvar = lambda: jax.lax.pcast(jnp.float32(0.0), PEER_AXIS, to="varying")  # noqa: E731
        mom0 = (
            (
                jax.tree.map(jnp.zeros_like, pvaried),
                jax.tree.map(jnp.zeros_like, pvaried) if alie else (),
                zvar(),
                zvar(),
            )
            if adaptive
            else ()
        )
        # Derived from pvaried (not fresh zeros) so the carry inherits the
        # peer-varying vma type the accumulated dci has.
        dci0 = (
            jax.tree.map(lambda p: p.astype(jnp.float32) * 0.0, pvaried)
            if cfg.scaffold
            else ()
        )
        (acc, moments, dci_acc), ys = lax.scan(
            chunk_step, (acc0, mom0, dci0), chunked + (jnp.arange(n_chunks),)
        )
        losses = ys[0]

        def unstack(t):  # [n_chunks, chunk, ...] -> [L, ...]
            return jax.tree.map(
                lambda l: l.reshape((l_per_dev,) + l.shape[2:]), t
            )
        if adaptive:
            from p2pdl_tpu.ops.attacks import ALIE_Z, IPM_EPS

            s1, s2, n_h, n_bt = lax.psum(moments, PEER_AXIS)
            n_h = jnp.maximum(n_h, 1.0)

            if alie:
                def bad_of(m1, m2):
                    mean = m1 / n_h.astype(m1.dtype)
                    var = jnp.maximum(m2 / n_h.astype(m2.dtype) - mean * mean, 0.0)
                    return mean - jnp.asarray(ALIE_Z, mean.dtype) * jnp.sqrt(var)

                bad = jax.tree.map(bad_of, s1, s2)
            else:
                bad = jax.tree.map(
                    lambda m1: -jnp.asarray(IPM_EPS, m1.dtype)
                    * (m1 / n_h.astype(m1.dtype)),
                    s1,
                )
            if cfg.dp_clip > 0.0:
                # Every adaptive attacker ships the SAME envelope vector;
                # the general body clips each copy with the identical
                # scale, so clipping the envelope once and adding n_bt
                # copies is exact.
                bsq = sum(
                    jnp.sum(b.astype(jnp.float32) ** 2)
                    for b in jax.tree.leaves(bad)
                )
                bscale = _dp_clip_scale(cfg, bsq)
                bad = jax.tree.map(
                    lambda b: (b.astype(jnp.float32) * bscale).astype(b.dtype), bad
                )
            acc = jax.tree.map(
                lambda a, b: lax.psum(a, PEER_AXIS) + n_bt.astype(a.dtype) * b,
                acc, bad,
            )
            agg = jax.tree.map(lambda a: a / count.astype(a.dtype), acc)
        else:
            agg = jax.tree.map(
                lambda a: lax.psum(a, PEER_AXIS) / count.astype(a.dtype), acc
            )
        if tau_eff is not None:
            agg = _fednova_rescale(agg, tau_eff)
        if cfg.dp_noise_multiplier > 0.0:
            agg = _dp_noise_tree(cfg, agg, mask_key)
        new_p = jax.tree.map(
            lambda p, a: p + cfg.server_lr * a.astype(p.dtype), params, agg
        )
        # Plain SGD only (config-enforced): optimizer state is empty, so
        # "advance trainers' state" is the identity and it passes through.
        if cfg.compress == "topk":
            return new_p, opt_state, losses.reshape(l_per_dev), unstack(ys[1])
        if cfg.scaffold:
            # Server c from the streamed numerator — identical math to the
            # general body's per-leaf update (count is the live trainer
            # count; scaffold excludes DP's fixed denominator by config).
            mean_dci = jax.tree.map(
                lambda a: lax.psum(a, PEER_AXIS) / count, dci_acc
            )
            new_c = jax.tree.map(
                lambda c, m: c + (count / n_total) * m, sc_c, mean_dci
            )
            return new_p, opt_state, losses.reshape(l_per_dev), new_c, unstack(ys[1])
        return new_p, opt_state, losses.reshape(l_per_dev)

    # Wrappers matching the general body's per-family signatures (what the
    # shard_map specs in the builders are laid out for).
    if cfg.compress == "topk":
        def body(params, opt_state, err, rng, x, y, trainer_idx, byz_gate, round_idx, mask_key):
            return _stream_body(
                params, opt_state, rng, x, y, trainer_idx, byz_gate,
                round_idx, mask_key, err=err,
            )
    elif cfg.scaffold:
        def body(params, opt_state, sc_c, sc_ci, rng, x, y, trainer_idx, byz_gate, round_idx, mask_key):
            return _stream_body(
                params, opt_state, rng, x, y, trainer_idx, byz_gate,
                round_idx, mask_key, sc_c=sc_c, sc_ci=sc_ci,
            )
    else:
        def body(params, opt_state, rng, x, y, trainer_idx, byz_gate, round_idx, mask_key):
            return _stream_body(
                params, opt_state, rng, x, y, trainer_idx, byz_gate,
                round_idx, mask_key,
            )

    return body


def _general_sync_body(
    cfg, attack, model, opt, l_per_dev, seq_axis=None, ep_axis=None,
    pair_seeds=None, mp_axis=None, mp_sharded=None,
):
    """Role-based round over single-copy global params: broadcast the global
    model into a vmapped local-SGD phase (peers diverge only transiently),
    aggregate trainer deltas, apply one deterministic server update. One
    fused program = the two phase fragments composed with no host boundary.

    ``mp_axis``/``mp_sharded``: the model-parallel mesh axis + per-leaf
    split-or-replicated bool tree, consumed by the cross-shard DP clip
    norm/noise and the distributed top-k compression threshold."""
    train = _local_train_phase(
        cfg, attack, model, opt, l_per_dev,
        seq_axis=seq_axis, ep_axis=ep_axis, with_bias=cfg.scaffold,
    )
    agg = _aggregate_phase(
        cfg, l_per_dev, pair_seeds=pair_seeds,
        dp_axis=mp_axis if cfg.dp_clip > 0.0 else None, dp_sharded=mp_sharded,
    )

    if cfg.compress == "topk":
        # EF top-k sparsification (ops/compression.py). Per round:
        #   v_i = delta_i + err_i; ship top-k(v_i); err_i' = v_i - sent_i.
        # Only TRAINERS consume and refresh their residual (non-trainers'
        # deltas are discarded whole, so their unsent mass must not
        # accumulate); the attack epilogue ran inside the train phase, so
        # an attacker ships the sparsified form of its corrupted update.
        # Under tp/ep/pp the per-peer threshold is the DISTRIBUTED k-th
        # magnitude (bit-bisection + count psums, ops/compression
        # kth_magnitude_sharded) — each shard then selects/ships/updates
        # its residual locally.
        from p2pdl_tpu.ops.compression import topk_ef, topk_ef_sharded

        n_mp_shards = max(cfg.tp_shards, cfg.ep_shards, cfg.pp_shards)

        def body(params, opt_state, err, rng, x, y, trainer_idx, byz_gate, round_idx, mask_key):
            dev = lax.axis_index(PEER_AXIS)
            local_ids = dev * l_per_dev + jnp.arange(l_per_dev)
            is_trainer = jnp.isin(local_ids, trainer_idx)
            delta, new_opt, losses = train(
                params, opt_state, rng, x, y, byz_gate, round_idx, mask_key
            )
            # topk_ef ships each leaf in the delta dtype and computes the
            # residual against the cast value, so the quantization error of
            # a low-precision param_dtype stays inside the EF telescoping.
            if mp_axis is not None:
                sent, new_err = topk_ef_sharded(
                    delta, err, cfg.compress_ratio, mp_axis, mp_sharded,
                    n_mp_shards,
                )
            else:
                sent, new_err = topk_ef(delta, err, cfg.compress_ratio)

            def keep_trainers(n, o):
                m = is_trainer.reshape((l_per_dev,) + (1,) * (n.ndim - 1))
                return jnp.where(m, n, o)

            new_err = jax.tree.map(keep_trainers, new_err, err)
            new_p, kept_opt = agg(
                params, opt_state, new_opt, sent, trainer_idx, mask_key, round_idx
            )
            return new_p, kept_opt, losses, new_err

        return body

    if cfg.scaffold:
        # SCAFFOLD (Karimireddy et al. 2020, option II). Per round:
        #   local steps:  w <- w - lr*(g + c - c_i)   (grad bias, constant)
        #   trainers:     c_i <- c_i - c - delta_i / (K*lr)
        #   server:       c   <- c + (T_live/N) * mean_trainers(c_i' - c_i)
        # The c_i update uses the POST-attack delta — a Byzantine peer
        # corrupts its control history exactly as it corrupts its update.
        k_steps = cfg.local_epochs * cfg.batches_per_epoch
        inv_klr = 1.0 / (k_steps * cfg.lr)
        n_total = float(cfg.num_peers)

        def body(params, opt_state, sc_c, sc_ci, rng, x, y, trainer_idx, byz_gate, round_idx, mask_key):
            dev = lax.axis_index(PEER_AXIS)
            local_ids = dev * l_per_dev + jnp.arange(l_per_dev)
            is_trainer = jnp.isin(local_ids, trainer_idx)
            bias = jax.tree.map(lambda c, ci: c[None] - ci, sc_c, sc_ci)
            delta, new_opt, losses = train(
                params, opt_state, rng, x, y, byz_gate, round_idx, mask_key, bias
            )
            new_p, kept_opt = agg(
                params, opt_state, new_opt, delta, trainer_idx, mask_key, round_idx
            )
            count = jnp.maximum(
                lax.psum(jnp.sum(is_trainer.astype(jnp.float32)), PEER_AXIS), 1.0
            )

            def upd(c, ci, d):
                gate = is_trainer.astype(jnp.float32).reshape(
                    (l_per_dev,) + (1,) * (d.ndim - 1)
                )
                dci = -c[None] - d.astype(jnp.float32) * inv_klr  # c_i' - c_i
                new_ci = ci + gate * dci
                mean_dci = lax.psum(jnp.sum(gate * dci, axis=0), PEER_AXIS) / count
                new_c = c + (count / n_total) * mean_dci
                return new_c, new_ci

            flat_c, treedef = jax.tree_util.tree_flatten(sc_c)
            flat_ci = jax.tree.leaves(sc_ci)
            flat_d = jax.tree.leaves(delta)
            outs = [upd(c, ci, d) for c, ci, d in zip(flat_c, flat_ci, flat_d)]
            new_c = jax.tree_util.tree_unflatten(treedef, [o[0] for o in outs])
            new_ci = jax.tree_util.tree_unflatten(treedef, [o[1] for o in outs])
            return new_p, kept_opt, losses, new_c, new_ci

        return body

    def body(params, opt_state, rng, x, y, trainer_idx, byz_gate, round_idx, mask_key):
        delta, new_opt, losses = train(
            params, opt_state, rng, x, y, byz_gate, round_idx, mask_key
        )
        if cfg.compress == "qsgd":
            # Unbiased stochastic quantization, stateless — ships in the
            # plain body (no residual carry). Draws keyed on GLOBAL peer
            # ids (layout-invariant); under tp/ep/pp the per-peer norm
            # psums over the model axis (ops/compression.qsgd).
            from p2pdl_tpu.ops.compression import qsgd

            dev = lax.axis_index(PEER_AXIS)
            local_ids = dev * l_per_dev + jnp.arange(l_per_dev)
            delta = qsgd(
                delta, cfg.qsgd_levels,
                jax.random.fold_in(mask_key, 0x7173),  # "qs"
                local_ids, axis=mp_axis, sharded=mp_sharded,
            )
        new_p, kept_opt = agg(
            params, opt_state, new_opt, delta, trainer_idx, mask_key, round_idx
        )
        return new_p, kept_opt, losses

    return body


def build_per_peer_eval_fn(cfg: Config, mesh: Mesh) -> Callable:
    """Per-peer accuracy of the synchronized global model on each peer's OWN
    local shard: ``(state, x, y) -> [num_peers]`` accuracies.

    This is the reference's per-tester progress metric — each tester
    evaluates on its own partition (reference ``evaluation/evaluation.py:10``,
    collected per round into the HTTP response at ``main.py:86-109``). The
    held-out global eval (``build_eval_fn``) remains the headline metric;
    this one exists for API parity and per-peer observability."""
    model = build_model(cfg)
    forward = make_forward_fn(model, jnp.dtype(cfg.compute_dtype))
    peer_params = params_layout(cfg) == "peer"

    def body(params, x, y):
        # Works for [B, C]/[B] classifiers and [B, T, C]/[B, T] sequence
        # models alike (argmax over the trailing class axis).
        def acc(p, xp, yp):
            logits = forward(p, xp)
            return jnp.mean(jnp.argmax(logits, axis=-1) == yp)

        if peer_params:
            # Gossip: every peer evaluates its OWN model (models genuinely
            # differ across peers between mixes).
            return jax.vmap(acc)(params, x, y)
        pvaried = jax.lax.pcast(params, PEER_AXIS, to="varying")
        return jax.vmap(acc, in_axes=(None, 0, 0))(pvaried, x, y)

    smapped = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(PEER_AXIS) if peer_params else P(), P(PEER_AXIS), P(PEER_AXIS)),
        out_specs=P(PEER_AXIS),
    )

    @jax.jit
    def eval_fn(state: PeerState, x, y):
        return smapped(state.params, x, y)

    return telemetry.traced("dispatch.eval_per_peer", eval_fn)


def build_personalized_eval_fn(
    cfg: Config, mesh: Mesh, finetune_steps: int = 1
) -> Callable:
    """Personalized accuracy: each peer fine-tunes the global model on its
    OWN training shard for ``finetune_steps`` epochs of plain local SGD,
    then evaluates the personalized copy on its own shard —
    ``(state, x, y) -> [num_peers]`` accuracies.

    The canonical personalization baseline of the FL literature (FedAvg +
    local fine-tuning — the protocol Ditto, Li et al. 2021 evaluates
    against): it answers "how good is the global model as a STARTING
    POINT for my data", which on non-IID shards can diverge sharply from
    the global accuracy. Like :func:`build_per_peer_eval_fn` (the
    reference's own-shard protocol, ``evaluation/evaluation.py:10``) the
    score is measured on the peer's own shard — the two functions differ
    exactly by the fine-tuning step, so their difference isolates the
    personalization gain. The fine-tuned copies are transient — the
    experiment's state is untouched. Sync layout only (gossip peers
    already keep personal models)."""
    if params_layout(cfg) != "sync":
        raise ValueError(
            "personalized eval is for the sync layout; gossip peers already "
            "hold personal models (use build_per_peer_eval_fn)"
        )
    if (
        cfg.seq_shards > 1 or cfg.tp_shards > 1
        or cfg.ep_shards > 1 or cfg.pp_shards > 1
    ):
        raise ValueError(
            "personalized eval does not support model/sequence parallelism "
            "(the fine-tune body is data-parallel; the TP bias pre-scale "
            "would corrupt its dense-twin gradients)"
        )
    # The BASELINE fine-tune is plain local SGD from the global model with
    # FRESH (empty) optimizer state: inheriting the experiment's FedProx
    # anchor would pull the personalized copy back toward the global model
    # (understating the gain this metric isolates), and stale Adam/momentum
    # buffers would distort the first steps.
    ft_cfg = cfg.replace(
        local_epochs=finetune_steps,
        fedprox_mu=0.0,
        optimizer="sgd",
        momentum=0.0,
        weight_decay=0.0,
    )
    model = build_model(ft_cfg)
    opt = make_optimizer(ft_cfg)
    local_train = make_local_train(ft_cfg, model, opt)
    forward = make_forward_fn(model, jnp.dtype(cfg.compute_dtype))

    def body(params, rng, x, y):
        params_v = jax.lax.pcast(params, PEER_AXIS, to="varying")

        def one(key, xp, yp):
            p, _, _ = local_train(params_v, opt.init(params_v), key, xp, yp)
            logits = forward(p, xp)
            return jnp.mean(jnp.argmax(logits, axis=-1) == yp)

        if cfg.peer_chunk > 0:
            # The config that needed delta streaming to fit training would
            # OOM on l_per_dev simultaneous fine-tune instances — run the
            # local peers sequentially instead (eval-path latency for
            # round-path memory parity).
            return jax.lax.map(lambda a: one(*a), (rng, x, y))
        return jax.vmap(one)(rng, x, y)

    sp = P(PEER_AXIS)
    smapped = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), sp, sp, sp),
        out_specs=sp,
    )

    @jax.jit
    def eval_fn(state: PeerState, x, y):
        return smapped(state.params, state.rng, x, y)

    return telemetry.traced("dispatch.eval_personalized", eval_fn)


def build_eval_fn(cfg: Config) -> Callable:
    """Held-out evaluation of the synchronized global model.

    Replaces reference ``evaluation/evaluation.py:4-24``, which evaluates on
    each node's *training* shard — here eval runs on data no peer trained on.
    """
    model = build_model(cfg)
    forward = make_forward_fn(model, jnp.dtype(cfg.compute_dtype))

    @jax.jit
    def eval_fn(state: PeerState, eval_x, eval_y):
        logits = forward(global_params(state, cfg), eval_x)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, eval_y).mean()
        acc = jnp.mean(jnp.argmax(logits, axis=-1) == eval_y)
        return {"eval_loss": loss, "eval_acc": acc}

    return telemetry.traced("dispatch.eval", eval_fn)
