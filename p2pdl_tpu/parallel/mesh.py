"""Device-mesh construction for the peer axis.

The TPU-native replacement for the reference's fully-connected TCP mesh over
127.0.0.1 (reference ``main.py:33-36``, ``node/node.py:251-263``): peers map
onto a 1-D ``jax.sharding.Mesh`` axis named ``"peers"``; peers beyond the
device count stack on an in-device vmap axis (two-level layout:
``num_peers = n_devices * peers_per_device``).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PEER_AXIS = "peers"
# Second mesh axis for sequence/context parallelism: with ``seq_shards > 1``
# the device grid is (peers x seq); each peer's token sequence is sharded
# over the seq axis and attention runs as ring attention over ICI.
SEQ_AXIS = "seq"
# Second mesh axis for tensor parallelism: with ``tp_shards > 1`` the grid
# is (peers x tp); attention heads + MLP hidden shard over it (ops/tp.py).
TP_AXIS = "tp"
# Second mesh axis for expert parallelism: with ``ep_shards > 1`` the grid
# is (peers x ep); MoE expert weights shard over it and tokens move by
# ``all_to_all`` (ops/moe.py).
EP_AXIS = "ep"
# Second mesh axis for pipeline parallelism: with ``pp_shards > 1`` the grid
# is (peers x pp); transformer depth shards over it and microbatch
# activations rotate by ``ppermute`` (ops/pipeline.py).
PP_AXIS = "pp"


def make_mesh(
    n_devices: int | None = None,
    devices=None,
    seq_shards: int = 1,
    tp_shards: int = 1,
    ep_shards: int = 1,
    pp_shards: int = 1,
) -> Mesh:
    """A mesh named ``("peers",)`` — or 2-D ``("peers", <axis>)`` when one of
    sequence / tensor / expert / pipeline parallelism splits the
    ``n_devices`` grid (``n_peer_devices = n_devices // shards``)."""
    requested = [
        (shards, axis)
        for shards, axis in (
            (seq_shards, SEQ_AXIS),
            (tp_shards, TP_AXIS),
            (ep_shards, EP_AXIS),
            (pp_shards, PP_AXIS),
        )
        if shards > 1
    ]
    if len(requested) > 1:
        names = ", ".join(axis for _, axis in requested)
        raise ValueError(
            f"model-parallel axes are currently exclusive (one second mesh "
            f"axis at a time); requested {names}"
        )
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(f"requested {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    devices = np.asarray(devices)
    if not requested:
        return Mesh(devices, (PEER_AXIS,))
    shards, axis = requested[0]
    if devices.size % shards != 0:
        raise ValueError(
            f"{axis}_shards ({shards}) must divide the device count ({devices.size})"
        )
    return Mesh(devices.reshape(-1, shards), (PEER_AXIS, axis))


def peer_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for peer-stacked arrays: leading dim split over the peer axis."""
    return NamedSharding(mesh, PartitionSpec(PEER_AXIS))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for peer-stacked INPUT arrays ``[P, S, ...]``. On a 2-D
    (peers x seq) mesh the third dimension — image height for ViT — is
    additionally split over the seq axis (the 4x4 patch stem is
    stride-aligned, so each shard patchifies its row block locally)."""
    if SEQ_AXIS in mesh.shape:
        return NamedSharding(mesh, PartitionSpec(PEER_AXIS, None, SEQ_AXIS))
    return peer_sharding(mesh)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def peer_devices(mesh: Mesh) -> int:
    """Number of devices along the peer axis (the full mesh when 1-D)."""
    return mesh.shape[PEER_AXIS]


def peers_per_device(num_peers: int, mesh: Mesh) -> int:
    n_dev = peer_devices(mesh)
    if num_peers % n_dev != 0:
        raise ValueError(
            f"num_peers ({num_peers}) must be divisible by the peer-axis size "
            f"({n_dev}); round num_peers up to a multiple"
        )
    return num_peers // n_dev
