"""Device-mesh construction for the peer axis.

The TPU-native replacement for the reference's fully-connected TCP mesh over
127.0.0.1 (reference ``main.py:33-36``, ``node/node.py:251-263``): peers map
onto a 1-D ``jax.sharding.Mesh`` axis named ``"peers"``; peers beyond the
device count stack on an in-device vmap axis (two-level layout:
``num_peers = n_devices * peers_per_device``).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PEER_AXIS = "peers"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over ``n_devices`` (default: all) named ``"peers"``."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(f"requested {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (PEER_AXIS,))


def peer_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for peer-stacked arrays: leading dim split over the peer axis."""
    return NamedSharding(mesh, PartitionSpec(PEER_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def peers_per_device(num_peers: int, mesh: Mesh) -> int:
    n_dev = mesh.devices.size
    if num_peers % n_dev != 0:
        raise ValueError(
            f"num_peers ({num_peers}) must be divisible by mesh size ({n_dev}); "
            f"round num_peers up to a multiple"
        )
    return num_peers // n_dev
